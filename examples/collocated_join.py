"""Collocated join: two tables partitioned on the join key share bucket
placement, so joins need no exchange (ref example:
examples/.../CollocatedJoinExample.scala).

Run: PYTHONPATH=. python examples/collocated_join.py
"""

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def main():
    s = SnappySession(catalog=Catalog())
    s.sql("""CREATE TABLE orders (o_orderkey BIGINT, o_custkey BIGINT,
        o_total DOUBLE) USING column OPTIONS (partition_by 'o_orderkey')""")
    s.sql("""CREATE TABLE lineitems (l_orderkey BIGINT, l_qty INT,
        l_price DOUBLE) USING column
        OPTIONS (partition_by 'l_orderkey', colocate_with 'orders')""")

    n_o, n_l = 10_000, 40_000
    rng = np.random.default_rng(1)
    s.insert_arrays("orders", [
        np.arange(n_o, dtype=np.int64),
        rng.integers(0, 1000, n_o).astype(np.int64),
        np.round(rng.uniform(10, 1000, n_o), 2)])
    s.insert_arrays("lineitems", [
        rng.integers(0, n_o, n_l).astype(np.int64),
        rng.integers(1, 10, n_l).astype(np.int32),
        np.round(rng.uniform(1, 100, n_l), 2)])

    out = s.sql("""
        SELECT o.o_custkey, count(*) AS items, sum(l.l_price * l.l_qty)
        FROM lineitems l JOIN orders o ON l.l_orderkey = o.o_orderkey
        GROUP BY o.o_custkey ORDER BY 3 DESC LIMIT 5""")
    print(out.to_pandas())


if __name__ == "__main__":
    main()
