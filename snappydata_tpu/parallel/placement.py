"""Shard placement: bucket → device assignment for the data mesh.

The in-process analogue of the cluster layer's bucket→member map
(parallel/buckets.BucketMap + the PR 8 rejoin/watermark machinery in
cluster/distributed.py): a mesh-sharded table's batch axis divides into
`num_buckets` logical buckets (contiguous batch runs — batch ≈ bucket is
the storage layer's own contract), and every bucket is owned by exactly
one mesh device.  The placement is what makes a mesh RESIZE a bucket
*rebalance* instead of a world invalidation: when a device is lost
(`rebalance(new_devices)`) the surviving devices take over its buckets
and the device caches MIGRATE device-to-device (storage/device.
migrate_mesh_cache) instead of rebuilding from host; a rejoin hands the
buckets back the same way (ref: GemFire bucket rebalance +
PartitionedRegion redundancy recovery — the PR 8 `rejoin_server`
watermark resync is the multi-process twin of this object).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from snappydata_tpu.utils import locks

_lock = locks.named_lock("parallel.placement")
_next_generation = [0]


@dataclasses.dataclass(frozen=True)
class ShardPlacement:
    """Immutable bucket→device assignment over `num_devices` devices.

    Buckets are contiguous runs of the (padded) batch axis, so the
    assignment is realizable as a plain `NamedSharding` block split —
    no batch permutation, which keeps the bind path identical with and
    without a placement.  `generation` is process-unique and monotone:
    caches and dashboards use it to tell two placements apart."""

    num_devices: int
    num_buckets: int
    assignment: Tuple[int, ...]     # bucket -> device
    generation: int
    moved_from_previous: int = 0    # buckets that changed device

    @classmethod
    def balanced(cls, num_devices: int,
                 num_buckets: int = 0) -> "ShardPlacement":
        from snappydata_tpu import config

        nb = int(num_buckets or config.global_properties().get(
            "mesh_num_buckets", 32) or 32)
        nb = max(nb, num_devices)
        assign = tuple(b * num_devices // nb for b in range(nb))
        with _lock:
            _next_generation[0] += 1
            gen = _next_generation[0]
        return cls(num_devices, nb, assign, gen)

    def rebalance(self, new_num_devices: int) -> "ShardPlacement":
        """New balanced assignment over `new_num_devices`, tracking how
        many buckets moved (the rebalance cost the dashboard shows).
        Like the reference's rebalance, ownership re-splits evenly; the
        moved set is whatever the new split displaces."""
        nb = self.num_buckets
        new_assign = tuple(b * new_num_devices // nb for b in range(nb))
        moved = sum(1 for a, b in zip(self.assignment, new_assign)
                    if a != b)
        with _lock:
            _next_generation[0] += 1
            gen = _next_generation[0]
        return ShardPlacement(new_num_devices, nb, new_assign, gen,
                              moved_from_previous=moved)

    def device_of_bucket(self, bucket: int) -> int:
        return self.assignment[bucket % self.num_buckets]

    def bucket_of_batch(self, batch: int, num_batches: int) -> int:
        """Bucket of one (padded) batch slot: contiguous equal blocks."""
        n = max(1, num_batches)
        return min(self.num_buckets - 1,
                   batch * self.num_buckets // n)

    def buckets_of_device(self, device: int) -> List[int]:
        return [b for b, d in enumerate(self.assignment) if d == device]

    def bucket_map(self) -> Dict[int, int]:
        """bucket -> device, for /status/api/v1/mesh."""
        return {b: d for b, d in enumerate(self.assignment)}
