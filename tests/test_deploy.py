"""DEPLOY JAR/PACKAGE — cluster artifact deploy surface.

Reference: DeployCommand / UnDeployCommand / ListPackageJarsCommand
(core/.../execution/ddl.scala; grammar SnappyDDLParser.deployPackages:858).
The reference resolves maven jars onto every member's classloader; here
artifacts are Python wheels/zips/modules added to the interpreter path,
copied into the disk store, and re-installed by catalog recovery.
"""

import os
import sys
import zipfile

import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def _write_module(tmp_path, name="depmod", value=41):
    p = tmp_path / f"{name}.py"
    p.write_text(f"MAGIC = {value}\n\ndef answer():\n    return MAGIC + 1\n")
    return str(p)


def _drop_modules(*names):
    for n in names:
        sys.modules.pop(n, None)


@pytest.fixture(autouse=True)
def _clean_modules():
    before = list(sys.path)
    yield
    sys.path[:] = before  # deploys are process-wide; isolate tests
    _drop_modules("depmod", "zipmod", "othermod")


def test_deploy_module_and_exec(tmp_path):
    s = SnappySession(catalog=Catalog())
    path = _write_module(tmp_path)
    s.sql(f"DEPLOY JAR depjar '{path}'")
    r = s.sql("EXEC PYTHON 'import depmod; result = [depmod.answer()]'")
    assert r.rows()[0][0] == 42

    rows = s.sql("LIST JARS").rows()
    assert [r[0] for r in rows] == ["depjar"]
    assert rows[0][2] is False or rows[0][2] == False  # noqa: E712
    assert s.sql("LIST PACKAGES").num_rows == 0


def test_deploy_zip_package(tmp_path):
    s = SnappySession(catalog=Catalog())
    zpath = str(tmp_path / "zippkg.zip")
    with zipfile.ZipFile(zpath, "w") as z:
        z.writestr("zipmod.py", "VALUE = 'from-zip'\n")
    s.sql(f"DEPLOY PACKAGE zpkg '{zpath}'")
    r = s.sql("EXEC PYTHON 'import zipmod; result = [zipmod.VALUE]'")
    assert r.rows()[0][0] == "from-zip"
    rows = s.sql("LIST PACKAGES").rows()
    assert [x[0] for x in rows] == ["zpkg"]
    assert bool(rows[0][2]) is True


def test_undeploy_removes_path(tmp_path):
    s = SnappySession(catalog=Catalog())
    path = _write_module(tmp_path)
    s.sql(f"DEPLOY JAR depjar '{path}'")
    root = os.path.dirname(path)
    assert root in sys.path
    s.sql("UNDEPLOY depjar")
    assert root not in sys.path
    assert s.sql("LIST JARS").num_rows == 0
    with pytest.raises(ValueError, match="nothing deployed"):
        s.sql("UNDEPLOY depjar")


def test_deploy_missing_artifact_is_loud():
    s = SnappySession(catalog=Catalog())
    with pytest.raises(ValueError, match="not found"):
        s.sql("DEPLOY JAR nope '/no/such/file.whl'")
    # maven-style coordinates get the no-egress hint
    with pytest.raises(ValueError, match="egress"):
        s.sql("DEPLOY PACKAGE gavfmt 'com.example:artifact:1.0'")


def test_deploy_requires_admin(tmp_path):
    s = SnappySession(catalog=Catalog())
    path = _write_module(tmp_path)
    s.sql("CREATE TABLE t (x INT) USING column")
    user = s.for_user("alice")
    with pytest.raises(PermissionError):
        user.sql(f"DEPLOY JAR depjar '{path}'")


def test_deploy_persists_across_recovery(tmp_path):
    data = str(tmp_path / "store")
    src = _write_module(tmp_path, value=7)
    s = SnappySession(data_dir=data)
    s.sql(f"DEPLOY JAR persisted '{src}'")
    # artifact is copied INTO the store: the original may vanish
    os.remove(src)
    s.checkpoint()

    _drop_modules("depmod")
    root = os.path.dirname(src)
    while root in sys.path:
        sys.path.remove(root)

    s2 = SnappySession(data_dir=data)
    r = s2.sql("EXEC PYTHON 'import depmod; result = [depmod.answer()]'")
    assert r.rows()[0][0] == 8
    assert [x[0] for x in s2.sql("LIST JARS").rows()] == ["persisted"]

    # undeploy persists too
    s2.sql("UNDEPLOY persisted")
    s3 = SnappySession(data_dir=data)
    assert s3.sql("LIST JARS").num_rows == 0


def test_redeploy_replaces(tmp_path):
    s = SnappySession(catalog=Catalog())
    p1 = _write_module(tmp_path, value=1)
    s.sql(f"DEPLOY JAR depjar '{p1}'")
    sub = tmp_path / "v2"
    sub.mkdir()
    p2 = _write_module(sub, value=100)
    s.sql(f"DEPLOY JAR depjar '{p2}'")
    _drop_modules("depmod")
    r = s.sql("EXEC PYTHON 'import depmod; result = [depmod.answer()]'")
    assert r.rows()[0][0] == 101
    assert s.sql("LIST JARS").num_rows == 1


def test_deploy_same_basename_no_overwrite(tmp_path):
    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir(), b.mkdir()
    (a / "util.py").write_text("WHO = 'a'\n")
    (b / "util.py").write_text("WHO = 'b'\n")
    data = str(tmp_path / "store")
    s = SnappySession(catalog=Catalog(), data_dir=data)
    s.sql(f"DEPLOY JAR both '{a / 'util.py'}, {b / 'util.py'}'")
    import os as _os
    stored = s._deployed()["both"]["files"]
    assert len(stored) == len(set(stored)) == 2
    assert all(_os.path.exists(f) for f in stored)
