"""Test fixture: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's tier-1 strategy (SnappyFunSuite boots a real
embedded engine in one JVM — no mocks; core/src/test/scala/io/snappydata/
SnappyFunSuite.scala:51-88): tests run the real engine in-process, with
multi-"chip" behavior exercised via XLA host devices instead of real TPUs.

Note: this machine's TPU bootstrap (sitecustomize) force-selects the
`axon` platform at interpreter start, overriding JAX_PLATFORMS env — so we
override the *config* after import, before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
assert jax.default_backend() == "cpu", jax.default_backend()

import pytest  # noqa: E402


@pytest.fixture()
def session():
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog())
    yield s
    s.stop()
