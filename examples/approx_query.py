"""Approximate query processing over a stratified sample (ref example:
examples/.../SynopsisDataExample.scala; docs/aqp.md).

Run: PYTHONPATH=. python examples/approx_query.py
"""

import time

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def main():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE taxi (borough STRING, fare DOUBLE) USING column")
    rng = np.random.default_rng(7)
    n = 2_000_000
    boroughs = np.array(["manhattan", "brooklyn", "queens", "bronx",
                         "staten"], dtype=object)
    probs = np.array([0.6, 0.2, 0.15, 0.045, 0.005])
    s.insert_arrays("taxi", [
        boroughs[rng.choice(5, n, p=probs)],
        np.round(rng.gamma(2.0, 8.0, n), 2)])

    # stratified sample keyed on the query column set
    s.sql("CREATE SAMPLE TABLE taxi_sample ON taxi "
          "OPTIONS (qcs 'borough', reservoir_size '500')")

    t0 = time.time()
    exact = s.sql("SELECT borough, count(*), avg(fare) FROM taxi "
                  "GROUP BY borough ORDER BY borough")
    t_exact = time.time() - t0
    t0 = time.time()
    approx = s.approx_sql("SELECT borough, count(*), avg(fare) FROM taxi "
                          "GROUP BY borough ORDER BY borough")
    t_approx = time.time() - t0

    print(f"exact   ({t_exact * 1000:.0f}ms):")
    print(exact.to_pandas())
    print(f"approx  ({t_approx * 1000:.0f}ms):")
    print(approx.to_pandas())

    s.create_topk("hot_boroughs", "taxi", "borough", k=3)
    print("TopK:", s.query_topk("hot_boroughs").rows())


if __name__ == "__main__":
    main()
