"""Distributed scatter-gather over real server members: partitioned
ingest routing, partial aggregation + merge, collocated joins, replicated
dims (ref: partitioned regions + partial agg + CollectAggregateExec +
CollapseCollocatedPlans, exercised over Arrow Flight)."""

import numpy as np
import pandas as pd
import pytest

pytestmark = pytest.mark.slow  # heavy/XLA-compile-bound; deselect with -m 'not slow'

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.distributed import (DistributedError,
                                                DistributedSession)


@pytest.fixture(scope="module")
def dist():
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address, SnappySession(catalog=Catalog()))
               .start() for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    yield ds, servers
    ds.close()
    for s in servers:
        s.stop()
    locator.stop()


@pytest.fixture(scope="module")
def loaded(dist):
    ds, servers = dist
    ds.sql("CREATE TABLE tx (k BIGINT, region STRING, amt DOUBLE) "
           "USING column OPTIONS (partition_by 'k')")
    ds.sql("CREATE TABLE dim (code STRING, label STRING) USING column")
    rng = np.random.default_rng(11)
    n = 30_000
    k = rng.integers(0, 5000, n).astype(np.int64)
    region = np.array(["e", "w", "n"], dtype=object)[rng.integers(0, 3, n)]
    amt = np.round(rng.random(n) * 100, 2)
    ds.insert_arrays("tx", [k, region, amt])
    ds.sql("INSERT INTO dim VALUES ('e', 'east'), ('w', 'west'), "
           "('n', 'north')")
    df = pd.DataFrame({"k": k, "region": region, "amt": amt})
    return ds, servers, df


def test_rows_sharded_across_servers(loaded):
    ds, servers, df = loaded
    counts = []
    for s in servers:
        r = s.session.sql("SELECT count(*) FROM tx").rows()[0][0]
        counts.append(r)
    assert sum(counts) == len(df)
    assert all(c > 0 for c in counts)          # every shard participates
    assert max(counts) < len(df)               # no server holds everything


def test_distributed_global_aggregate(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT count(*), sum(amt), avg(amt), min(amt), max(amt) "
               "FROM tx").rows()[0]
    assert r[0] == len(df)
    assert r[1] == pytest.approx(df.amt.sum())
    assert r[2] == pytest.approx(df.amt.mean())
    assert r[3] == pytest.approx(df.amt.min())
    assert r[4] == pytest.approx(df.amt.max())


def test_distributed_group_by_with_filter(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT region, count(*) AS c, sum(amt) AS total FROM tx "
               "WHERE amt > 50 GROUP BY region ORDER BY region")
    sel = df[df.amt > 50]
    exp = sel.groupby("region").agg(c=("amt", "size"), total=("amt", "sum"))
    for row, (reg, e) in zip(r.rows(), exp.sort_index().iterrows()):
        assert row[0] == reg
        assert row[1] == e.c
        assert row[2] == pytest.approx(e.total)


def test_distributed_scan_concat(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT k, amt FROM tx WHERE amt > 99.5")
    exp = df[df.amt > 99.5]
    assert r.num_rows == len(exp)


def test_distributed_replicated_join(loaded):
    ds, _, df = loaded
    r = ds.sql("SELECT d.label, sum(t.amt) AS total FROM tx t "
               "JOIN dim d ON t.region = d.code GROUP BY d.label "
               "ORDER BY d.label")
    exp = df.groupby("region").amt.sum()
    label_of = {"e": "east", "w": "west", "n": "north"}
    got = {row[0]: row[1] for row in r.rows()}
    for reg, total in exp.items():
        assert got[label_of[reg]] == pytest.approx(total)


def test_distributed_update_delete(loaded):
    ds, _, df = loaded
    ds.sql("CREATE TABLE mut (k BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'k')")
    ds.insert_arrays("mut", [np.arange(100, dtype=np.int64),
                             np.ones(100)])
    n = ds.sql("UPDATE mut SET v = 5.0 WHERE k < 10").rows()[0][0]
    assert n == 10
    n = ds.sql("DELETE FROM mut WHERE k >= 90").rows()[0][0]
    assert n == 10
    r = ds.sql("SELECT count(*), sum(v) FROM mut").rows()[0]
    assert r[0] == 90
    assert r[1] == pytest.approx(10 * 5.0 + 80 * 1.0)


def test_collocated_join_allowed_non_collocated_rejected(loaded):
    ds, _, _ = loaded
    ds.sql("CREATE TABLE orders2 (ok BIGINT, cust BIGINT) USING column "
           "OPTIONS (partition_by 'ok')")
    ds.sql("CREATE TABLE items2 (ok BIGINT, price DOUBLE) USING column "
           "OPTIONS (partition_by 'ok', colocate_with 'orders2')")
    ds.insert_arrays("orders2", [np.arange(50, dtype=np.int64),
                                 np.arange(50, dtype=np.int64) % 7])
    ds.insert_arrays("items2", [np.arange(50, dtype=np.int64),
                                np.full(50, 2.0)])
    r = ds.sql("SELECT count(*), sum(i.price) FROM orders2 o "
               "JOIN items2 i ON o.ok = i.ok").rows()[0]
    assert r[0] == 50 and r[1] == pytest.approx(100.0)
    # non-collocated partitioned join: small side broadcasts automatically
    ds.sql("CREATE TABLE other (x BIGINT, tag STRING) USING column "
           "OPTIONS (partition_by 'x')")
    ds.insert_arrays("other", [np.arange(0, 50, 2, dtype=np.int64),
                               np.array(["t"] * 25, dtype=object)])
    r = ds.sql("SELECT count(*) FROM orders2 o JOIN other t ON o.ok = t.x")
    assert r.rows()[0][0] == 25  # broadcast exchange made it complete


def test_broadcast_exchange_group_by(loaded):
    ds, _, df = loaded
    # tx is partitioned by k; make a small partitioned dim on another key
    ds.sql("CREATE TABLE kdim (kk BIGINT, bucket_name STRING) USING column "
           "OPTIONS (partition_by 'kk')")
    kk = np.arange(0, 5000, dtype=np.int64)
    ds.insert_arrays("kdim", [kk, np.array(
        [f"b{k % 3}" for k in kk], dtype=object)])
    r = ds.sql("SELECT d.bucket_name, count(*) FROM tx t JOIN kdim d "
               "ON t.k = d.kk GROUP BY d.bucket_name ORDER BY d.bucket_name")
    exp = df.assign(b=[f"b{k % 3}" for k in df.k]).groupby("b").size()
    assert [(x[0], x[1]) for x in r.rows()] == list(exp.items())


# --------------------------------------------------------------------------
# hash-repartition (shuffle) exchange: both-sides-large, non-collocated
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_nc(dist):
    """TPC-H-shaped schema deliberately NON-collocated: orders partitioned
    by customer key, lineitem by order key, customer by nation key — every
    join needs an exchange (ref: Spark falls back to a shuffle exchange,
    SnappyStrategies.scala:80-128)."""
    ds, servers = dist
    ds.sql("CREATE TABLE nc_customer (c_custkey BIGINT, c_mktsegment STRING, "
           "c_nationkey BIGINT) USING column OPTIONS (partition_by 'c_nationkey')")
    ds.sql("CREATE TABLE nc_orders (o_orderkey BIGINT, o_custkey BIGINT, "
           "o_orderdate BIGINT, o_shippriority BIGINT) USING column "
           "OPTIONS (partition_by 'o_custkey')")
    ds.sql("CREATE TABLE nc_lineitem (l_orderkey BIGINT, l_extendedprice DOUBLE, "
           "l_discount DOUBLE, l_shipdate BIGINT, l_suppkey BIGINT) "
           "USING column OPTIONS (partition_by 'l_orderkey')")
    ds.sql("CREATE TABLE nc_supplier (s_suppkey BIGINT, s_nationkey BIGINT) "
           "USING column OPTIONS (partition_by 's_suppkey')")
    rng = np.random.default_rng(7)
    n_cust, n_ord, n_li, n_supp = 400, 3000, 12000, 50
    cust = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_mktsegment": np.array(["BUILDING", "AUTO", "STEEL"],
                                 dtype=object)[rng.integers(0, 3, n_cust)],
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int64)})
    orders = pd.DataFrame({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
        "o_orderdate": rng.integers(0, 1000, n_ord).astype(np.int64),
        "o_shippriority": rng.integers(0, 2, n_ord).astype(np.int64)})
    li = pd.DataFrame({
        "l_orderkey": rng.integers(0, n_ord, n_li).astype(np.int64),
        "l_extendedprice": np.round(rng.random(n_li) * 1000, 2),
        "l_discount": np.round(rng.random(n_li) * 0.1, 2),
        "l_shipdate": rng.integers(0, 1000, n_li).astype(np.int64),
        "l_suppkey": rng.integers(0, n_supp, n_li).astype(np.int64)})
    supp = pd.DataFrame({
        "s_suppkey": np.arange(n_supp, dtype=np.int64),
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64)})
    for name, df in (("nc_customer", cust), ("nc_orders", orders),
                     ("nc_lineitem", li), ("nc_supplier", supp)):
        ds.insert_arrays(name, [df[c].to_numpy() for c in df.columns])
    return ds, cust, orders, li, supp


def test_shuffle_exchange_q3(tpch_nc):
    """Q3 shape: big-big join (lineitem x orders) repartitions orders onto
    the order key; customer broadcasts."""
    ds, cust, orders, li, _ = tpch_nc
    r = ds.sql(
        "SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS rev,"
        " o_orderdate, o_shippriority "
        "FROM nc_customer, nc_orders, nc_lineitem "
        "WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey "
        "AND l_orderkey = o_orderkey AND o_orderdate < 500 "
        "AND l_shipdate > 500 "
        "GROUP BY l_orderkey, o_orderdate, o_shippriority "
        "ORDER BY rev DESC, l_orderkey LIMIT 10")
    m = li.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(cust, left_on="o_custkey", right_on="c_custkey")
    m = m[(m.c_mktsegment == "BUILDING") & (m.o_orderdate < 500)
          & (m.l_shipdate > 500)]
    m["rev"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                     as_index=False).rev.sum()
           .sort_values(["rev", "l_orderkey"],
                        ascending=[False, True]).head(10))
    got = r.rows()
    assert len(got) == len(exp)
    for row, (_, e) in zip(got, exp.iterrows()):
        assert row[0] == e.l_orderkey
        assert row[1] == pytest.approx(e.rev)
        assert row[2] == e.o_orderdate and row[3] == e.o_shippriority


def test_shuffle_exchange_q10_shape(tpch_nc):
    """Q10 shape: customer revenue over returned-ish items."""
    ds, cust, orders, li, _ = tpch_nc
    r = ds.sql(
        "SELECT c_custkey, sum(l_extendedprice * (1 - l_discount)) AS rev "
        "FROM nc_customer, nc_orders, nc_lineitem "
        "WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey "
        "AND o_orderdate >= 300 AND o_orderdate < 700 "
        "GROUP BY c_custkey ORDER BY rev DESC, c_custkey LIMIT 20")
    m = li.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(cust, left_on="o_custkey", right_on="c_custkey")
    m = m[(m.o_orderdate >= 300) & (m.o_orderdate < 700)]
    m["rev"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby("c_custkey", as_index=False).rev.sum()
           .sort_values(["rev", "c_custkey"],
                        ascending=[False, True]).head(20))
    got = r.rows()
    assert len(got) == len(exp)
    for row, (_, e) in zip(got, exp.iterrows()):
        assert row[0] == e.c_custkey
        assert row[1] == pytest.approx(e.rev)


def test_shuffle_exchange_q5_shape(tpch_nc):
    """Q5 shape: four tables, two exchanges (orders→orderkey shuffle,
    supplier+customer broadcast)."""
    ds, cust, orders, li, supp = tpch_nc
    r = ds.sql(
        "SELECT s_nationkey, sum(l_extendedprice * (1 - l_discount)) AS rev "
        "FROM nc_customer, nc_orders, nc_lineitem, nc_supplier "
        "WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey "
        "AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey "
        "GROUP BY s_nationkey ORDER BY rev DESC, s_nationkey")
    m = li.merge(orders, left_on="l_orderkey", right_on="o_orderkey")
    m = m.merge(cust, left_on="o_custkey", right_on="c_custkey")
    m = m.merge(supp, left_on="l_suppkey", right_on="s_suppkey")
    m = m[m.c_nationkey == m.s_nationkey]
    m["rev"] = m.l_extendedprice * (1 - m.l_discount)
    exp = (m.groupby("s_nationkey", as_index=False).rev.sum()
           .sort_values(["rev", "s_nationkey"], ascending=[False, True]))
    got = r.rows()
    assert len(got) == len(exp)
    for row, (_, e) in zip(got, exp.iterrows()):
        assert row[0] == e.s_nationkey
        assert row[1] == pytest.approx(e.rev)


def test_shuffle_exchange_invalidates_on_update(tpch_nc):
    """Exchange temp tables are cached by mutation VERSION: an UPDATE that
    keeps row counts constant must still invalidate them."""
    ds, cust, orders, li, _ = tpch_nc
    q = ("SELECT count(*), sum(l_extendedprice) FROM nc_orders, nc_lineitem "
         "WHERE l_orderkey = o_orderkey AND o_shippriority = 1")
    before = ds.sql(q).rows()[0]
    ds.sql("UPDATE nc_lineitem SET l_extendedprice = l_extendedprice + 1")
    after = ds.sql(q).rows()[0]
    assert after[0] == before[0]
    assert after[1] == pytest.approx(before[1] + before[0])


def test_outer_join_via_repartition(tpch_nc):
    """Outer joins of non-collocated tables work through repartition
    (broadcast is correctly refused for them)."""
    ds, cust, orders, li, _ = tpch_nc
    r = ds.sql(
        "SELECT count(*) FROM nc_orders o LEFT JOIN nc_lineitem l "
        "ON o.o_orderkey = l.l_orderkey")
    m = orders.merge(li, left_on="o_orderkey", right_on="l_orderkey",
                     how="left")
    assert r.rows()[0][0] == len(m)


def test_composite_key_shuffle_join(dist):
    """A composite-key join (x AND y) between two large non-collocated
    tables resolves by repartitioning on ONE key; the second equality is a
    residual filter (review finding: it used to raise)."""
    ds, _ = dist
    ds.sql("CREATE TABLE ck_a (x BIGINT, y BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'v')")
    ds.sql("CREATE TABLE ck_b (x BIGINT, y BIGINT, w DOUBLE) USING column "
           "OPTIONS (partition_by 'w')")
    rng = np.random.default_rng(5)
    n = 4000
    ax = np.arange(n, dtype=np.int64)   # unique build keys
    ay = ax % 7
    ds.insert_arrays("ck_a", [ax, ay, rng.random(n)])
    bx = rng.integers(0, n, 9000).astype(np.int64)
    by = rng.integers(0, 7, 9000).astype(np.int64)
    ds.insert_arrays("ck_b", [bx, by, rng.random(9000)])
    # force both over the broadcast budget so repartition is the only plan
    old = ds.planner.conf.hash_join_size
    ds.planner.conf.hash_join_size = 1
    try:
        r = ds.sql("SELECT count(*) FROM ck_a, ck_b WHERE ck_a.x = ck_b.x "
                   "AND ck_a.y = ck_b.y").rows()[0][0]
    finally:
        ds.planner.conf.hash_join_size = old
    da = pd.DataFrame({"x": ax, "y": ay})
    db = pd.DataFrame({"x": bx, "y": by})
    assert r == len(da.merge(db, on=["x", "y"]))


def test_exchange_cache_invalidated_by_recreate(dist):
    """DROP + CREATE resets server-side version counters; the exchange
    cache must not serve the dead incarnation's temp (review finding)."""
    ds, _ = dist
    for _ in range(2):
        ds.sql("DROP TABLE IF EXISTS rc_f")
        ds.sql("DROP TABLE IF EXISTS rc_d")
    ds.sql("CREATE TABLE rc_f (k BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'v')")
    ds.sql("CREATE TABLE rc_d (k BIGINT, t BIGINT) USING column "
           "OPTIONS (partition_by 'k')")
    ds.insert_arrays("rc_f", [np.arange(100, dtype=np.int64),
                              np.arange(100).astype(np.float64)])
    ds.insert_arrays("rc_d", [np.arange(100, dtype=np.int64),
                              np.ones(100, dtype=np.int64)])
    q = "SELECT count(*) FROM rc_f, rc_d WHERE rc_f.k = rc_d.k"
    assert ds.sql(q).rows()[0][0] == 100
    ds.sql("DROP TABLE rc_f")
    ds.sql("CREATE TABLE rc_f (k BIGINT, v DOUBLE) USING column "
           "OPTIONS (partition_by 'v')")
    ds.insert_arrays("rc_f", [np.arange(40, dtype=np.int64),
                              np.arange(40).astype(np.float64)])
    assert ds.sql(q).rows()[0][0] == 40


# --------------------------------------------------------------------------
# bucket redundancy: replica writes + failover re-hosting
# --------------------------------------------------------------------------

def _mini_cluster(n_servers=3):
    from snappydata_tpu.cluster import LocatorNode, ServerNode

    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(n_servers)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    return locator, servers, ds


def test_replica_failover_exact_counts():
    """Kill one of three servers after load: with REDUNDANCY 1 the
    replicas are promoted and count(*)/sum() stay EXACT (ref:
    StoreUtils.scala:179-215 redundant copies + membership recovery)."""
    locator, servers, ds = _mini_cluster()
    try:
        ds.sql("CREATE TABLE rf (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        rng = np.random.default_rng(31)
        n = 30_000
        k = rng.integers(0, 10_000, n).astype(np.int64)
        v = np.round(rng.random(n) * 100, 3)
        ds.insert_arrays("rf", [k, v])
        exact = (n, float(v.sum()))
        r = ds.sql("SELECT count(*), sum(v) FROM rf").rows()[0]
        assert r[0] == exact[0] and r[1] == pytest.approx(exact[1])

        # primary copies are disjoint; replicas are invisible to queries
        primary_total = sum(
            s.session.sql("SELECT count(*) FROM rf").rows()[0][0]
            for s in servers)
        assert primary_total == n

        servers[1].stop()   # kill a member
        ds.mark_server_failed(1)
        r = ds.sql("SELECT count(*), sum(v) FROM rf").rows()[0]
        assert r[0] == exact[0]
        assert r[1] == pytest.approx(exact[1])
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


def test_replica_failover_mid_load_auto_detect():
    """A server dying MID-LOAD: the insert loop re-routes the failed
    shard to the promoted owner and the final counts are exact."""
    locator, servers, ds = _mini_cluster()
    try:
        ds.sql("CREATE TABLE ml (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        rng = np.random.default_rng(37)
        total = 0
        for chunk in range(6):
            if chunk == 3:
                servers[2].stop()   # dies between chunks, NOT announced —
                # the next insert discovers it and fails over by itself
            nn = 5_000
            k = rng.integers(0, 8_000, nn).astype(np.int64)
            v = np.ones(nn)
            ds.insert_arrays("ml", [k, v])
            total += nn
        r = ds.sql("SELECT count(*), sum(v) FROM ml").rows()[0]
        assert r[0] == total and r[1] == pytest.approx(float(total))
        # UPDATE after failover still exact (replica shadows mutated too)
        upd = ds.sql("UPDATE ml SET v = 2.0 WHERE k < 4000").rows()[0][0]
        r2 = ds.sql("SELECT sum(v) FROM ml").rows()[0][0]
        assert r2 == pytest.approx(float(total) + upd)
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


def test_collocated_join_survives_failover():
    """Collocation is preserved across failover: both tables' buckets
    move to the SAME surviving server."""
    locator, servers, ds = _mini_cluster()
    try:
        ds.sql("CREATE TABLE co_a (k BIGINT, x DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        ds.sql("CREATE TABLE co_b (k BIGINT, y DOUBLE) USING column "
               "OPTIONS (partition_by 'k', colocate_with 'co_a', "
               "redundancy '1')")
        n = 5_000
        k = np.arange(n, dtype=np.int64)
        ds.insert_arrays("co_a", [k, np.ones(n)])
        ds.insert_arrays("co_b", [k, np.full(n, 2.0)])
        q = ("SELECT count(*), sum(a.x + b.y) FROM co_a a JOIN co_b b "
             "ON a.k = b.k")
        r = ds.sql(q).rows()[0]
        assert r[0] == n and r[1] == pytest.approx(3.0 * n)
        servers[0].stop()
        ds.mark_server_failed(0)
        r = ds.sql(q).rows()[0]
        assert r[0] == n and r[1] == pytest.approx(3.0 * n)
        # post-failover inserts follow the updated bucket map and stay
        # collocated with pre-failover rows
        k2 = np.arange(n, n + 1000, dtype=np.int64)
        ds.insert_arrays("co_a", [k2, np.ones(1000)])
        ds.insert_arrays("co_b", [k2, np.full(1000, 2.0)])
        r = ds.sql(q).rows()[0]
        assert r[0] == n + 1000 and r[1] == pytest.approx(3.0 * (n + 1000))
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


def test_redundancy_restored_after_successive_failures():
    """After a failover the promoted buckets are RE-REPLICATED onto a
    surviving member, so a SECOND member death still loses nothing."""
    locator, servers, ds = _mini_cluster(4)
    try:
        ds.sql("CREATE TABLE rr (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        rng = np.random.default_rng(41)
        n = 20_000
        k = rng.integers(0, 9_000, n).astype(np.int64)
        v = np.round(rng.random(n) * 10, 3)
        ds.insert_arrays("rr", [k, v])
        exact = (n, float(v.sum()))

        servers[0].stop()
        ds.mark_server_failed(0)
        r = ds.sql("SELECT count(*), sum(v) FROM rr").rows()[0]
        assert r[0] == exact[0] and r[1] == pytest.approx(exact[1])

        # redundancy was restored → a SECOND death is survivable
        servers[1].stop()
        ds.mark_server_failed(1)
        r = ds.sql("SELECT count(*), sum(v) FROM rr").rows()[0]
        assert r[0] == exact[0], (r[0], exact[0])
        assert r[1] == pytest.approx(exact[1])

        # and the cluster still ingests + mutates exactly
        ds.insert_arrays("rr", [np.arange(1000, dtype=np.int64),
                                np.ones(1000)])
        r = ds.sql("SELECT count(*) FROM rr").rows()[0][0]
        assert r == n + 1000
        upd = ds.sql("UPDATE rr SET v = 0.0 WHERE k < 100").rows()[0][0]
        r2 = ds.sql("SELECT count(*) FROM rr WHERE v = 0.0").rows()[0][0]
        assert r2 >= upd
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()
