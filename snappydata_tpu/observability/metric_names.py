"""Declared metric names — the registry the metrics-hygiene lint
(`python -m tools.locklint`, tools/locklint/metrics_lint.py) checks
every `.inc/.time/.record_time/.gauge` call against.

Why a static registry when the runtime registry is a defaultdict: the
PR 10 `_prom_name` collision class ("a.b" vs "a_b" silently merged in
Prometheus exposition until the crc-suffix fix) and plain typo'd
counter names (incremented forever, graphed never) are both invisible
at runtime. Declaring the namespace here turns both into CI failures.

Rules enforced by the lint:
- every literal metric name used anywhere in the package must appear
  below (any kind — several names are mirrored counter/gauge);
- dynamic names (f-strings / concatenation) must start with a prefix
  from DYNAMIC_PREFIXES;
- no two distinct declared-or-used names may collide after Prometheus
  sanitization.

This file must stay PURE LITERALS — the lint parses it without
importing the package.
"""

COUNTERS = {
    "agg_reduce_passes",
    "auto_rejoin_poll_errors",
    "batch_corrupt_records",
    "batches_skipped_dict",
    "breaker_open",
    "client_deadline_exceeded",
    "code_domain_predicates",
    "column_batches_seen",
    "column_batches_skipped",
    "compressed_fallbacks",
    "device_cache_evictions",
    "dist_downgrades",
    "failover_member_failed",
    "failover_redundancy_degraded",
    "failover_redundancy_restored",
    "failover_retries",
    "fault_injected",
    "gidx_cache_hits",
    "gidx_cache_misses",
    "governor_admitted",
    "governor_cancelled",
    "governor_degrade_epoch_trims",
    "governor_degrade_kills",
    "governor_degrade_plan_evictions",
    "governor_degrade_spills",
    "governor_degrade_view_evictions",
    "governor_queued",
    "governor_rejected",
    "governor_timeouts",
    "hedged_reads_fired",
    "hedged_reads_won",
    "host_batches_spilled",
    "host_fallbacks",
    "join_build_cache_hits",
    "join_build_cache_misses",
    "join_build_sorts",
    "join_device_joins",
    "join_expand_out_rows",
    "join_expand_probe_rows",
    "join_host_fallbacks",
    "join_trans_cache_hits",
    "member_heartbeat_failures",
    "member_heartbeats_stopped",
    "member_rejoins",
    "mesh_broadcast_bytes",
    "mesh_broadcast_cache_hits",
    "mesh_buckets_moved",
    "mesh_cache_moves",
    "mesh_exchange_bytes",
    "mesh_exchange_cache_hits",
    "mesh_exchange_rows",
    "mesh_fallback_budget",
    "mesh_fallback_compile",
    "mesh_fallback_complex",
    "mesh_fallback_decimal_exact",
    "mesh_fallback_decompose",
    "mesh_fallback_error",
    "mesh_fallback_merge_space",
    "mesh_fallback_outer_sort",
    "mesh_fallback_overflow",
    "mesh_fallback_params",
    "mesh_fallback_shape",
    "mesh_join_broadcast",
    "mesh_join_shuffle",
    "mesh_moved_bytes",
    "mesh_psum_merges",
    "mesh_rebalances",
    "mesh_shard_execs",
    "mutation_dedup_hits",
    "mvcc_cut_expand_errors",
    "mvcc_ddl_conflicts",
    "mvcc_epoch_trims",
    "mvcc_pin_releases",
    "mvcc_pins",
    "mvcc_repins",
    "plan_cache_evictions",
    "plan_cache_hits",
    "plan_cache_misses",
    "plan_key_builds",
    "point_lookups",
    "queries",
    "rejoin_clean_buckets",
    "rejoin_copied_buckets",
    "rejoin_partial_errors",
    "rle_run_predicates",
    "rows_returned",
    "scan_tile_device_merges",
    "scan_tile_host_merges",
    "scan_tile_prefetch_overlap",
    "scan_tiles",
    "serving_batch_fallbacks",
    "serving_batch_requests",
    "serving_batched_dispatches",
    "serving_bulk_transfers",
    "serving_handle_evictions",
    "serving_passthrough",
    "serving_prepared_hits",
    "serving_prepared_misses",
    "serving_reprepares",
    "serving_straight_through",
    "serving_vmap_compiles",
    "slow_queries",
    "stats_poll_errors",
    "stream_apply_errors",
    "stream_scan_chunks",
    "stream_scan_early_stops",
    "stream_scan_rows",
    "stream_source_errors",
    "take_batches_decoded",
    "take_early_stops",
    "view_delta_folds",
    "view_fold_errors",
    "view_full_refreshes",
    "view_pending_folds",
    "view_pending_replays",
    "view_reads",
    "view_replay_folds",
    "view_rows_folded",
    "view_stale_marks",
    "view_state_evictions",
    "view_state_regrows",
    "view_subtract_folds",
    "view_syncs",
    "view_unmanaged_writes",
    "wal_bytes_written",
    "wal_flusher_errors",
    "wal_fsync_count",
    "wal_group_commit_batches",
    "wal_records_written",
}

TIMERS = {
    "failover_backoff",
    "plan_compile",
    "query",
    "wal_group_flush",
}

GAUGES = {
    "governor_active_queries",
    "governor_device_bytes",
    "governor_host_bytes",
    "governor_inflight_bytes",
    "governor_queued_queries",
    "heartbeats_stopped",
    "rows_total",
    "tables_total",
}

# literal prefixes that dynamic (f-string / concatenated) metric names
# are allowed to extend — each is a bounded family, not a free-form
# namespace
DYNAMIC_PREFIXES = {
    "fault_injected_",
    "agg_strategy_",
    "compressed_fallback_",
    "join_fallback_",
    "mesh_fallback_",
    "mesh_join_shuffle_fallback_",
}
