"""End-to-end request reliability (the HA tentpole): deadlines that cut
stalled scatters (SQLSTATE XCL52), idempotent mutation retry through the
WAL-persisted dedup window, hedged replica reads, member rejoin with
watermark delta-resync, heartbeat hardening, and the /status/api/v1/ha
observability surface — plus a seeded kill-a-server schedule running
UNDER the prepared-statement serving path.

Invariants (the acceptance battery):

  - a failpoint-latency-stalled member cannot hold a scatter past its
    deadline; the caller gets XCL52 within deadline + one probe
    interval;
  - hedged reads (when enabled) return correct first-answer results
    with hedged_reads_fired > 0;
  - a mutation whose ack is lost retries TRANSPARENTLY and never
    double-applies — including across ≥5 seeded crash-recover rounds
    (the dedup window is rebuilt from WAL headers);
  - a killed-and-restarted member is resynced and re-admitted
    automatically: degraded_buckets() empties without a manual
    restore_redundancy(), clean buckets move ZERO bytes;
  - under the serving path, killing a member mid-stream leaves every
    in-flight request either value-correct or failed with a typed
    RETRYABLE error; acked rows survive, nothing double-applies.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.ha

from snappydata_tpu import SnappySession, config, fault, reliability
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.client import SnappyClient
from snappydata_tpu.cluster.distributed import (DistributedError,
                                                DistributedSession)
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.resource.context import CancelException


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


def _counter(name):
    return global_registry().counter(name)


def _cluster(tmp_path=None, n=2, redundancy=1, table=True):
    locator = LocatorNode().start()
    sessions = []
    for i in range(n):
        kw = {}
        if tmp_path is not None:
            kw = {"data_dir": str(tmp_path / f"srv{i}"), "recover": False}
        sessions.append(SnappySession(catalog=Catalog(), **kw))
    servers = [ServerNode(locator.address, s).start() for s in sessions]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers],
        locator=locator.address)
    if table:
        ds.sql(f"CREATE TABLE t (k BIGINT, v DOUBLE) USING column "
               f"OPTIONS (partition_by 'k', redundancy '{redundancy}')")
    return locator, sessions, servers, ds


def _teardown(locator, sessions, servers, ds):
    ds.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass
    for s in sessions:
        try:
            if s.disk_store is not None:
                s.disk_store.close()
        except Exception:
            pass
    locator.stop()


# -----------------------------------------------------------------------
# deadline propagation
# -----------------------------------------------------------------------

def test_deadline_cuts_stalled_scatter():
    """A latency-stalled member cannot hold a scatter query past its
    deadline: the caller gets XCL52 well before the stall would have
    released, the deadline counter ticks, and the cluster answers
    normally afterwards (the stall was slowness, not death — no
    spurious failover)."""
    locator, sessions, servers, ds = _cluster(n=2, redundancy=0)
    try:
        n = 2000
        ks = np.arange(n, dtype=np.int64)
        ds.insert_arrays("t", [ks, ks * 1.0])
        ds.sql("SELECT count(*) FROM t")   # warm compiles
        before = _counter("client_deadline_exceeded")
        fault.arm("flight.serve", "latency", param=5.0, count=1)
        t0 = time.time()
        with pytest.raises(CancelException) as ei:
            ds.sql("SELECT count(*) FROM t", timeout_s=0.5)
        elapsed = time.time() - t0
        assert "XCL52" in str(ei.value)
        # deadline + one (deadline-capped) probe interval, NOT the 5s
        # stall — generous 3s bound absorbs container contention
        assert elapsed < 3.0, elapsed
        assert _counter("client_deadline_exceeded") > before
        assert not reliability.is_retryable(ei.value)
        fault.clear()
        # slowness was not death: both members still alive and exact
        assert all(ds.alive)
        assert ds.sql("SELECT count(*), sum(v) FROM t").rows() == \
            [(n, float(ks.sum()))]
        # query_timeout_s (the session knob) arms the same deadline
        # when no per-request timeout is given
        try:
            ds.planner.conf.query_timeout_s = 0.4
            fault.arm("flight.serve", "latency", param=5.0, count=1)
            t0 = time.time()
            with pytest.raises(CancelException):
                ds.sql("SELECT count(*) FROM t")
            assert time.time() - t0 < 3.0
        finally:
            ds.planner.conf.query_timeout_s = 0.0
    finally:
        _teardown(locator, sessions, servers, ds)


# -----------------------------------------------------------------------
# hedged replica reads
# -----------------------------------------------------------------------

def test_hedged_read_takes_first_answer():
    """With hedge_reads on, a stalled primary's fragment re-issues to
    its replica holder over the __replica shadows and the first answer
    wins — value-asserted, well before the stall releases."""
    props = config.global_properties()
    locator, sessions, servers, ds = _cluster(n=3, redundancy=1)
    try:
        n = 3000
        ks = np.arange(n, dtype=np.int64)
        ds.insert_arrays("t", [ks, ks * 1.0])
        ds.sql("SELECT count(*), sum(v) FROM t")   # warm compiles
        props.set("hedge_reads", True)
        props.set("hedge_after_ms", 40.0)
        fired0 = _counter("hedged_reads_fired")
        fault.arm("flight.serve", "latency", param=4.0, count=1)
        t0 = time.time()
        rows = ds.sql("SELECT count(*), sum(v) FROM t",
                      timeout_s=15.0).rows()
        elapsed = time.time() - t0
        fault.clear()
        assert rows == [(n, float(ks.sum()))]
        assert elapsed < 3.5, elapsed   # never waited out the 4s stall
        assert _counter("hedged_reads_fired") > fired0
    finally:
        props.set("hedge_reads", False)
        _teardown(locator, sessions, servers, ds)


@pytest.mark.slow
def test_hedge_ineligible_without_redundancy():
    """No replicas → no hedge target: the builder declines and reads
    stay exact (a hedge over non-mirroring shadows would answer wrong
    rows — declining IS the correctness property)."""
    props = config.global_properties()
    locator, sessions, servers, ds = _cluster(n=2, redundancy=0)
    try:
        ds.insert_arrays("t", [np.arange(100, dtype=np.int64),
                               np.ones(100)])
        props.set("hedge_reads", True)
        fired0 = _counter("hedged_reads_fired")
        assert ds.sql("SELECT count(*) FROM t").rows() == [(100,)]
        assert _counter("hedged_reads_fired") == fired0
    finally:
        props.set("hedge_reads", False)
        _teardown(locator, sessions, servers, ds)


# -----------------------------------------------------------------------
# idempotent mutation retry (lost-ack dedup)
# -----------------------------------------------------------------------

def test_mutation_lost_ack_retries_transparently():
    """The PR 2 blind-retry trap, closed: a response dropped AFTER the
    server applied used to raise ConnectionError to the caller (retrying
    would have double-applied). The stamped statement id + server dedup
    window turn it into a transparent retry that applies exactly once."""
    locator = LocatorNode().start()
    sess = SnappySession(catalog=Catalog())
    node = ServerNode(locator.address, sess).start()
    client = SnappyClient(address=node.flight_address)
    try:
        client.execute("CREATE TABLE mut (k BIGINT) USING column")
        r0, d0 = _counter("mutation_retries"), _counter(
            "mutation_dedup_hits")
        fault.arm("flight.rpc", "drop", phase="after", count=1)
        out = client.execute("INSERT INTO mut VALUES (7)")
        fault.clear()
        assert out.get("deduped"), out
        assert _counter("mutation_retries") == r0 + 1
        assert _counter("mutation_dedup_hits") == d0 + 1
        got = client.sql("SELECT count(*) FROM mut").to_pydict()
        assert list(got.values())[0] == [1]
        # do_put lane too: a dropped put-ack retries and dedups
        import pyarrow as pa

        fault.arm("flight.rpc", "drop", phase="after", count=1)
        client.insert("mut", pa.table({"k": np.array([8], np.int64)}))
        fault.clear()
        got = client.sql(
            "SELECT count(*), count(DISTINCT k) FROM mut").to_pydict()
        assert [v[0] for v in got.values()] == [2, 2]
    finally:
        node.stop()
        locator.stop()


def test_mutation_retry_pins_to_applied_server():
    """A mutation retry must reconnect to the SAME member that may have
    applied the first send — dedup windows are per-server, so a locator
    failover to a different member would re-apply there. When the
    member is gone the client surfaces the connection error (zero or
    one applies, never two); idempotent reads still fail over."""
    locator = LocatorNode().start()
    sessions = [SnappySession(catalog=Catalog()) for _ in range(2)]
    servers = [ServerNode(locator.address, s).start() for s in sessions]
    for s in sessions:
        s.sql("CREATE TABLE pin (k BIGINT) USING column")
    client = SnappyClient(locator=locator.address)
    try:
        client.sql("SELECT count(*) FROM pin")   # connect somewhere
        addr = client._conn_addr
        victim = next(i for i, s in enumerate(servers)
                      if s.flight_address == addr)
        other = sessions[1 - victim]
        servers[victim].stop()
        with pytest.raises(ConnectionError):
            client.execute("INSERT INTO pin VALUES (1)")
        # at-most-once held: the OTHER member never saw the mutation
        assert other.sql("SELECT count(*) FROM pin").rows() == [(0,)]
        # idempotent reads are not pinned: the next query fails over
        got = client.sql("SELECT count(*) FROM pin")
        assert got.column(0).to_pylist() == [0]
        assert client._conn_addr != addr
    finally:
        client.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()


def test_mutation_dedup_survives_crash_recovery(tmp_path):
    """≥5 seeded crash-recover rounds: a retry carrying the SAME
    statement id that lands AFTER the server restarted still dedups —
    the window is rebuilt from WAL record headers during replay. Final
    rowcounts assert exactly-once end to end."""
    locator = LocatorNode().start()
    d = str(tmp_path / "srv")
    sess = SnappySession(catalog=Catalog(), data_dir=d, recover=False)
    node = ServerNode(locator.address, sess).start()
    client = SnappyClient(address=node.flight_address)
    client.execute("CREATE TABLE mut (k BIGINT) USING column")
    try:
        for i in range(5):
            sid = f"ha-round-{i}"
            client.execute(f"INSERT INTO mut VALUES ({100 + i})",
                           stmt_id=sid)
            # crash + recover the server
            node.stop()
            sess.disk_store.close()
            sess = SnappySession(data_dir=d, recover=True)
            node = ServerNode(locator.address, sess).start()
            client = SnappyClient(address=node.flight_address)
            d0 = _counter("mutation_dedup_hits")
            out = client.execute(f"INSERT INTO mut VALUES ({100 + i})",
                                 stmt_id=sid)
            assert out.get("deduped"), (i, out)
            assert _counter("mutation_dedup_hits") == d0 + 1
        got = client.sql(
            "SELECT count(*), count(DISTINCT k) FROM mut").to_pydict()
        assert [v[0] for v in got.values()] == [5, 5]
    finally:
        node.stop()
        try:
            sess.disk_store.close()
        except Exception:
            pass
        locator.stop()


# -----------------------------------------------------------------------
# member rejoin with watermark delta-resync
# -----------------------------------------------------------------------

def test_rejoin_resyncs_and_restores_redundancy(tmp_path):
    """Kill a member, keep writing (dirtying SOME buckets), restart it
    from its recovered data dir, and let the locator-driven poll rejoin
    it: degraded_buckets() empties WITHOUT restore_redundancy(), clean
    buckets reclaim zero-copy, dirty ones get fresh copies — and a
    subsequent death of the OTHER member proves the restored redundancy
    is real (no phantom replicas)."""
    locator, sessions, servers, ds = _cluster(tmp_path, n=2, redundancy=1)
    try:
        n = 4000
        ks = np.arange(n, dtype=np.int64)
        rng = np.random.default_rng(7)
        vs = np.round(rng.random(n) * 100, 3)
        ds.insert_arrays("t", [ks, vs])
        expected_sum = float(vs.sum())

        servers[1].stop()
        sessions[1].disk_store.close()
        ds.mark_server_failed(1)
        assert len(ds.degraded_buckets()) == ds.num_buckets
        # writes while the member is down: a NARROW key range, so most
        # buckets stay clean (watermark unchanged)
        extra = np.arange(n, n + 64, dtype=np.int64)
        ds.insert_arrays("t", [extra, np.ones(64)])
        expected_sum += 64.0
        total = n + 64

        # restart with recovered data + membership-driven auto-rejoin
        sessions[1] = SnappySession(data_dir=str(tmp_path / "srv1"),
                                    recover=True)
        servers[1] = ServerNode(locator.address, sessions[1]).start()
        rj0 = _counter("member_rejoins")
        out = ds.poll_rejoins()
        assert out and out[0]["rejoined"], out
        summary = out[0]
        assert _counter("member_rejoins") == rj0 + 1
        assert summary["errors"] == []
        # delta resync: clean buckets moved ZERO bytes, dirty ones copied
        assert summary["clean_primary_buckets"] > 0
        assert summary["copied_buckets"] > 0
        assert summary["clean_primary_buckets"] + \
            summary["clean_replica_buckets"] + \
            summary["copied_buckets"] <= 2 * ds.num_buckets
        # THE acceptance bar: redundancy restored with no manual
        # restore_redundancy()
        assert ds.degraded_buckets() == []
        rows = ds.sql("SELECT count(*), sum(v) FROM t").rows()
        assert rows[0][0] == total
        assert rows[0][1] == pytest.approx(expected_sum, rel=1e-9)
        # value-asserted sample rows (not just aggregates)
        got = ds.sql("SELECT v FROM t WHERE k = 1234").rows()
        assert got == [(pytest.approx(float(vs[1234])),)]

        # the restored redundancy is REAL: kill the other member — the
        # rejoined one answers complete, exact results on its own
        servers[0].stop()
        sessions[0].disk_store.close()
        ds.mark_server_failed(0)
        rows = ds.sql("SELECT count(*), sum(v) FROM t").rows()
        assert rows[0][0] == total
        assert rows[0][1] == pytest.approx(expected_sum, rel=1e-9)
    finally:
        _teardown(locator, sessions, servers, ds)


@pytest.mark.slow
def test_rejoin_without_snapshot_full_resync(tmp_path):
    """A lead with no death snapshot (it restarted too) cannot verify
    any recovered bucket: rejoin degrades to full resync — still
    automatic, still exact, still redundancy-restoring."""
    locator, sessions, servers, ds = _cluster(tmp_path, n=2, redundancy=1)
    try:
        n = 1500
        ks = np.arange(n, dtype=np.int64)
        ds.insert_arrays("t", [ks, ks * 0.25])
        servers[1].stop()
        sessions[1].disk_store.close()
        ds.mark_server_failed(1)
        ds._death_snapshots.clear()   # lead restarted: watermark gone
        sessions[1] = SnappySession(data_dir=str(tmp_path / "srv1"),
                                    recover=True)
        servers[1] = ServerNode(locator.address, sessions[1]).start()
        out = ds.rejoin_server(1, servers[1].flight_address)
        assert out["rejoined"] and out["errors"] == []
        assert out["clean_primary_buckets"] == 0   # nothing verifiable
        assert ds.degraded_buckets() == []
        assert ds.sql("SELECT count(*), sum(v) FROM t").rows() == \
            [(n, pytest.approx(float(ks.sum()) * 0.25))]
    finally:
        _teardown(locator, sessions, servers, ds)


def test_rejoin_restores_lost_buckets(tmp_path):
    """Redundancy 0: a member death LOSES its buckets (no surviving
    copy). The restarted member's recovered rows are the ONLY copy —
    rejoin must RESTORE them, never purge them (review finding: the
    purge path used to journal the only copy away), with or without a
    usable watermark snapshot."""
    locator, sessions, servers, ds = _cluster(tmp_path, n=2,
                                              redundancy=0)
    try:
        n = 1200
        ks = np.arange(n, dtype=np.int64)
        ds.insert_arrays("t", [ks, ks * 2.0])
        servers[1].stop()
        sessions[1].disk_store.close()
        ds.mark_server_failed(1)
        lost_now = ds.sql("SELECT count(*) FROM t").rows()[0][0]
        assert lost_now < n   # buckets really were lost
        sessions[1] = SnappySession(data_dir=str(tmp_path / "srv1"),
                                    recover=True)
        servers[1] = ServerNode(locator.address, sessions[1]).start()
        out = ds.rejoin_server(1, servers[1].flight_address)
        assert out["rejoined"], out
        rows = ds.sql("SELECT count(*), sum(v) FROM t").rows()
        assert rows == [(n, pytest.approx(float(ks.sum()) * 2.0))], rows
        # same invariant with NO watermark snapshot (lead restarted):
        # the full-resync path must still keep the only-copy buckets
        servers[1].stop()
        sessions[1].disk_store.close()
        ds.mark_server_failed(1)
        ds._death_snapshots.clear()
        sessions[1] = SnappySession(data_dir=str(tmp_path / "srv1"),
                                    recover=True)
        servers[1] = ServerNode(locator.address, sessions[1]).start()
        out = ds.rejoin_server(1, servers[1].flight_address)
        assert out["rejoined"], out
        rows = ds.sql("SELECT count(*), sum(v) FROM t").rows()
        assert rows == [(n, pytest.approx(float(ks.sum()) * 2.0))], rows
    finally:
        _teardown(locator, sessions, servers, ds)


# -----------------------------------------------------------------------
# heartbeat hardening (satellite)
# -----------------------------------------------------------------------

def test_heartbeat_survives_transient_runtime_errors():
    """Transient protocol-shaped failures (locator restart mid-upgrade)
    retry with capped backoff instead of permanently stopping the
    heartbeat loop — the member stays in the view and the
    heartbeats_stopped gauge stays clean."""
    from snappydata_tpu.cluster.locator import Locator, LocatorClient

    loc = Locator().start()
    lc = LocatorClient(loc.address, "hb-member", "server", port=1234)
    try:
        lc.register()
        lc.start_heartbeats(interval_s=0.05)
        hb0 = _counter("member_heartbeat_failures")
        fault.arm("locator.heartbeat", "raise", exc="runtime", count=3)
        deadline = time.time() + 5.0
        while _counter("member_heartbeat_failures") < hb0 + 3 and \
                time.time() < deadline:
            time.sleep(0.02)
        assert _counter("member_heartbeat_failures") >= hb0 + 3
        # wait for a post-fault successful beat
        time.sleep(0.5)
        members = {m.member_id for m in lc.members()}
        assert "hb-member" in members, "member was swept out"
        snap = global_registry().snapshot()["gauges"]
        assert (snap.get("heartbeats_stopped") or 0.0) == 0.0
    finally:
        lc.close()
        loc.stop()


@pytest.mark.slow
def test_heartbeat_gives_up_visibly_on_persistent_mismatch():
    """A REAL protocol mismatch persists past the retry cap: the loop
    stops — but visibly, on the heartbeats_stopped gauge an operator
    can alarm on (the old behavior stopped silently on the FIRST)."""
    from snappydata_tpu.cluster.locator import Locator, LocatorClient

    loc = Locator().start()
    lc = LocatorClient(loc.address, "hb-doomed", "server", port=1235)
    lc.HEARTBEAT_GIVEUP = 2          # keep the test fast
    lc.HEARTBEAT_BACKOFF_MAX_S = 0.05
    try:
        lc.register()
        s0 = _counter("member_heartbeats_stopped")
        fault.arm("locator.heartbeat", "raise", exc="runtime", count=50)
        lc.start_heartbeats(interval_s=0.02)
        deadline = time.time() + 5.0
        while _counter("member_heartbeats_stopped") < s0 + 1 and \
                time.time() < deadline:
            time.sleep(0.02)
        fault.clear()
        assert _counter("member_heartbeats_stopped") == s0 + 1
        snap = global_registry().snapshot()["gauges"]
        assert (snap.get("heartbeats_stopped") or 0.0) >= 1.0
    finally:
        lc.close()   # discards from the gauge: deliberate ≠ alarm
        loc.stop()
        snap = global_registry().snapshot()["gauges"]
        assert (snap.get("heartbeats_stopped") or 0.0) == 0.0


# -----------------------------------------------------------------------
# observability surface
# -----------------------------------------------------------------------

def test_rest_ha_endpoint_and_dashboard():
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability import TableStatsService

    s = SnappySession(catalog=Catalog())
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        base = f"http://{svc.host}:{svc.port}"
        with urllib.request.urlopen(f"{base}/status/api/v1/ha") as r:
            ha = json.loads(r.read())
        for key in ("mutation_retries", "mutation_dedup_hits",
                    "hedged_reads_fired", "member_rejoins",
                    "deadline_exceeded", "heartbeats_stopped",
                    "hedge_reads", "client_timeout_s"):
            assert key in ha, key
        with urllib.request.urlopen(f"{base}/dashboard") as r:
            html = r.read().decode()
        assert "High availability" in html
    finally:
        svc.stop()


@pytest.mark.slow
def test_rest_sql_timeout_s():
    """POST /sql honors a per-request timeout_s: a stalled statement
    returns the XCL52 error body instead of holding the HTTP worker."""
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability import TableStatsService

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rt (k BIGINT) USING column")
    s.insert_arrays("rt", [np.arange(50_000, dtype=np.int64)])
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        base = f"http://{svc.host}:{svc.port}"
        body = json.dumps({"sql": "SELECT count(*) FROM rt",
                           "timeout_s": 1e-7}).encode()
        req = urllib.request.Request(
            f"{base}/sql", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert "XCL52" in ei.value.read().decode()
        # sane budget: same statement completes
        body = json.dumps({"sql": "SELECT count(*) FROM rt",
                           "timeout_s": 30.0}).encode()
        req = urllib.request.Request(
            f"{base}/sql", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["rows"] == [[50_000]]
    finally:
        svc.stop()


# -----------------------------------------------------------------------
# seeded kill-a-server schedule UNDER the serving path (satellite)
# -----------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_kill_under_serving_path():
    """PR 7's front door under PR 8's reliability layer: concurrent
    prepared-statement readers (fused batches included) hammer the
    cluster while a seeded fault storm runs and a member is hard-killed
    mid-stream. Invariants:

      - every COMPLETED request is value-correct (prepared point reads
        checked row by row);
      - every FAILED in-flight request failed with a typed RETRYABLE
        error (reliability.is_retryable) — never a wrong answer, never
        an unclassifiable error;
      - killing a primary mid-scatter with redundancy 1 completes the
        scatter with value-asserted rows;
      - acked mutations all survive; nothing double-applies."""
    seed = 20260804
    rng = np.random.default_rng(seed)
    fault.reseed(seed)
    locator, sessions, servers, ds = _cluster(n=3, redundancy=1)
    try:
        # replicated serving table: any member answers point reads whole
        ds.sql("CREATE TABLE kv (k BIGINT, v DOUBLE) USING column")
        nk = 512
        kk = np.arange(nk, dtype=np.int64)
        ds.insert_arrays("kv", [kk, kk * 2.0])
        acked = 0
        ks = np.arange(1000, dtype=np.int64)
        ds.insert_arrays("t", [ks, ks * 1.0])
        acked += 1000

        wrong, unexpected = [], []
        stop = threading.Event()
        completed = [0]

        def reader(ci):
            client = SnappyClient(address=servers[ci % 3].flight_address,
                                  locator=locator.address)
            r = np.random.default_rng(1000 + ci)
            while not stop.is_set():
                k = int(r.integers(0, nk))
                try:
                    tbl = client.sql("SELECT v FROM kv WHERE k = ?",
                                     params=[k], prepared=True)
                    vals = tbl.column(0).to_pylist()
                    if vals != [k * 2.0]:
                        wrong.append((k, vals))
                    completed[0] += 1
                except Exception as e:   # noqa: BLE001
                    if not reliability.is_retryable(e):
                        unexpected.append(repr(e))
            client.close()

        threads = [threading.Thread(target=reader, args=(ci,))
                   for ci in range(4)]
        for t in threads:
            t.start()
        # seeded fault storm over client RPC (connection-shaped only:
        # the typed-retryable contract is exactly what we're asserting)
        fault.arm("flight.rpc", "latency", param=0.002, p=0.3)
        fault.arm("flight.rpc", "drop", p=0.1)
        deadline = time.time() + 10.0
        while completed[0] < 40 and time.time() < deadline:
            time.sleep(0.01)
        assert completed[0] >= 40, "storm starved every reader"
        # mutations keep landing during the storm (acked == counted)
        for i in range(6):
            try:
                ds.insert_arrays(
                    "t", [np.arange(1000 + acked, 1008 + acked,
                                    dtype=np.int64)[:8], np.ones(8)])
                acked += 8
            except Exception:
                pass   # un-acked: excluded by design

        # hard-kill a member mid-stream; readers keep going (failover)
        victim = next(i for i in range(3) if ds.alive[i])
        servers[victim].stop()
        # the very next scatter pays the failover and must still be
        # value-correct (replica promotion keeps it complete)
        got = ds.sql("SELECT count(*), sum(v) FROM kv").rows()
        assert got == [(nk, float(kk.sum()) * 2.0)], got
        t_deadline = time.time() + 10.0
        c0 = completed[0]
        while completed[0] < c0 + 20 and time.time() < t_deadline:
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        fault.clear()
        assert not any(t.is_alive() for t in threads), \
            "a reader hung through the kill"
        assert wrong == [], f"wrong answers under chaos: {wrong[:3]}"
        assert unexpected == [], \
            f"non-retryable in-flight failures: {unexpected[:3]}"
        assert completed[0] > c0, "no reader survived the kill"
        # acked rows complete, nothing double-applied, values exact
        rows = ds.sql(
            "SELECT count(*), count(DISTINCT k) FROM t").rows()
        assert rows[0][0] == acked and rows[0][1] == acked, (rows, acked)
    finally:
        fault.clear()
        _teardown(locator, sessions, servers, ds)
