"""CREATE FUNCTION / UDFs (round-3 verdict Missing #6; ref:
SnappyDDLParser.scala:765 createFunction, dispatch :1056): SQL-registered
scalar functions callable in queries. TPU-first: the python body runs on
the TRACED values, fusing into the compiled XLA program; the host path
evaluates the identical body on numpy arrays."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def sess():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE tf (k BIGINT, price DOUBLE, rate DOUBLE, "
          "name STRING) USING column")
    rng = np.random.default_rng(4)
    n = 5000
    s.insert_arrays("tf", [
        np.arange(n, dtype=np.int64),
        np.round(rng.random(n) * 100, 2),
        np.round(rng.random(n) * 0.2, 3),
        np.array([f"s{i % 5}" for i in range(n)], dtype=object)])
    yield s
    s.stop()


def test_udf_in_projection_and_where(sess):
    sess.sql("CREATE FUNCTION taxed AS "
             "'lambda price, rate: price * (1 + rate)' RETURNS DOUBLE")
    r = sess.sql("SELECT sum(taxed(price, rate)) FROM tf")
    # oracle
    pr = sess.sql("SELECT sum(price * (1 + rate)) FROM tf").rows()[0][0]
    assert r.rows()[0][0] == pytest.approx(pr)
    r2 = sess.sql("SELECT count(*) FROM tf WHERE taxed(price, rate) > 60")
    e2 = sess.sql("SELECT count(*) FROM tf "
                  "WHERE price * (1 + rate) > 60").rows()[0][0]
    assert r2.rows()[0][0] == e2


def test_udf_with_jnp_ops(sess):
    sess.sql("CREATE FUNCTION clipped AS "
             "'lambda x: jnp.clip(x, 10, 90)' RETURNS DOUBLE")
    r = sess.sql("SELECT avg(clipped(price)) FROM tf").rows()[0][0]
    prices = sess.sql("SELECT price FROM tf")
    exact = float(np.clip(np.asarray(prices.columns[0]), 10, 90).mean())
    assert r == pytest.approx(exact, rel=1e-9)


def test_udf_nulls_propagate(sess):
    sess.sql("CREATE FUNCTION dbl AS 'lambda x: x * 2' RETURNS DOUBLE")
    sess.sql("CREATE TABLE tn (v DOUBLE) USING column")
    sess.sql("INSERT INTO tn VALUES (1.0), (NULL), (3.0)")
    r = sess.sql("SELECT dbl(v) FROM tn ORDER BY v NULLS FIRST")
    vals = [row[0] for row in r.rows()]
    assert None in vals
    assert sorted(v for v in vals if v is not None) == [2.0, 6.0]


def test_udf_group_by_key(sess):
    sess.sql("CREATE FUNCTION bucket2 AS 'lambda k: k % 3' "
             "RETURNS LONG")
    r = sess.sql("SELECT bucket2(k) AS b, count(*) FROM tf "
                 "GROUP BY bucket2(k) ORDER BY b")
    assert [row[0] for row in r.rows()] == [0, 1, 2]
    assert sum(row[1] for row in r.rows()) == 5000


def test_or_replace_and_drop(sess):
    sess.sql("CREATE FUNCTION f1 AS 'lambda x: x + 1' RETURNS DOUBLE")
    assert sess.sql("SELECT f1(price) FROM tf LIMIT 1").num_rows == 1
    with pytest.raises(Exception, match="already exists"):
        sess.sql("CREATE FUNCTION f1 AS 'lambda x: x + 2'")
    sess.sql("CREATE OR REPLACE FUNCTION f1 AS 'lambda x: x + 100' "
             "RETURNS DOUBLE")
    one = sess.sql("SELECT f1(price) - price FROM tf LIMIT 1").rows()[0][0]
    assert one == pytest.approx(100.0)
    sess.sql("DROP FUNCTION f1")
    with pytest.raises(Exception, match="unknown function|unsupported"):
        sess.sql("SELECT f1(price) FROM tf")
    sess.sql("DROP FUNCTION IF EXISTS f1")   # no error


def test_udf_rejected_on_unauthenticated_network_principal(sess):
    remote = sess.for_user("bob", remote=True, authenticated=False)
    # refused either by the DDL-is-admin gate or the code-surface gate
    with pytest.raises(PermissionError,
                       match="CREATE FUNCTION|admin-only"):
        remote.execute_statement(
            __import__("snappydata_tpu.sql.parser",
                       fromlist=["parse"]).parse(
                "CREATE FUNCTION evil AS 'lambda x: x'"))


def test_udf_invalid_body_rejected(sess):
    with pytest.raises(Exception, match="does not evaluate|callable"):
        sess.sql("CREATE FUNCTION bad AS 'this is not python'")
    with pytest.raises(Exception, match="callable"):
        sess.sql("CREATE FUNCTION bad2 AS '42'")


def test_udf_survives_recovery(tmp_path):
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE rt (v DOUBLE) USING column")
    s.sql("INSERT INTO rt VALUES (2.0), (4.0)")
    s.sql("CREATE FUNCTION trip AS 'lambda x: x * 3' RETURNS DOUBLE")
    s.checkpoint()
    s.disk_store.close()
    s2 = SnappySession(data_dir=d)
    r = s2.sql("SELECT sum(trip(v)) FROM rt").rows()[0][0]
    assert r == pytest.approx(18.0)
    s2.disk_store.close()
