"""Fault injection + failure-handling primitives (failpoints, backoff,
circuit breaker). See fault/failpoints.py for the failpoint registry and
cluster/retry.py for the retry policies the injected faults exercise."""

from snappydata_tpu.fault.failpoints import (ACTIONS, KNOWN_POINTS,
                                             FailpointRegistry,
                                             FaultConnectionDropped,
                                             FaultError, FaultSpec, arm,
                                             clear, disarm, hit, registry,
                                             reseed)

__all__ = [
    "ACTIONS", "KNOWN_POINTS", "FailpointRegistry", "FaultSpec",
    "FaultError", "FaultConnectionDropped", "arm", "clear", "disarm",
    "hit", "registry", "reseed",
]
