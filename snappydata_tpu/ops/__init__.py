"""TPU kernels (Pallas) for hot ops the XLA-level path can't express
optimally. Import from submodules; everything degrades gracefully on
non-TPU backends (interpret mode / jnp fallback)."""

from snappydata_tpu.ops.pallas_reduce import masked_kahan_sum  # noqa: F401
