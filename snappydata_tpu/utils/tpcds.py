"""TPC-DS mini-kit: generators + the reporting-family queries.

Parity with the reference's TPC-DS harness (cluster/src/test/scala/org/
apache/spark/sql/execution/benchmark/TPCDSQuerySnappyBenchmark.scala —
it drives dsdgen output through SnappySession; here the star-schema
tables generate synthetically at a scale factor, FK-consistent, with
the canonical column names so the canonical query text runs verbatim).

Queries included: the brand/category revenue reporting family
(q3, q42, q52, q55), the 6-way join q19, the correlated-subquery
customer report q6, the ROLLUP gross-margin report q36, and the
windowed revenue-ratio report q98.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

STORE_SALES_ROWS_PER_SF = 2_880_000


def gen_date_dim(num_years: int = 5, seed: int = 0) -> Dict[str, np.ndarray]:
    days = 365 * num_years
    sk = np.arange(2_450_000, 2_450_000 + days, dtype=np.int64)
    doy = np.arange(days) % 365
    year = 1998 + (np.arange(days) // 365)
    moy = (doy // 30) % 12 + 1
    return {
        "d_date_sk": sk,
        "d_year": year.astype(np.int32),
        "d_moy": moy.astype(np.int32),
        "d_qoy": ((moy - 1) // 3 + 1).astype(np.int32),
        "d_dow": (np.arange(days) % 7).astype(np.int32),
    }


def gen_item(n: int, seed: int = 1) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    sk = np.arange(1, n + 1, dtype=np.int64)
    brand_id = rng.integers(1, 1000, n).astype(np.int32)
    cat_id = rng.integers(1, 11, n).astype(np.int32)
    manufact = rng.integers(1, 200, n).astype(np.int32)
    manager = rng.integers(1, 100, n).astype(np.int32)
    price = np.round(rng.uniform(1, 100, n), 2)
    # drawn AFTER the original columns so their RNG stream (and the
    # canonical queries' point predicates) is unchanged
    cls_id = rng.integers(1, 17, n).astype(np.int32)
    return {
        "i_item_sk": sk,
        "i_brand_id": brand_id,
        "i_brand": np.array([f"brand#{b}" for b in brand_id],
                            dtype=object),
        "i_category_id": cat_id,
        "i_category": np.array([f"cat#{c}" for c in cat_id], dtype=object),
        "i_class_id": cls_id,
        "i_class": np.array([f"class#{c}" for c in cls_id], dtype=object),
        "i_manufact_id": manufact,
        "i_manager_id": manager,
        "i_current_price": price,
    }


def gen_customer(n: int, n_addr: int, seed: int = 2):
    rng = np.random.default_rng(seed)
    return {
        "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
        "c_current_addr_sk": rng.integers(1, n_addr + 1,
                                          n).astype(np.int64),
        "c_birth_month": rng.integers(1, 13, n).astype(np.int32),
    }


def gen_customer_address(n: int, seed: int = 3):
    rng = np.random.default_rng(seed)
    return {
        "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
        "ca_gmt_offset": rng.choice([-8.0, -7.0, -6.0, -5.0], n),
        "ca_state": np.array(["CA", "TX", "NY", "WA"],
                             dtype=object)[rng.integers(0, 4, n)],
    }


def gen_store(n: int, seed: int = 4):
    rng = np.random.default_rng(seed)
    return {
        "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
        "s_gmt_offset": rng.choice([-8.0, -7.0, -6.0, -5.0], n),
        "s_state": np.array(["CA", "TX", "NY", "WA"],
                            dtype=object)[rng.integers(0, 4, n)],
    }


def gen_store_sales(n: int, n_dates: int, n_items: int, n_cust: int,
                    n_stores: int, seed: int = 5):
    rng = np.random.default_rng(seed)
    return {
        "ss_sold_date_sk": (2_450_000 + rng.integers(
            0, n_dates, n)).astype(np.int64),
        "ss_item_sk": rng.integers(1, n_items + 1, n).astype(np.int64),
        "ss_customer_sk": rng.integers(1, n_cust + 1, n).astype(np.int64),
        "ss_store_sk": rng.integers(1, n_stores + 1, n).astype(np.int64),
        "ss_quantity": rng.integers(1, 100, n).astype(np.int32),
        "ss_ext_sales_price": np.round(rng.uniform(1, 2000, n), 2),
        "ss_sales_price": np.round(rng.uniform(1, 200, n), 2),
        "ss_net_profit": np.round(rng.uniform(-200, 2000, n), 2),
        "ss_coupon_amt": np.round(rng.uniform(0, 50, n), 2),
        "ss_list_price": np.round(rng.uniform(1, 250, n), 2),
    }


def table_sizes(sf: float) -> Dict[str, int]:
    """Row counts per scale factor — the single sizing source for both
    the loader and test oracles."""
    return {
        "store_sales": max(2000, int(STORE_SALES_ROWS_PER_SF * sf)),
        "item": max(100, int(18_000 * sf)),
        "customer": max(200, int(100_000 * sf)),
        "customer_address": max(100, int(50_000 * sf)),
        "store": max(4, int(12 * max(sf, 1.0))),
    }


def load_tpcds(session, sf: float = 0.001, seed: int = 0,
               partition_sales: bool = False) -> None:
    """Create + populate the TPC-DS star schema at the scale factor."""
    sizes = table_sizes(sf)
    n_ss = sizes["store_sales"]
    n_item = sizes["item"]
    n_cust = sizes["customer"]
    n_addr = sizes["customer_address"]
    n_store = sizes["store"]
    dd = gen_date_dim(seed=seed)
    n_dates = len(dd["d_date_sk"])

    opts = " OPTIONS (partition_by 'ss_item_sk')" if partition_sales \
        else ""
    session.sql(
        "CREATE TABLE store_sales (ss_sold_date_sk BIGINT, "
        "ss_item_sk BIGINT, ss_customer_sk BIGINT, ss_store_sk BIGINT, "
        "ss_quantity INT, ss_ext_sales_price DOUBLE, "
        "ss_sales_price DOUBLE, ss_net_profit DOUBLE, "
        "ss_coupon_amt DOUBLE, ss_list_price DOUBLE) USING column"
        + opts)
    session.sql("CREATE TABLE date_dim (d_date_sk BIGINT, d_year INT, "
                "d_moy INT, d_qoy INT, d_dow INT) USING column")
    session.sql("CREATE TABLE item (i_item_sk BIGINT, i_brand_id INT, "
                "i_brand STRING, i_category_id INT, i_category STRING, "
                "i_class_id INT, i_class STRING, "
                "i_manufact_id INT, i_manager_id INT, "
                "i_current_price DOUBLE) USING column")
    session.sql("CREATE TABLE customer (c_customer_sk BIGINT, "
                "c_current_addr_sk BIGINT, c_birth_month INT) "
                "USING column")
    session.sql("CREATE TABLE customer_address (ca_address_sk BIGINT, "
                "ca_gmt_offset DOUBLE, ca_state STRING) USING column")
    session.sql("CREATE TABLE store (s_store_sk BIGINT, "
                "s_gmt_offset DOUBLE, s_state STRING) USING column")

    session.insert_arrays("date_dim", list(dd.values()))
    session.insert_arrays("item",
                          list(gen_item(n_item, seed + 1).values()))
    session.insert_arrays(
        "customer", list(gen_customer(n_cust, n_addr,
                                      seed + 2).values()))
    session.insert_arrays(
        "customer_address",
        list(gen_customer_address(n_addr, seed + 3).values()))
    session.insert_arrays("store",
                          list(gen_store(n_store, seed + 4).values()))
    session.insert_arrays(
        "store_sales",
        list(gen_store_sales(n_ss, n_dates, n_item, n_cust, n_store,
                             seed + 5).values()))


Q3 = """SELECT d_year, i_brand_id, i_brand,
    sum(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manufact_id = 100 AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id LIMIT 100"""

Q42 = """SELECT d_year, i_category_id, i_category,
    sum(ss_ext_sales_price) AS total
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY total DESC, d_year, i_category_id, i_category LIMIT 100"""

Q52 = """SELECT d_year, i_brand_id, i_brand,
    sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 1 AND d_moy = 11 AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id LIMIT 100"""

Q55 = """SELECT i_brand_id, i_brand,
    sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id = 28 AND d_moy = 11 AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id LIMIT 100"""

# q19's point predicates (manager = 8, one month of one year) select
# ~1 row against the synthetic distributions at test scale; the manager
# range keeps the 6-way join shape while returning a result set
Q19 = """SELECT i_brand_id, i_brand, i_manufact_id,
    sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 8 AND 40 AND d_moy = 11
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ss_store_sk = s_store_sk AND ca_state <> s_state
GROUP BY i_brand_id, i_brand, i_manufact_id
ORDER BY ext_price DESC, i_brand_id LIMIT 100"""

# q6: state-level count of customers buying items priced over 1.2x
# their category average — CORRELATED scalar-aggregate subquery +
# HAVING (month predicates widened to return rows at test scale)
Q6 = """SELECT a.ca_state AS state, count(*) AS cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_year = 2000
  AND i.i_current_price > 1.2 *
      (SELECT avg(j.i_current_price) FROM item j
       WHERE j.i_category = i.i_category)
GROUP BY a.ca_state HAVING count(*) >= 10
ORDER BY cnt, state LIMIT 100"""

# q36: gross-margin reporting over ROLLUP(category, class)
Q36 = """SELECT sum(ss_net_profit) / sum(ss_ext_sales_price)
    AS gross_margin, i_category, i_class
FROM store_sales, date_dim, item, store
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk AND d_year = 2001
  AND s_state IN ('CA', 'TX')
GROUP BY ROLLUP(i_category, i_class)
ORDER BY gross_margin, i_category, i_class LIMIT 100"""

# q98: per-item revenue as a ratio of its class total — a window
# aggregate over the grouped result
Q98 = """SELECT i_item_sk, i_class, itemrevenue,
    itemrevenue * 100.0 / sum(itemrevenue)
        OVER (PARTITION BY i_class) AS revenueratio
FROM (SELECT i_item_sk, i_class,
             sum(ss_ext_sales_price) AS itemrevenue
      FROM store_sales, item, date_dim
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND i_category IN ('cat#1', 'cat#2', 'cat#3')
        AND d_year = 1999 AND d_moy = 2
      GROUP BY i_item_sk, i_class) t
ORDER BY i_class, revenueratio LIMIT 100"""

QUERIES = {"q3": Q3, "q6": Q6, "q19": Q19, "q36": Q36, "q42": Q42,
           "q52": Q52, "q55": Q55, "q98": Q98}
