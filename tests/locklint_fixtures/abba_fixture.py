"""Fixture: the PR 6 ABBA shape, reduced to its skeleton.

Thread A (committer): mutation_lock -> view lock (the fold).
Thread B (stale-view reader, the PRE-fix bug): view lock ->
mutation_lock (refresh inside the read).

tools/locklint must flag the `fixture.view -> fixture.mutation` edge as
undeclared AND report the two-edge cycle with both sites. This module
is analyzed by tests, never imported by the engine."""

import threading

from snappydata_tpu.utils import locks


class Store:
    def __init__(self):
        self.mutation_lock = locks.named_rlock("fixture.mutation")
        self.rows = []

    def commit(self, view: "View", delta):
        # the fold path: mutation -> view
        with self.mutation_lock:
            self.rows.extend(delta)
            view.fold(delta)


class View:
    def __init__(self, store):
        self._lock = threading.Lock()   # also an unnamed-lock finding
        self.store = store
        self.state = 0
        self.stale = True

    def fold(self, delta):
        with self._lock:
            self.state += len(delta)

    def read(self):
        # the PRE-FIX bug: refresh under the view lock takes the
        # mutation lock -> view -> mutation, closing the cycle
        with self._lock:
            if self.stale:
                with self.store.mutation_lock:
                    self.state = len(self.store.rows)
                    self.stale = False
            return self.state
