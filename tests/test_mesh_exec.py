"""Mesh-sharded query execution (engine/mesh_exec.py + parallel/):

* shard_bucket padding contract (mesh-divisible {2^k, 1.5·2^k} ladder)
* sharded-vs-single-device VALUE equivalence for aggregate/join/filter
  shapes — NULL keys, empty shards, non-unique builds, `?` binds
* broadcast-vs-shuffle strategy selection proof (counters + values)
* encoded plates stay resident per device under the mesh
* MVCC pinned scan isolated from concurrent sharded ingest
* live rebalance (kill→rejoin moves buckets) under query traffic
* REST /status/api/v1/mesh + dashboard surface, bench --check guards
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.parallel import MeshContext, data_mesh
from snappydata_tpu.parallel.mesh import shard_bucket
from snappydata_tpu.parallel.placement import ShardPlacement
from snappydata_tpu.storage import mvcc
from snappydata_tpu.utils import tpch

pytestmark = pytest.mark.mesh


def _counters():
    return dict(global_registry().snapshot()["counters"])


def _delta(c0, key):
    return _counters().get(key, 0) - c0.get(key, 0)


def _rows_equal(a, b, rel=1e-9):
    assert len(a) == len(b), (len(a), len(b))
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float) or isinstance(y, float):
                assert abs(float(x) - float(y)) <= \
                    rel * max(1.0, abs(float(x))), (ra, rb)
            else:
                assert x == y, (ra, rb)


# -- padding contract ------------------------------------------------------

def test_shard_bucket_ladder():
    """shard counts must divide the padded batch size AND the result
    stays on the storage ladder, so a resharded table reuses executable
    shapes instead of re-specializing every static key."""
    from snappydata_tpu.storage.device import batch_bucket

    ladder = set()
    n = 1
    while n < 1 << 16:
        ladder.add(batch_bucket(n))
        n += 1
    for nd in (1, 2, 4, 8, 16):
        for n in list(range(1, 70)) + [100, 129, 192, 1000]:
            b = shard_bucket(n, nd)
            assert b >= n and b % nd == 0, (n, nd, b)
            assert b in ladder, (n, nd, b)   # pow2 shard counts: ladder
    # 3·2^k shard counts still land on the ladder's 1.5·2^k rungs
    for n in (1, 5, 7, 16, 100):
        b = shard_bucket(n, 6)
        assert b >= n and b % 6 == 0
        assert b in ladder, (n, b)
    # shard counts the ladder never divides fall back to a multiple
    b = shard_bucket(16, 5)
    assert b >= 16 and b % 5 == 0
    # sanity: the single-device path is the plain ladder
    for n in (1, 3, 5, 100):
        assert shard_bucket(n, 1) == batch_bucket(n)


def test_placement_rebalance_moves_minimum_metadata():
    p = ShardPlacement.balanced(8, 32)
    assert p.num_buckets == 32 and len(set(p.assignment)) == 8
    assert all(p.device_of_bucket(b) == p.assignment[b]
               for b in range(32))
    p2 = p.rebalance(4)
    assert p2.num_devices == 4 and p2.generation > p.generation
    assert p2.moved_from_previous > 0
    assert set(p2.assignment) == set(range(4))
    # bucket→device map is the dashboard surface
    assert p2.bucket_map()[0] == 0


# -- shared tiny workload --------------------------------------------------

@pytest.fixture(scope="module")
def loaded():
    s = SnappySession(catalog=Catalog())
    tpch.load_tpch(s, sf=0.02, seed=11)
    s.sql("CREATE TABLE nk (g BIGINT, grp STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(5)
    g = rng.integers(0, 4, 4000).astype(np.float64)
    g[rng.random(4000) < 0.1] = np.nan   # NULL group keys
    grp = np.array(["a", "b", "c"], dtype=object)[
        rng.integers(0, 3, 4000)]
    v = rng.normal(size=4000)
    nulls = [np.isnan(g), None, None]
    s.catalog.describe("nk").data.insert_arrays(
        [np.nan_to_num(g).astype(np.int64), grp, v], nulls=nulls)
    yield s
    s.stop()


@pytest.fixture(scope="module")
def mesh8():
    """ONE shared 8-device context for the whole module: a fresh
    context per test would rotate the device-cache token (re-upload
    every plate) — the jit caches already share across equal meshes."""
    return MeshContext(data_mesh(8))


def _mesh_vs_single(s, ctx, q, params=()):
    single = s.sql(q, params=params).rows() if params \
        else s.sql(q).rows()
    with ctx:
        mesh = s.sql(q, params=params).rows() if params \
            else s.sql(q).rows()
    _rows_equal(single, mesh)
    return mesh


def test_mesh_q1_q6_value_equivalence_and_lane_evidence(loaded, mesh8):
    c0 = _counters()
    _mesh_vs_single(loaded, mesh8, tpch.Q1)
    _mesh_vs_single(loaded, mesh8, tpch.Q6)
    assert _delta(c0, "mesh_shard_execs") >= 2
    assert _delta(c0, "mesh_psum_merges") >= 3


def test_mesh_aggregate_shapes(loaded, mesh8):
    # min/max families, HAVING, WHERE, avg — all through the psum/pmin/
    # pmax merge tree; NULL group keys ride the nk table
    _mesh_vs_single(loaded, mesh8, (
        "SELECT l_returnflag, min(l_quantity), max(l_extendedprice), "
        "avg(l_discount), count(*) FROM lineitem "
        "WHERE l_shipdate > DATE '1994-01-01' "
        "GROUP BY l_returnflag HAVING count(*) > 10 "
        "ORDER BY l_returnflag"))


def test_mesh_null_group_keys(loaded, mesh8):
    _mesh_vs_single(loaded, mesh8, (
        "SELECT g, grp, count(*), sum(v) FROM nk "
        "GROUP BY g, grp ORDER BY g, grp"))


def test_mesh_empty_shards(loaded, mesh8):
    """A table with fewer batches than devices: some shards see only
    dead padded batches — identity partials must merge away."""
    s = loaded
    s.sql("CREATE TABLE tiny (k BIGINT, v DOUBLE) USING column")
    s.insert_arrays("tiny", [np.arange(50, dtype=np.int64),
                             np.arange(50, dtype=np.float64)])
    _mesh_vs_single(s, mesh8, "SELECT k % 3, sum(v), count(*) FROM tiny "
                              "GROUP BY k % 3 ORDER BY 1")


def test_mesh_param_binds_stay_correct(loaded, mesh8):
    """`?` binds decline the partial lane (counted) but stay sharded
    and value-correct through the GSPMD lane."""
    s = loaded
    c0 = _counters()
    single = s.sql("SELECT count(*), sum(l_quantity) FROM lineitem "
                   "WHERE l_quantity < ?", params=(25,)).rows()
    with mesh8:
        mesh = s.sql("SELECT count(*), sum(l_quantity) FROM lineitem "
                     "WHERE l_quantity < ?", params=(25,)).rows()
    _rows_equal(single, mesh)
    assert _delta(c0, "mesh_fallback_params") >= 1


def test_mesh_encoded_plates_resident_per_device(loaded, mesh8):
    """Sharded tables keep plates ENCODED per device: the CodePlate
    leaves shard over the mesh and per-device resident bytes stay at
    the encoded size (no decode-on-shard regression)."""
    from snappydata_tpu.storage.device import (
        build_device_table, device_cache_bytes_by_device)
    from snappydata_tpu.storage.device_decode import CodePlate

    s = loaded
    info = s.catalog.lookup_table("lineitem")
    info.data._device_cache.clear()
    with mesh8 as ctx:
        dt = build_device_table(info.data, None, [4])  # l_quantity
        col = dt.columns[4]
        assert isinstance(col, CodePlate), type(col)
        assert len(col.codes.sharding.device_set) == 8
        assert col.codes.shape[0] % 8 == 0
        per_dev = device_cache_bytes_by_device(
            [("lineitem", info.data)])
        assert len(per_dev) == 8
        total = sum(per_dev.values())
        decoded = dt.valid.size * 8   # the f64 plate that never existed
        assert total < decoded, (total, decoded)
        # evenly spread: no device holds the whole column
        assert max(per_dev.values()) < total


# -- join distribution strategies -----------------------------------------

def test_join_broadcast_default_and_shuffle_forced(loaded, mesh8):
    """Q3C (non-unique build side): AUTO picks broadcast-build under
    the byte threshold; forcing shuffle exchanges both sides
    bucket-wise — both strategies value-identical, both counted, and
    the shuffle exchange is cached across executions.  A tiny
    mesh_broadcast_build_bytes then proves AUTO flips to shuffle."""
    s = loaded
    single = s.sql(tpch.Q3C).rows()
    props = config.global_properties()
    c0 = _counters()
    with mesh8:
        _rows_equal(single, s.sql(tpch.Q3C).rows())
    assert _delta(c0, "mesh_join_broadcast") >= 1
    assert _delta(c0, "mesh_join_shuffle") == 0
    old = props.get("mesh_join_strategy")
    try:
        props.set("mesh_join_strategy", "shuffle")
        c1 = _counters()
        with mesh8:
            _rows_equal(single, s.sql(tpch.Q3C).rows())
            _rows_equal(single, s.sql(tpch.Q3C).rows())
        assert _delta(c1, "mesh_join_shuffle") >= 2
        assert _delta(c1, "mesh_exchange_bytes") > 0
        assert _delta(c1, "mesh_exchange_rows") > 0
        assert _delta(c1, "mesh_exchange_cache_hits") >= 1
    finally:
        props.set("mesh_join_strategy", old)
    # AUTO past the broadcast budget: selection flips per bind, no
    # knob-flush needed (the shuffle specialization rides a static)
    old_b = props.get("mesh_broadcast_build_bytes")
    try:
        props.set("mesh_broadcast_build_bytes", 1)  # everything is big
        c2 = _counters()
        with mesh8:
            _rows_equal(single, s.sql(tpch.Q3C).rows())
        assert _delta(c2, "mesh_join_shuffle") >= 1
        assert _delta(c2, "mesh_join_broadcast") == 0
    finally:
        props.set("mesh_broadcast_build_bytes", old_b)


def test_shuffle_ineligible_declines_to_broadcast(loaded, mesh8):
    """A multi-join tree can't shuffle on ONE key — the decline is
    itemized by reason (like the join engine's fallback reasons) and
    the query still answers correctly via broadcast."""
    s = loaded
    q = ("SELECT o_orderpriority, count(*) FROM orders "
         "JOIN lineitem ON o_orderkey = l_orderkey "
         "JOIN customer ON o_custkey = c_custkey "
         "GROUP BY o_orderpriority ORDER BY o_orderpriority")
    props = config.global_properties()
    single = s.sql(q).rows()
    old = props.get("mesh_join_strategy")
    try:
        props.set("mesh_join_strategy", "shuffle")
        c0 = _counters()
        with mesh8:
            _rows_equal(single, s.sql(q).rows())
        fallbacks = {k: v for k, v in _counters().items()
                     if k.startswith("mesh_join_shuffle_fallback_")
                     and v > c0.get(k, 0)}
        assert fallbacks, "expected an itemized shuffle decline"
    finally:
        props.set("mesh_join_strategy", old)


# -- MVCC × mesh -----------------------------------------------------------

def test_mesh_pinned_scan_isolated_from_sharded_ingest(loaded, mesh8):
    """A pinned statement scope under the mesh reads its epoch while a
    concurrent writer ingests into the SHARDED table — repeatable
    reads, then the new rows appear after release."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE mt (k BIGINT, v DOUBLE) USING column")
    s.insert_arrays("mt", [np.arange(1000, dtype=np.int64),
                           np.ones(1000)])
    with mesh8:
        with mvcc.pinned_scope(s.catalog, ["mt"]) as pin:
            assert pin is not None
            before = s.sql("SELECT count(*), sum(v) FROM mt").rows()
            assert before == [(1000, 1000.0)]
            done = []

            def ingest():
                w = SnappySession(catalog=s.catalog)
                w.insert_arrays("mt", [np.arange(500, dtype=np.int64),
                                       np.full(500, 2.0)])
                done.append(True)

            th = threading.Thread(target=ingest)
            th.start()
            th.join(timeout=30)
            assert done, "sharded ingest blocked behind a pinned reader"
            # the pinned statement still reads its epoch
            assert s.sql("SELECT count(*), sum(v) FROM mt").rows() \
                == [(1000, 1000.0)]
        # release → the concurrent commit is visible
        assert s.sql("SELECT count(*), sum(v) FROM mt").rows() \
            == [(1500, 2000.0)]
    s.stop()


# -- live rebalance --------------------------------------------------------

def test_rebalance_under_traffic(loaded):
    """Kill→rejoin as a mesh resize: buckets move, resident plates
    migrate device-to-device, and every in-flight query stays
    value-correct throughout."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE rt (k BIGINT, v DOUBLE) USING column")
    n = 20_000
    s.insert_arrays("rt", [np.arange(n, dtype=np.int64),
                           np.arange(n, dtype=np.float64)])
    expect = s.sql("SELECT k % 7, count(*), sum(v) FROM rt "
                   "GROUP BY k % 7 ORDER BY 1").rows()
    s.default_mesh = data_mesh(8)
    # COLD resize — no mesh query has run, _mesh_ctx is None: the miss
    # path must not re-acquire the non-reentrant resize lock (review
    # finding: it self-deadlocked; under lockdep it raises instead)
    assert s.resize_mesh(8)["num_devices"] == 8
    s.sql("SELECT count(*) FROM rt")   # warm the mesh cache
    errors = []
    stop = threading.Event()

    def reader():
        w = SnappySession(catalog=s.catalog)
        w.default_mesh = s.default_mesh
        w._mesh_ctx = s._mesh_ctx
        while not stop.is_set():
            try:
                got = w.sql("SELECT k % 7, count(*), sum(v) FROM rt "
                            "GROUP BY k % 7 ORDER BY 1").rows()
                _rows_equal(expect, got)
                # the resize swaps the session's mesh mid-traffic
                w.default_mesh = s.default_mesh
                w._mesh_ctx = s._mesh_ctx
            except Exception as e:  # noqa: BLE001 — collected, asserted
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        c0 = _counters()
        down = s.resize_mesh(4)    # "kill": half the devices leave
        assert down["num_devices"] == 4 and down["buckets_moved"] > 0
        for _ in range(3):
            got = s.sql("SELECT k % 7, count(*), sum(v) FROM rt "
                        "GROUP BY k % 7 ORDER BY 1").rows()
            _rows_equal(expect, got)
        up = s.resize_mesh(8)      # "rejoin": they come back
        assert up["num_devices"] == 8 and up["buckets_moved"] > 0
        for _ in range(3):
            got = s.sql("SELECT k % 7, count(*), sum(v) FROM rt "
                        "GROUP BY k % 7 ORDER BY 1").rows()
            _rows_equal(expect, got)
        assert _delta(c0, "mesh_rebalances") == 2
        # resident plates MIGRATED instead of rebuilding from host
        assert down["cache_entries_moved"] > 0
        assert down["bytes_moved"] > 0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    s.executor.clear_cache()
    s.stop()


# -- surfaces --------------------------------------------------------------

def test_mesh_snapshot_and_rest_surface(loaded, mesh8):
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import (
        TableStatsService, mesh_snapshot)

    s = loaded
    with mesh8:
        s.sql(tpch.Q6)
        snap = mesh_snapshot(s.catalog, s)
        assert snap["active"] and snap["num_devices"] == 8
        assert snap["mesh_shard_execs"] >= 1
        assert snap["placement"]["bucket_map"]
        assert snap["resident_bytes_by_device"]
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    base = f"http://{svc.host}:{svc.port}"
    try:
        with urllib.request.urlopen(base + "/status/api/v1/mesh",
                                    timeout=5) as resp:
            body = json.loads(resp.read())
        assert "mesh_shard_execs" in body
        assert "mesh_join_strategy" in body
        with urllib.request.urlopen(base + "/dashboard",
                                    timeout=5) as resp:
            html = resp.read().decode()
        assert "Mesh execution" in html
    finally:
        svc.stop()


def test_bench_mesh_guard_logic():
    import bench

    def rec(mc):
        return {"value": 1e6, "detail": {"multichip": mc}}

    good = {"value_mismatches": 0, "mesh_shard_execs": 8,
            "scaling_efficiency": {"2": 0.95, "4": 0.9, "8": 0.85},
            "resident_bytes_per_row_single": 25.0,
            "resident_bytes_per_row_sharded": 26.0}
    assert bench.check_regression(rec(good), rec(good)) == []
    # pre-mesh records (no multichip section) skip the guards
    assert bench.check_regression(
        {"value": 1e6, "detail": {}}, {"value": 1e6, "detail": {}}) == []
    bad = dict(good, value_mismatches=3)
    assert any("diverged" in f for f in
               bench.check_regression(rec(bad), rec(good)))
    bad = dict(good, scaling_efficiency={"2": 1.0, "4": 1.0, "8": 0.4})
    assert any("efficiency" in f for f in
               bench.check_regression(rec(bad), rec(good)))
    bad = dict(good, mesh_shard_execs=0)
    assert any("shard_map" in f for f in
               bench.check_regression(rec(bad), rec(good)))
    bad = dict(good, resident_bytes_per_row_sharded=60.0)
    assert any("encoded" in f for f in
               bench.check_regression(rec(bad), rec(good)))
