"""REST surface on the lead: status API, metrics, job submission.

Reference: `/status/api/v1` JSON resources (cluster/.../status/api/v1/
snappyapi.scala), MetricsServlet at lead:5050/metrics/json
(docs/monitoring/metrics.md:8), and the spark-jobserver REST contract
(SnappySQLJob.runSnappyJob, cluster/.../SnappySessionFactory.scala:112-136).
"""

from __future__ import annotations

import json
import threading
from snappydata_tpu.utils import locks
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from snappydata_tpu.observability.metrics import global_registry


class JobRegistry:
    """Async SQL jobs (the jobserver analogue): submit → job id → poll."""

    def __init__(self, session):
        self.session = session
        self._jobs: Dict[str, dict] = {}
        self._lock = locks.named_lock("rest.jobs")

    def submit_sql(self, sql: str, params=(), session=None,
                   timeout_s=None) -> str:
        from snappydata_tpu import resource

        job_id = uuid.uuid4().hex[:12]
        sess = session or self.session
        # the job's governor context is created AND registered up front
        # so its queryId is visible (GET /jobs/<id>) and cancellable
        # (POST /queries/<qid>/cancel) from the moment of submission —
        # even before the worker thread reaches admission
        ctx = resource.global_broker().watch(
            resource.new_query(sql, user=sess.user))
        if timeout_s:
            # per-request deadline counts from SUBMISSION (queue time
            # included, like query_timeout_s)
            ctx.set_deadline_in(float(timeout_s))
        with self._lock:
            self._jobs[job_id] = {"status": "RUNNING", "sql": sql,
                                  "queryId": ctx.query_id}

        def run():
            from snappydata_tpu.observability import tracing

            try:
                with tracing.request_scope(sql, user=sess.user,
                                           kind="job") as tr:
                    if tr is not None:
                        with self._lock:
                            self._jobs[job_id]["trace_id"] = tr.trace_id
                    result = sess.sql(sql, params=params, query_ctx=ctx)
                with self._lock:
                    self._jobs[job_id].update(
                        status="FINISHED",
                        rows=[[_j(v) for v in r] for r in
                              result.rows()[:1000]],
                        names=result.names)
            except Exception as e:
                with self._lock:
                    self._jobs[job_id].update(status="ERROR", error=str(e))
            finally:
                # idempotent: clears the watched registration even when
                # the statement failed before reaching admission (parse
                # errors included)
                resource.global_broker().release(ctx)

        threading.Thread(target=run, daemon=True).start()
        return job_id

    def status(self, job_id: str) -> Optional[dict]:
        with self._lock:
            return dict(self._jobs.get(job_id) or {}) or None

    def list(self) -> dict:
        with self._lock:
            return {jid: j["status"] for jid, j in self._jobs.items()}


def _j(v):
    if v is None or isinstance(v, (int, float, str, bool)):
        return v
    return str(v)


def _render_dashboard(svc) -> str:
    """Minimal HTML dashboard (ref: SnappyDashboardPage cluster overview +
    member grid + table stats)."""
    from html import escape as esc

    members = []
    if svc.membership is not None:
        try:
            members = svc.membership.members()
        except Exception:
            members = []
    tables = svc.stats_service.current()
    snap = global_registry().snapshot()
    rows_m = "".join(
        f"<tr><td>{esc(str(m.role))}</td><td>{esc(str(m.member_id))}</td>"
        f"<td>{esc(str(m.host))}:{m.port}</td></tr>" for m in members)
    rows_t = "".join(
        f"<tr><td>{esc(str(name))}</td><td>{esc(str(t['provider']))}</td>"
        f"<td>{t['row_count']:,}</td><td>{t['batches']}</td>"
        f"<td>{t['in_memory_bytes']:,}</td></tr>"
        for name, t in sorted(tables.items()))
    counters = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{v}</td></tr>"
        for k, v in sorted(snap["counters"].items()))
    from snappydata_tpu.observability.stats_service import (
        durability_snapshot, ha_snapshot, join_snapshot, scan_snapshot)

    ha = ha_snapshot(svc.session.catalog, svc.distributed)
    rows_ha = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in ha.items())
    wal = durability_snapshot()
    rows_w = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in wal.items() if not isinstance(v, dict)) + (
        f"<tr><td>wal_group_flush_ms (mean/max)</td>"
        f"<td>{wal['wal_group_flush_ms']['mean_ms']} / "
        f"{wal['wal_group_flush_ms']['max_ms']}</td></tr>")
    agg = scan_snapshot(svc.session.catalog)
    enc_tables = agg.pop("tables", {})
    rows_agg = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in agg.items())
    rows_enc = "".join(
        f"<tr><td>{esc(str(name))}</td><td>{t['rows']:,}</td>"
        f"<td>{esc(str(t['encoding_mix']))}</td>"
        f"<td>{t['at_rest_bytes']:,}</td><td>{t['decoded_bytes']:,}</td>"
        f"<td>{esc(str(t['at_rest_ratio']))}</td>"
        f"<td>{t['device_resident_bytes']:,}</td>"
        f"<td>{esc(str(t['resident_bytes_per_row']))}</td></tr>"
        for name, t in sorted(enc_tables.items()))
    jn = join_snapshot()
    rows_jn = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in jn.items())
    from snappydata_tpu.observability.stats_service import mesh_snapshot

    msh = mesh_snapshot(svc.session.catalog, svc.session)
    mesh_placement = msh.pop("placement", None)
    mesh_perdev = msh.pop("resident_bytes_by_device", {})
    rows_msh = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in msh.items())
    if mesh_placement is not None:
        rows_msh += (
            f"<tr><td>placement (gen "
            f"{mesh_placement['generation']}, "
            f"{mesh_placement['num_buckets']} buckets)</td>"
            f"<td>{esc(str(mesh_placement['bucket_map']))}</td></tr>")
    rows_mshd = "".join(
        f"<tr><td>{esc(str(d))}</td><td>{b:,}</td></tr>"
        for d, b in mesh_perdev.items())
    from snappydata_tpu.views import view_snapshot

    mv = view_snapshot(svc.session.catalog)
    rows_mv = "".join(
        f"<tr><td>{esc(str(v['name']))}</td>"
        f"<td>{esc(str(v['base_table']))}</td><td>{v['groups']:,}</td>"
        f"<td>{v['state_bytes']:,}</td>"
        f"<td>{'STALE' if v['stale'] else 'fresh'}</td>"
        f"<td>{v['delta_folds']}</td><td>{v['rows_folded']:,}</td>"
        f"<td>{v['full_refreshes']}</td></tr>"
        for v in mv["views"])
    rows_mvc = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in mv.items() if k != "views")
    from snappydata_tpu.observability.stats_service import storage_snapshot

    stg = storage_snapshot()
    rows_stg = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in stg["tier"].items()) + "".join(
        f"<tr><td>prefetch {esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in stg["prefetch"].items()) + (
        f"<tr><td>failpoint fires</td>"
        f"<td>{stg['failpoints']['fires']} "
        f"({len(stg['failpoints']['armed'])} armed)</td></tr>")
    from snappydata_tpu.observability.stats_service import mvcc_snapshot

    mvc = mvcc_snapshot(svc.session.catalog)
    mvcc_tables = mvc.pop("tables", {})
    rows_mvcc = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in mvc.items())
    rows_mvcct = "".join(
        f"<tr><td>{esc(str(name))}</td><td>{t['version']}</td>"
        f"<td>{t['epoch']}</td><td>{t['wal_seq']}</td>"
        f"<td>{len(t['retained_epochs'])}</td>"
        f"<td>{sum(e['pins'] for e in t['retained_epochs'])}</td>"
        f"<td>{t['retained_bytes']:,}</td></tr>"
        for name, t in sorted(mvcc_tables.items()))
    from snappydata_tpu.serving import serving_snapshot

    sv = serving_snapshot(svc.session.catalog)
    rows_sv = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in sv.items() if k != "handles")
    rows_svh = "".join(
        f"<tr><td>{esc(str(h['sql']))}</td><td>{h['params']}</td>"
        f"<td>{h['executes']}</td>"
        f"<td>{esc(str(h['passthrough'] or 'compiled'))}</td></tr>"
        for h in sv.get("handles", ()))
    recent = list(reversed(svc.session.recent_queries()))[:25]
    rows_q = "".join(
        f"<tr><td>{esc(str(q['sql']))[:120]}</td><td>{q['ms']}</td>"
        f"<td>{q['rows']}</td><td>{esc(str(q.get('user', '')))}</td></tr>"
        for q in recent)
    from snappydata_tpu.observability.tracing import (ring,
                                                      tracing_snapshot)

    trc = tracing_snapshot()
    rows_trc = "".join(
        f"<tr><td>{esc(str(k))}</td><td>{esc(str(v))}</td></tr>"
        for k, v in trc.items())
    rows_trq = "".join(
        f"<tr><td><code>{esc(str(t['trace_id']))}</code></td>"
        f"<td>{esc(str(t['kind']))}</td>"
        f"<td>{esc(str(t['sql']))[:100]}</td><td>{t['ms']}</td>"
        f"<td>{t['spans']}</td><td>{esc(str(t['status']))}</td></tr>"
        for t in ring().traces(15))
    streams = svc.session.streaming_queries()
    rows_s = "".join(
        f"<tr><td>{esc(str(q['name']))}</td><td>{esc(str(q['table']))}</td>"
        f"<td>{'yes' if q['active'] else 'NO'}</td>"
        f"<td>{q['batches_processed']}</td><td>{q['rows_processed']:,}</td>"
        f"<td>{q['rows_per_s']:,}</td>"
        f"<td>{esc(str(q['last_error'] or ''))[:80]}</td></tr>"
        for q in streams)
    return f"""<!doctype html><html><head><title>snappydata_tpu</title>
<style>body{{font-family:sans-serif;margin:2em}}table{{border-collapse:
collapse;margin:1em 0}}td,th{{border:1px solid #ccc;padding:4px 10px;
text-align:left}}h2{{margin-top:1.5em}}</style></head><body>
<h1>snappydata_tpu cluster</h1>
<h2>Members ({len(members)})</h2>
<table><tr><th>role</th><th>member</th><th>address</th></tr>{rows_m}</table>
<h2>Tables ({len(tables)})</h2>
<table><tr><th>table</th><th>provider</th><th>rows</th><th>batches</th>
<th>bytes</th></tr>{rows_t}</table>
<h2>Streaming queries ({len(streams)})</h2>
<table><tr><th>query</th><th>table</th><th>active</th><th>batches</th>
<th>rows</th><th>rows/s</th><th>last error</th></tr>{rows_s}</table>
<h2>High availability (deadlines / hedges / dedup / rejoin)</h2>
<table>{rows_ha}</table>
<h2>Durability (WAL group commit)</h2><table>{rows_w}</table>
<h2>Scan &amp; decode (compressed domain / Aggregation engine /
tiled scans)</h2>
<table>{rows_agg}</table>
<table><tr><th>table</th><th>rows</th><th>encoding mix</th>
<th>at-rest bytes</th><th>decoded bytes</th><th>at-rest ratio</th>
<th>device resident</th><th>resident B/row</th></tr>{rows_enc}</table>
<h2>Join engine (device path / build cache / expansion)</h2>
<table>{rows_jn}</table>
<h2>Mesh execution (shard_map lane / exchange / placement)</h2>
<table>{rows_msh}</table>
<table><tr><th>device</th><th>resident bytes</th></tr>{rows_mshd}</table>
<h2>Serving path (prepared statements / micro-batched dispatch)</h2>
<table>{rows_sv}</table>
<table><tr><th>prepared sql</th><th>params</th><th>executes</th>
<th>mode</th></tr>{rows_svh}</table>
<h2>Storage (tier ladder / self-healing / prefetch workers)</h2>
<table>{rows_stg}</table>
<h2>Snapshot isolation (MVCC epochs / pins / retained bytes)</h2>
<table>{rows_mvcc}</table>
<table><tr><th>table</th><th>version</th><th>epoch</th><th>commit seq</th>
<th>retained epochs</th><th>pins</th><th>retained bytes</th></tr>
{rows_mvcct}</table>
<h2>Materialized views ({len(mv["views"])})</h2>
<table><tr><th>view</th><th>base</th><th>groups</th><th>state bytes</th>
<th>freshness</th><th>delta folds</th><th>rows folded</th>
<th>full refreshes</th></tr>{rows_mv}</table>
<table>{rows_mvc}</table>
<h2>Tracing (trace ring / slow-query log)</h2>
<table>{rows_trc}</table>
<table><tr><th>trace id</th><th>kind</th><th>sql</th><th>ms</th>
<th>spans</th><th>status</th></tr>{rows_trq}</table>
<p>Detail: GET /status/api/v1/traces?trace_id=&lt;id&gt;</p>
<h2>Counters</h2><table>{counters}</table>
<h2>Recent queries ({len(recent)})</h2>
<table><tr><th>sql</th><th>ms</th><th>rows</th><th>user</th></tr>{rows_q}
</table>
<p>Plans: GET /status/api/v1/queries/plan?id=N</p>
</body></html>"""


class RestService:
    # validated Basic credentials are cached this long, bounding both the
    # per-request provider cost (LDAP bind) and the revocation latency
    BASIC_CACHE_TTL_S = 300.0

    def __init__(self, session, stats_service, membership=None,
                 host: str = "127.0.0.1", port: int = 0,
                 auth_tokens=None, auth_provider=None):
        """`auth_tokens`: token → user map. When configured, job submission
        requires `Authorization: Bearer <token>` (or `X-Snappy-Token`) and
        runs as that principal; when absent, jobs run as an unauthenticated
        remote session (EXEC PYTHON refused — advisor finding: the job
        endpoint used to execute arbitrary SQL as the admin superuser).
        `auth_provider` (BUILTIN/LDAP) additionally accepts
        `Authorization: Basic <user:password>` credentials; validated
        principals are cached so LDAP isn't bound per request."""
        self.session = session
        self.stats_service = stats_service
        self.membership = membership
        # optional DistributedSession (the lead's cluster view) — powers
        # operator actions like POST /rebalance
        self.distributed = None
        self.auth_tokens = auth_tokens or {}
        self.auth_provider = auth_provider
        self._basic_cache = {}   # sha256(user:password) -> (user, expiry)
        self.jobs = JobRegistry(session)
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _send(self, payload, code=200, content_type="application/json"):
                body = payload if isinstance(payload, bytes) else \
                    json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/")
                if path == "/status/api/v1/cluster":
                    members = []
                    if svc.membership is not None:
                        try:
                            members = [vars(m) for m in
                                       svc.membership.members()]
                        except Exception:
                            members = []
                    self._send({"members": members,
                                "tables": svc.stats_service.current()})
                elif path == "/status/api/v1/tables":
                    self._send(svc.stats_service.current())
                elif path == "/status/api/v1/ha":
                    # end-to-end reliability stats: failovers, hedged
                    # reads, mutation-retry dedup, rejoins, deadline
                    # expiries, heartbeat health — plus live membership
                    # and bucket-redundancy state when this lead holds a
                    # cluster view
                    from snappydata_tpu.observability.stats_service import \
                        ha_snapshot

                    self._send(ha_snapshot(svc.session.catalog,
                                           svc.distributed))
                elif path == "/status/api/v1/wal":
                    # group-commit write-path stats: fsync mode/knobs +
                    # wal_fsync_count / wal_group_commit_batches /
                    # wal_bytes_written / flush timings
                    from snappydata_tpu.observability.stats_service import \
                        durability_snapshot

                    self._send(durability_snapshot())
                elif path == "/status/api/v1/scan":
                    # scan read-path stats: reduction strategies,
                    # fused-pass counts, group-index cache hit rate,
                    # tiled-scan device merges, and the compressed-domain
                    # block (code/run predicates, dictionary batch
                    # skipping, per-reason fallbacks, per-table encoding
                    # mix + at-rest vs decoded bytes)
                    from snappydata_tpu.observability.stats_service import \
                        scan_snapshot

                    self._send(scan_snapshot(svc.session.catalog))
                elif path == "/status/api/v1/join":
                    # join-engine stats: device vs host-path counts (host
                    # fallbacks itemized by reason), build-artifact cache
                    # hit rate/bytes, one-to-many expansion factor
                    from snappydata_tpu.observability.stats_service import \
                        join_snapshot

                    self._send(join_snapshot())
                elif path == "/status/api/v1/serving":
                    # prepared-statement serving stats: registry
                    # population + compile-once and batched-dispatch
                    # evidence counters (handle SQL text leaks literals →
                    # same auth as /queries)
                    if self._principal_session() is None:
                        return
                    from snappydata_tpu.serving import serving_snapshot

                    self._send(serving_snapshot(svc.session.catalog))
                elif path == "/status/api/v1/views":
                    # materialized-view stats: per-view state size /
                    # staleness / fold counters + the global fold totals
                    # proving O(delta) maintenance (view definitions leak
                    # SQL text → same auth as /queries)
                    if self._principal_session() is None:
                        return
                    from snappydata_tpu.views import view_snapshot

                    self._send(view_snapshot(svc.session.catalog))
                elif path == "/status/api/v1/storage":
                    # tiered-storage health: per-rung resident bytes,
                    # quarantine/rebuild ledger, prefetch-worker
                    # liveness, armed failpoints — the self-healing
                    # story as numbers
                    from snappydata_tpu.observability.stats_service import \
                        storage_snapshot

                    self._send(storage_snapshot())
                elif path == "/status/api/v1/mvcc":
                    # snapshot-isolation stats: epoch clock, active pins,
                    # per-table version vector + retained-epoch list and
                    # bytes — what readers can rely on, as numbers
                    from snappydata_tpu.observability.stats_service import \
                        mvcc_snapshot

                    self._send(mvcc_snapshot(svc.session.catalog))
                elif path == "/status/api/v1/mesh":
                    # mesh execution: shard_map lane counters, join
                    # distribution strategies, bucket→device placement,
                    # per-device resident plate bytes
                    from snappydata_tpu.observability.stats_service import \
                        mesh_snapshot

                    self._send(mesh_snapshot(svc.session.catalog,
                                             svc.session))
                elif path == "/status/api/v1/streaming":
                    # streaming query progress (ref: the structured-
                    # streaming UI tab / StreamingQueryManager.active);
                    # last_error may embed SQL/data → same auth as /queries
                    if self._principal_session() is None:
                        return
                    self._send(svc.session.streaming_queries())
                elif path == "/status/api/v1/traces":
                    # request-trace ring: recent completed traces
                    # (summaries), `?trace_id=` for full span trees of
                    # every local trace under that id, `?slow=1` for the
                    # slow-query log. Trace SQL leaks literals → same
                    # auth gate as /queries.
                    if self._principal_session() is None:
                        return
                    from urllib.parse import parse_qs, urlparse

                    from snappydata_tpu.observability.tracing import (
                        ring, tracing_snapshot)

                    q = parse_qs(urlparse(self.path).query)
                    tid = q.get("trace_id", [None])[0]
                    if tid:
                        self._send({"trace_id": tid,
                                    "traces": ring().get(tid)})
                        return
                    out = tracing_snapshot()
                    if q.get("slow", ["0"])[0] in ("1", "true"):
                        out["slow"] = ring().slow()
                    else:
                        try:
                            limit = int(q.get("limit", ["50"])[0])
                        except (TypeError, ValueError):
                            limit = 50
                        out["traces"] = ring().traces(limit)
                    self._send(out)
                elif path == "/status/api/v1/queries":
                    # query text leaks literals: same auth as /jobs
                    if self._principal_session() is None:
                        return
                    self._send(svc.session.recent_queries())
                elif path.startswith("/status/api/v1/queries/plan"):
                    # live plan view: EXPLAIN of a logged query on demand,
                    # under the REQUEST principal so table privileges apply
                    sess = self._principal_session()
                    if sess is None:
                        return
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        qid = int(q.get("id", q.get("idx", ["-1"]))[0])
                    except (TypeError, ValueError):
                        self._send({"error": "id must be an integer"}, 400)
                        return
                    entry = next((e for e in svc.session.recent_queries()
                                  if e["id"] == qid), None)
                    if entry is None:
                        self._send({"error": "no such query"}, 404)
                        return
                    try:
                        plan = sess.sql("EXPLAIN " + entry["sql"])
                        self._send({"sql": entry["sql"],
                                    "plan": [r[0] for r in plan.rows()]})
                    except Exception as e:  # noqa: BLE001
                        self._send({"error": str(e)}, 500)
                elif path == "/queries":
                    # live governed queries (running + queued) from the
                    # resource broker — query text leaks literals, so
                    # the same auth gate as /jobs applies
                    if self._principal_session() is None:
                        return
                    from snappydata_tpu import resource

                    self._send(resource.global_broker().queries())
                elif path == "/queries/ledger":
                    if self._principal_session() is None:
                        return
                    from snappydata_tpu import resource

                    self._send(resource.global_broker().ledger())
                elif path == "/faults":
                    # fault-injection surface (chaos tooling): armed
                    # failpoints + fire counts. Same admin gate as the
                    # POST side — fault state reveals operational detail
                    if self._admin_session("fault state") is None:
                        return
                    from snappydata_tpu.fault import failpoints

                    self._send({
                        "faults": failpoints.registry().list(),
                        "injected":
                            global_registry().counter("fault_injected")})
                elif path == "/metrics/json":
                    self._send(global_registry().snapshot())
                elif path == "/metrics/prometheus":
                    self._send(global_registry().to_prometheus().encode(),
                               content_type="text/plain")
                elif path in ("", "/dashboard"):
                    # shows recent query text → token-gated when auth on
                    if (svc.auth_tokens or svc.auth_provider is not None) \
                            and self._principal_session() is None:
                        return
                    self._send(_render_dashboard(svc).encode(),
                               content_type="text/html")
                elif path.startswith("/jobs/"):
                    # job results carry query rows: same auth as submission
                    if self._principal_session() is None:
                        return
                    st = svc.jobs.status(path.split("/")[-1])
                    self._send(st if st else {"error": "no such job"},
                               200 if st else 404)
                elif path == "/jobs":
                    if self._principal_session() is None:
                        return
                    self._send(svc.jobs.list())
                else:
                    self._send({"error": "not found"}, 404)

            def _admin_session(self, action_desc):
                """Operator-action gate: resolved principal, admin-only
                when auth is configured; None → 401/403 already sent."""
                sess = self._principal_session()
                if sess is None:
                    return None
                if (svc.auth_tokens or svc.auth_provider) and \
                        sess.user != "admin":
                    self._send({"error": f"{action_desc} requires "
                                         f"admin"}, 403)
                    return None
                return sess

            def _principal_session(self):
                """Resolve the request principal; None → 401 already sent."""
                auth = self.headers.get("Authorization", "")
                token = self.headers.get("X-Snappy-Token")
                if token is None and auth.startswith("Bearer "):
                    token = auth[len("Bearer "):]
                if not svc.auth_tokens and svc.auth_provider is None:
                    return svc.session.for_user(svc.session.user,
                                                authenticated=False)
                user = svc.auth_tokens.get(token) if token else None
                if user is None and svc.auth_provider is not None \
                        and auth.startswith("Basic "):
                    import base64
                    import hashlib
                    import time as _t
                    try:
                        raw = base64.b64decode(auth[len("Basic "):],
                                               validate=True)
                        u, _, p = raw.decode("utf-8").partition(":")
                    except Exception:
                        raw, u, p = b"", "", ""
                    digest = hashlib.sha256(raw).hexdigest()
                    now = _t.time()
                    cached = svc._basic_cache.get(digest)
                    if cached is not None and cached[0] == u \
                            and cached[1] > now:
                        user = u
                    elif u and p and svc.auth_provider.authenticate(u, p):
                        # short TTL: a revoked/changed credential stops
                        # working within BASIC_CACHE_TTL_S, not never
                        svc._basic_cache[digest] = (
                            u, now + svc.BASIC_CACHE_TTL_S)
                        user = u
                if user is None:
                    self._send({"error": "missing or invalid "
                                         "token/credentials"}, 401)
                    return None
                return svc.session.for_user(user, authenticated=True)

            def do_POST(self):
                path = self.path.rstrip("/")
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if path == "/jobs":
                    sess = self._principal_session()
                    if sess is None:
                        return
                    job_id = svc.jobs.submit_sql(
                        body["sql"], tuple(body.get("params", ())),
                        session=sess, timeout_s=body.get("timeout_s"))
                    self._send({"jobId": job_id, "status": "STARTED"})
                elif path == "/sql":
                    # synchronous query POST, routed through the serving
                    # executor: repeated statements hit the prepared-plan
                    # registry (compile-once) and concurrent requests of
                    # one shape fuse into a single device dispatch; the
                    # governor admits per request under the caller's
                    # principal
                    sess = self._principal_session()
                    if sess is None:
                        return
                    from snappydata_tpu.observability import tracing

                    trace_id = None
                    try:
                        # per-request deadline: `timeout_s` in the body
                        # arms the QueryContext, so a stalled query stops
                        # cooperatively (XCL52) instead of holding the
                        # HTTP worker past the caller's patience
                        ctx = None
                        t = body.get("timeout_s")
                        if t:
                            from snappydata_tpu import resource

                            ctx = resource.new_query(body["sql"],
                                                     user=sess.user)
                            ctx.set_deadline_in(float(t))
                        # REST is a front door: mint (or join, if the
                        # caller sent one) the request's trace id — it
                        # comes back in the response, and on errors, so
                        # a client-visible failure is joinable against
                        # /status/api/v1/traces
                        with tracing.request_scope(
                                body.get("sql", ""), user=sess.user,
                                kind="rest",
                                trace_id=body.get("trace_id")) as tr:
                            trace_id = tr.trace_id if tr else None
                            result = sess.serving_sql(
                                body["sql"],
                                tuple(body.get("params", ())),
                                query_ctx=ctx)
                        # JSON over HTTP is the small-result surface:
                        # cap the payload but SAY so — a silently
                        # truncated result reads as a complete one
                        # (bulk reads belong on Flight, which streams)
                        cap = 10000
                        payload = {
                            "names": result.names,
                            "rows": [[_j(v) for v in r]
                                     for r in result.rows()[:cap]],
                            "total_rows": result.num_rows,
                        }
                        if result.num_rows > cap:
                            payload["truncated"] = True
                        if trace_id:
                            payload["trace_id"] = trace_id
                        self._send(payload)
                    except (KeyError, TypeError) as e:
                        err = {"error": f"bad request: {e}"}
                        if trace_id:
                            err["trace_id"] = trace_id
                        self._send(err, 400)
                    except Exception as e:      # noqa: BLE001
                        err = {"error": str(e)}
                        if trace_id:
                            err["trace_id"] = trace_id
                        self._send(err, 400)
                elif path.startswith("/queries/") and \
                        path.endswith("/cancel"):
                    # cooperative cancel: flags the query's context; the
                    # engine stops it at the next batch/tile boundary.
                    # Non-admin principals may only cancel their own.
                    sess = self._principal_session()
                    if sess is None:
                        return
                    qid = path[len("/queries/"):-len("/cancel")]
                    from snappydata_tpu import resource

                    # same is-not-None test as _principal_session: a
                    # falsy-but-configured provider must still gate
                    gate = sess.user if (svc.auth_tokens or
                                         svc.auth_provider is not None) \
                        else None
                    try:
                        ok = resource.global_broker().cancel(
                            qid, "cancelled via REST", user=gate)
                    except PermissionError as e:
                        self._send({"error": str(e)}, 403)
                        return
                    self._send({"queryId": qid, "cancelled": ok},
                               200 if ok else 404)
                elif path == "/faults":
                    # arm/disarm failpoints at runtime (the chaos
                    # harness's remote control). Injecting faults is an
                    # operator action: admin only when auth is on.
                    if self._admin_session("fault injection") is None:
                        return
                    from snappydata_tpu.fault import failpoints

                    reg = failpoints.registry()
                    try:
                        if body.get("clear"):
                            reg.clear()
                        elif body.get("disarm"):
                            reg.disarm(body["name"])
                        elif "seed" in body and "name" not in body \
                                and "spec" not in body:
                            reg.reseed(int(body["seed"]))
                        elif "spec" in body:   # compact-grammar string
                            reg.arm_from_spec(body["spec"])
                        else:
                            def _opt(key, cast):
                                v = body.get(key)
                                return None if v is None else cast(v)
                            reg.arm(body["name"], body["action"],
                                    param=float(body.get("param", 0.0)),
                                    exc=body.get("exc", "io"),
                                    phase=body.get("phase", "before"),
                                    count=_opt("count", int),
                                    every=_opt("every", int),
                                    p=_opt("p", float))
                    except (KeyError, ValueError, TypeError) as e:
                        self._send({"error": f"bad fault spec: {e}"}, 400)
                        return
                    self._send({"faults": reg.list()})
                elif path == "/wal/flush":
                    # durability barrier: drain+fsync the WAL commit
                    # buffer past any relaxed interval-mode ack — on the
                    # whole cluster when this lead has one, else locally
                    if self._admin_session("operator action") is None:
                        return
                    try:
                        if svc.distributed is not None:
                            self._send(svc.distributed.flush_wals())
                        elif svc.session.disk_store is not None:
                            svc.session.disk_store.wal_sync(force=True)
                            self._send({"flushed_members": 1,
                                        "durable_members": 1})
                        else:
                            self._send({"flushed_members": 0,
                                        "durable_members": 0})
                    except Exception as e:
                        self._send({"error": str(e)}, 500)
                elif path in ("/rebalance", "/redundancy/restore"):
                    # SYS.REBALANCE_ALL_BUCKETS analogue + redundancy
                    # re-restoration (operator actions; admin only when
                    # auth is on)
                    if self._admin_session("operator action") is None:
                        return
                    if svc.distributed is None:
                        self._send({"error": "no cluster session on "
                                             "this lead"}, 409)
                        return
                    try:
                        if path == "/rebalance":
                            self._send(svc.distributed.rebalance())
                        else:
                            self._send(
                                svc.distributed.restore_redundancy())
                    except Exception as e:
                        # both ops are restartable: report how they
                        # failed rather than aborting the connection
                        self._send({"error": str(e)}, 500)
                else:
                    self._send({"error": "not found"}, 404)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RestService":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
