"""Distribution: partitioner, bucket map, device mesh execution.

The reference's parallelism inventory (SURVEY.md §2.6) maps here:
- PARTITION_BY + murmur3 buckets (StoreHashFunction)  → hashing/buckets
- replicated tables / collocated joins                → GSPMD shardings
- partial aggregation + driver merge                  → psum via GSPMD
"""

from snappydata_tpu.parallel.hashing import murmur3_hash_np  # noqa: F401
from snappydata_tpu.parallel.mesh import (  # noqa: F401
    data_mesh, shard_batches, MeshContext,
)
from snappydata_tpu.parallel.buckets import BucketMap  # noqa: F401
