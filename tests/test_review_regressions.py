"""Regressions for code-review findings: NULL fidelity through insert/
update, prepared-statement params, plan-cache invalidation on DDL,
self-join aliasing, CTAS IF NOT EXISTS idempotence."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def test_numeric_null_insert_roundtrip(s):
    s.sql("CREATE TABLE t (a INT, b DOUBLE) USING column")
    s.sql("INSERT INTO t VALUES (1, 1.5), (NULL, 2.5), (3, NULL)")
    assert s.sql("SELECT count(*) FROM t WHERE a IS NULL").rows()[0][0] == 1
    assert s.sql("SELECT count(a) FROM t").rows()[0][0] == 2
    assert s.sql("SELECT sum(b) FROM t").rows()[0][0] == pytest.approx(4.0)
    rows = s.sql("SELECT a, b FROM t ORDER BY b").rows()
    # Spark semantics: ASC → NULLS FIRST
    assert rows[0] == (3, None)
    assert rows[1] == (1, 1.5) and rows[2] == (None, 2.5)


def test_null_survives_rollover(s):
    s.sql("CREATE TABLE t (a INT) USING column "
          "OPTIONS (column_max_delta_rows '3')")
    s.sql("INSERT INTO t VALUES (1), (NULL), (2), (NULL), (5)")
    assert s.sql("SELECT count(*) FROM t WHERE a IS NULL").rows()[0][0] == 2
    assert s.sql("SELECT sum(a) FROM t").rows()[0][0] == 8


def test_update_to_null_and_back(s):
    s.sql("CREATE TABLE t (k INT, name STRING) USING column "
          "OPTIONS (column_max_delta_rows '2')")
    s.sql("INSERT INTO t VALUES (1, 'x'), (2, 'y'), (3, 'z')")
    s.sql("UPDATE t SET name = NULL WHERE k = 2")
    assert s.sql("SELECT count(*) FROM t WHERE name IS NULL").rows()[0][0] == 1
    s.sql("UPDATE t SET name = 'restored' WHERE k = 2")
    assert s.sql("SELECT count(*) FROM t WHERE name IS NULL").rows()[0][0] == 0
    rows = {r[0]: r[1] for r in s.sql("SELECT k, name FROM t").rows()}
    assert rows[2] == "restored"


def test_row_table_numeric_nulls(s):
    """Row tables must preserve numeric NULLs end-to-end (they were stored
    as 0), including through host semi/anti joins where NULL keys never
    match."""
    s.sql("CREATE TABLE rc (ck INT) USING row")
    s.sql("CREATE TABLE ro (ok INT) USING row")
    s.sql("INSERT INTO rc VALUES (1), (NULL)")
    s.sql("INSERT INTO ro VALUES (NULL), (2)")
    assert s.sql("SELECT count(*) FROM rc WHERE ck IS NULL").rows()[0][0] == 1
    assert s.sql("SELECT sum(ck), count(ck) FROM rc").rows()[0] == (1, 1)
    r = s.sql("SELECT count(*) FROM rc WHERE NOT EXISTS "
              "(SELECT 1 FROM ro WHERE ok = ck)")
    assert r.rows()[0][0] == 2


def test_lag_null_input_shifts_as_null(s):
    s.sql("CREATE TABLE lgr (ord INT, v INT) USING column")
    s.sql("INSERT INTO lgr VALUES (1, 100), (2, NULL), (3, 300)")
    r = s.sql("SELECT ord, lag(v) OVER (ORDER BY ord) FROM lgr ORDER BY ord")
    assert r.rows() == [(1, None), (2, 100), (3, None)]


def test_prepared_statement_params(s):
    s.sql("CREATE TABLE t (a INT, b INT) USING column")
    s.sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    out = s.sql("SELECT a FROM t WHERE a >= ? AND b <= ?", params=(2, 20))
    assert [r[0] for r in out.rows()] == [2]
    out = s.sql("SELECT a FROM t WHERE a >= ? AND b <= ?", params=(1, 30))
    assert sorted(r[0] for r in out.rows()) == [1, 2, 3]


def test_plan_cache_invalidated_on_recreate(s):
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (2)")
    assert s.sql("SELECT count(*) FROM t").rows()[0][0] == 2
    s.sql("DROP TABLE t")
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (7)")
    assert s.sql("SELECT count(*) FROM t").rows()[0][0] == 1


def test_self_join_not_collapsed(s):
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (2), (3)")
    out = s.sql("SELECT count(*) FROM t x, t y WHERE x.a = y.a")
    assert out.rows()[0][0] == 3
    out = s.sql("SELECT count(*) FROM t x, t y")
    assert out.rows()[0][0] == 9


def test_ctas_if_not_exists_idempotent(s):
    s.sql("CREATE TABLE src (a INT) USING column")
    s.sql("INSERT INTO src VALUES (1), (2)")
    s.sql("CREATE TABLE IF NOT EXISTS dst USING column AS SELECT a FROM src")
    s.sql("CREATE TABLE IF NOT EXISTS dst USING column AS SELECT a FROM src")
    assert s.sql("SELECT count(*) FROM dst").rows()[0][0] == 2


def test_join_duplicate_build_keys_expand():
    """The device join is searchsorted (one build match per probe row) and
    must reroute to the host path when the build side has duplicate join
    keys — N:M and 1:N-on-build joins used to silently drop matches."""
    import pandas as pd
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE jl (k BIGINT, a BIGINT) USING column")
    s.sql("CREATE TABLE jr (k BIGINT, b BIGINT) USING column")
    rng = np.random.default_rng(3)
    lk = rng.integers(0, 20, 100).astype(np.int64)
    rk = rng.integers(0, 20, 80).astype(np.int64)
    s.insert_arrays("jl", [lk, np.arange(100, dtype=np.int64)])
    s.insert_arrays("jr", [rk, np.arange(80, dtype=np.int64)])
    dl = pd.DataFrame({"k": lk}); dr = pd.DataFrame({"k": rk})
    exp_inner = len(dl.merge(dr, on="k"))
    exp_left = len(dl.merge(dr, on="k", how="left"))
    got_inner = s.sql(
        "SELECT count(*) FROM jl JOIN jr ON jl.k = jr.k").rows()[0][0]
    got_left = s.sql(
        "SELECT count(*) FROM jl LEFT JOIN jr ON jl.k = jr.k").rows()[0][0]
    assert got_inner == exp_inner
    assert got_left == exp_left
    # sums must match too (not just counts)
    exp_sum = int(dl.assign(i=np.arange(100)).merge(dr, on="k").i.sum())
    got_sum = s.sql(
        "SELECT sum(jl.a) FROM jl JOIN jr ON jl.k = jr.k").rows()[0][0]
    assert got_sum == exp_sum
