"""End-to-end SQL engine tests (ref analogue: ColumnTableTest/
RowTableTest/SnappyJoinSuite tier-1 coverage — real engine, in process)."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def _sales(s, provider="column"):
    s.sql(f"CREATE TABLE sales (id INT, sym STRING, qty INT, price DOUBLE) "
          f"USING {provider}")
    rng = np.random.default_rng(42)
    n = 5000
    syms = np.array(["AAPL", "GOOG", "MSFT"], dtype=object)
    s.insert_arrays("sales", [
        np.arange(n, dtype=np.int32),
        syms[rng.integers(0, 3, n)],
        rng.integers(1, 100, n).astype(np.int32),
        np.round(rng.random(n) * 500, 2),
    ])
    return n


def test_create_show_describe(s):
    s.sql("CREATE TABLE t1 (a INT, b STRING) USING column")
    s.sql("CREATE TABLE t2 (a INT PRIMARY KEY, b STRING) USING row")
    out = s.sql("SHOW TABLES")
    assert {r[0] for r in out.rows()} == {"t1", "t2"}
    d = s.sql("DESCRIBE t1")
    assert d.rows()[0][:2] == ("a", "int")
    s.sql("DROP TABLE t1")
    assert len(s.sql("SHOW TABLES").rows()) == 1


def test_insert_values_and_select(s):
    s.sql("CREATE TABLE t (a INT, b STRING, c DOUBLE) USING column")
    n = s.sql("INSERT INTO t VALUES (1, 'x', 1.5), (2, 'y', 2.5), "
              "(3, 'x', 3.5)")
    assert n.rows()[0][0] == 3
    out = s.sql("SELECT a, b, c FROM t ORDER BY a")
    assert out.rows() == [(1, "x", 1.5), (2, "y", 2.5), (3, "x", 3.5)]


def test_filter_project_expressions(s):
    _sales(s)
    out = s.sql("SELECT id, qty * price AS total FROM sales "
                "WHERE qty > 90 AND sym = 'AAPL' ORDER BY id LIMIT 5")
    assert out.names == ["id", "total"]
    assert out.num_rows == 5
    # cross-check against full host recompute
    full = s.sql("SELECT id, qty, price, sym FROM sales ORDER BY id")
    exp = [(r[0], r[1] * r[2]) for r in full.rows()
           if r[1] > 90 and r[3] == "AAPL"][:5]
    got = [(r[0], pytest.approx(r[1])) for r in out.rows()]
    assert [r[0] for r in got] == [e[0] for e in exp]


def test_group_by_string_key(s):
    _sales(s)
    out = s.sql("SELECT sym, count(*) AS cnt, sum(qty) AS total, "
                "avg(price) AS ap, min(qty) AS mn, max(qty) AS mx "
                "FROM sales GROUP BY sym ORDER BY sym")
    rows = out.rows()
    assert [r[0] for r in rows] == ["AAPL", "GOOG", "MSFT"]
    full = s.sql("SELECT sym, qty, price FROM sales").rows()
    for sym, cnt, total, ap, mn, mx in rows:
        sel = [(q, p) for sy, q, p in full if sy == sym]
        assert cnt == len(sel)
        assert total == sum(q for q, _ in sel)
        assert ap == pytest.approx(sum(p for _, p in sel) / len(sel))
        assert mn == min(q for q, _ in sel)
        assert mx == max(q for q, _ in sel)


def test_group_by_numeric_generic_path(s):
    _sales(s)
    out = s.sql("SELECT qty, count(*) AS c FROM sales GROUP BY qty")
    full = s.sql("SELECT qty FROM sales").rows()
    from collections import Counter

    expected = Counter(q for (q,) in full)
    got = {r[0]: r[1] for r in out.rows()}
    assert got == dict(expected)


def test_global_aggregate_no_groups(s):
    _sales(s)
    out = s.sql("SELECT count(*), sum(qty), avg(price) FROM sales")
    assert out.num_rows == 1
    full = s.sql("SELECT qty, price FROM sales").rows()
    r = out.rows()[0]
    assert r[0] == len(full)
    assert r[1] == sum(q for q, _ in full)
    assert r[2] == pytest.approx(sum(p for _, p in full) / len(full))


def test_having_and_order_by_agg(s):
    _sales(s)
    out = s.sql("SELECT sym, count(*) AS cnt FROM sales GROUP BY sym "
                "HAVING count(*) > 0 ORDER BY cnt DESC")
    rows = out.rows()
    assert len(rows) == 3
    assert rows[0][1] >= rows[1][1] >= rows[2][1]


def test_join_inner(s):
    s.sql("CREATE TABLE dept (did INT, dname STRING) USING column")
    s.sql("CREATE TABLE emp (eid INT, did INT, sal DOUBLE) USING column")
    s.sql("INSERT INTO dept VALUES (1, 'eng'), (2, 'ops'), (3, 'hr')")
    s.sql("INSERT INTO emp VALUES (10, 1, 100.0), (11, 1, 200.0), "
          "(12, 2, 300.0), (13, 9, 400.0)")
    out = s.sql("SELECT e.eid, d.dname FROM emp e JOIN dept d "
                "ON e.did = d.did ORDER BY e.eid")
    assert out.rows() == [(10, "eng"), (11, "eng"), (12, "ops")]


def test_join_left(s):
    s.sql("CREATE TABLE a (x INT) USING column")
    s.sql("CREATE TABLE b (y INT, label STRING) USING column")
    s.sql("INSERT INTO a VALUES (1), (2), (3)")
    s.sql("INSERT INTO b VALUES (2, 'two'), (3, 'three')")
    out = s.sql("SELECT x, label FROM a LEFT JOIN b ON x = y ORDER BY x")
    assert out.rows() == [(1, None), (2, "two"), (3, "three")]


def test_string_key_join_across_dictionaries(s):
    """Regression: each table has its own dictionary — string-key joins
    must translate codes, not compare them raw (insertion order differs)."""
    s.sql("CREATE TABLE l (code STRING, v INT) USING column")
    s.sql("CREATE TABLE r (code STRING, label STRING) USING column")
    # deliberately different insertion orders → different code assignments
    s.sql("INSERT INTO l VALUES ('b', 1), ('a', 2), ('c', 3), ('zz', 4)")
    s.sql("INSERT INTO r VALUES ('c', 'C!'), ('b', 'B!'), ('a', 'A!')")
    out = s.sql("SELECT l.code, r.label, l.v FROM l JOIN r "
                "ON l.code = r.code ORDER BY l.code")
    assert out.rows() == [("a", "A!", 2), ("b", "B!", 1), ("c", "C!", 3)]
    out = s.sql("SELECT count(*) FROM l LEFT JOIN r ON l.code = r.code "
                "WHERE r.label IS NULL")
    assert out.rows()[0][0] == 1  # 'zz' matches nothing


def test_join_then_aggregate(s):
    s.sql("CREATE TABLE dept (did INT, dname STRING) USING column")
    s.sql("CREATE TABLE emp (eid INT, did INT, sal DOUBLE) USING column")
    s.sql("INSERT INTO dept VALUES (1, 'eng'), (2, 'ops')")
    s.sql("INSERT INTO emp VALUES (10, 1, 100.0), (11, 1, 200.0), "
          "(12, 2, 300.0)")
    out = s.sql("SELECT d.dname, sum(e.sal) AS total FROM emp e "
                "JOIN dept d ON e.did = d.did GROUP BY d.dname "
                "ORDER BY d.dname")
    assert out.rows() == [("eng", 300.0), ("ops", 300.0)]


def test_update_delete_sql(s):
    s.sql("CREATE TABLE t (k INT, v DOUBLE) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    s.sql("INSERT INTO t VALUES (1, 1.0), (2, 2.0), (3, 3.0), (4, 4.0), "
          "(5, 5.0), (6, 6.0)")
    n = s.sql("UPDATE t SET v = v * 10 WHERE k <= 2").rows()[0][0]
    assert n == 2
    n = s.sql("DELETE FROM t WHERE k >= 5").rows()[0][0]
    assert n == 2
    out = s.sql("SELECT k, v FROM t ORDER BY k")
    assert out.rows() == [(1, 10.0), (2, 20.0), (3, 3.0), (4, 4.0)]


def test_put_into_row_table(s):
    s.sql("CREATE TABLE kv (k INT PRIMARY KEY, v STRING) USING row")
    s.sql("INSERT INTO kv VALUES (1, 'a'), (2, 'b')")
    s.sql("PUT INTO kv VALUES (2, 'B'), (3, 'c')")
    out = s.sql("SELECT k, v FROM kv ORDER BY k")
    assert out.rows() == [(1, "a"), (2, "B"), (3, "c")]
    assert s.get("kv", (2,)) == (2, "B")


def test_row_table_scan_and_join_with_column(s):
    s.sql("CREATE TABLE dim (id INT PRIMARY KEY, name STRING) USING row")
    s.sql("CREATE TABLE facts (fid INT, id INT, amt DOUBLE) USING column")
    s.sql("INSERT INTO dim VALUES (1, 'one'), (2, 'two')")
    s.sql("INSERT INTO facts VALUES (100, 1, 5.0), (101, 2, 7.0), "
          "(102, 1, 9.0)")
    out = s.sql("SELECT d.name, sum(f.amt) AS total FROM facts f "
                "JOIN dim d ON f.id = d.id GROUP BY d.name ORDER BY d.name")
    assert out.rows() == [("one", 14.0), ("two", 7.0)]


def test_nulls_and_case(s):
    s.sql("CREATE TABLE t (a INT, b STRING) USING column")
    s.sql("INSERT INTO t VALUES (1, 'x'), (2, NULL), (3, 'y')")
    out = s.sql("SELECT a, CASE WHEN b IS NULL THEN 'missing' ELSE b END "
                "AS label FROM t ORDER BY a")
    assert [r[1] for r in out.rows()] == ["x", "missing", "y"]
    out2 = s.sql("SELECT count(b) FROM t")
    assert out2.rows()[0][0] == 2


def test_in_between_like(s):
    _sales(s)
    out = s.sql("SELECT count(*) FROM sales WHERE sym IN ('AAPL', 'MSFT')")
    full = s.sql("SELECT sym FROM sales").rows()
    assert out.rows()[0][0] == sum(1 for (x,) in full if x in ("AAPL", "MSFT"))
    out = s.sql("SELECT count(*) FROM sales WHERE qty BETWEEN 10 AND 20")
    qty = [r[0] for r in s.sql("SELECT qty FROM sales").rows()]
    assert out.rows()[0][0] == sum(1 for q in qty if 10 <= q <= 20)
    out = s.sql("SELECT count(*) FROM sales WHERE sym LIKE 'A%'")
    assert out.rows()[0][0] == sum(1 for (x,) in full if x.startswith("A"))


def test_distinct_union_values(s):
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (1), (2)")
    assert sorted(r[0] for r in s.sql("SELECT DISTINCT a FROM t").rows()) \
        == [1, 2]
    u = s.sql("SELECT a FROM t UNION ALL SELECT a FROM t")
    assert u.num_rows == 6
    v = s.sql("VALUES (1, 'a'), (2, 'b')")
    assert v.rows() == [(1, "a"), (2, "b")]


def test_plan_cache_reuse_across_literals(s):
    _sales(s)
    r1 = s.sql("SELECT count(*) FROM sales WHERE qty > 50")
    n_compiled = len(s.executor._plan_cache)
    r2 = s.sql("SELECT count(*) FROM sales WHERE qty > 70")
    assert len(s.executor._plan_cache) == n_compiled  # same tokenized plan
    qty = [r[0] for r in s.sql("SELECT qty FROM sales").rows()]
    assert r1.rows()[0][0] == sum(1 for q in qty if q > 50)
    assert r2.rows()[0][0] == sum(1 for q in qty if q > 70)


def test_subquery_in_from(s):
    _sales(s)
    out = s.sql("SELECT sym, total FROM (SELECT sym, sum(qty) AS total "
                "FROM sales GROUP BY sym) t WHERE total > 0 ORDER BY sym")
    assert out.num_rows == 3


def test_date_functions_and_literals(s):
    s.sql("CREATE TABLE ev (d DATE, v INT) USING column")
    s.sql("INSERT INTO ev VALUES (DATE '2020-03-15', 1), "
          "(DATE '2021-07-04', 2), (DATE '2020-12-31', 3)")
    out = s.sql("SELECT year(d), month(d), day(d) FROM ev ORDER BY v")
    assert out.rows() == [(2020, 3, 15), (2021, 7, 4), (2020, 12, 31)]
    out = s.sql("SELECT count(*) FROM ev WHERE d >= DATE '2020-06-01' "
                "AND d < DATE '2021-01-01'")
    assert out.rows()[0][0] == 1
    out = s.sql("SELECT count(*) FROM ev "
                "WHERE d < DATE '2021-01-01' - INTERVAL '30' DAY")
    assert out.rows()[0][0] == 1  # only 2020-03-15 precedes 2020-12-02


def test_count_distinct_on_device(s):
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE cd (g STRING, v INT) USING column")
    rng = np.random.default_rng(3)
    s.insert_arrays("cd", [
        np.array(["x", "y"], dtype=object)[rng.integers(0, 2, 20000)],
        rng.integers(0, 250, 20000).astype(np.int32)])
    before = global_registry().counter("host_fallbacks")
    out = s.sql("SELECT g, count(DISTINCT v) FROM cd GROUP BY g ORDER BY g")
    assert [r[1] for r in out.rows()] == [250, 250]
    assert global_registry().counter("host_fallbacks") == before
    assert s.sql("SELECT count(DISTINCT g) FROM cd").rows()[0][0] == 2


def test_device_cache_eviction_budget(s):
    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry

    config.global_properties().device_cache_bytes = 1_000_000
    try:
        for i in range(4):
            s.sql(f"CREATE TABLE ev{i} (a BIGINT) USING column")
            s.insert_arrays(f"ev{i}",
                            [np.arange(60_000, dtype=np.int64)])
        before = global_registry().counter("device_cache_evictions")
        for i in range(4):
            assert s.sql(f"SELECT sum(a) FROM ev{i}").rows()[0][0] == \
                sum(range(60_000))
        assert global_registry().counter("device_cache_evictions") > before
        # evicted caches rebuild transparently and stay correct
        for i in range(4):
            assert s.sql(f"SELECT count(*) FROM ev{i}").rows()[0][0] == \
                60_000
    finally:
        config.global_properties().device_cache_bytes = 0


def test_batch_skipping_stats(s):
    """Stats-based batch pruning (ref columnBatchesSkipped) must not
    change results and must actually skip."""
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE ev (d INT, v DOUBLE) USING column "
          "OPTIONS (column_batch_rows '1024', column_max_delta_rows '512')")
    s.insert_arrays("ev", [np.arange(50_000, dtype=np.int32),
                           np.ones(50_000)])
    before = global_registry().counter("column_batches_skipped")
    r = s.sql("SELECT count(*), sum(v) FROM ev "
              "WHERE d >= 40000 AND d < 45000")
    assert r.rows() == [(5000, 5000.0)]
    assert global_registry().counter("column_batches_skipped") > before
    # literal change reuses the plan but re-prunes
    r2 = s.sql("SELECT count(*), sum(v) FROM ev WHERE d >= 0 AND d < 100")
    assert r2.rows() == [(100, 100.0)]
    # mutations must not be masked by stale stats
    s.sql("UPDATE ev SET d = 49999 WHERE d = 0")
    r3 = s.sql("SELECT count(*) FROM ev WHERE d = 49999")
    assert r3.rows() == [(2,)]


def test_views(s):
    s.sql("CREATE TABLE t (a INT, b STRING) USING column")
    s.sql("INSERT INTO t VALUES (1, 'x'), (5, 'y'), (9, 'z')")
    s.sql("CREATE VIEW big AS SELECT a, b FROM t WHERE a > 2")
    assert s.sql("SELECT count(*) FROM big").rows()[0][0] == 2
    out = s.sql("SELECT v.b FROM big v WHERE v.a = 9")
    assert out.rows() == [("z",)]
    s.sql("CREATE OR REPLACE VIEW big AS SELECT a FROM t WHERE a > 8")
    assert s.sql("SELECT count(*) FROM big").rows()[0][0] == 1
    s.sql("DROP VIEW big")
    with pytest.raises(Exception):
        s.sql("SELECT * FROM big")


def test_mutation_then_query_sees_new_version(s):
    s.sql("CREATE TABLE t (k INT, v INT) USING column "
          "OPTIONS (column_max_delta_rows '2')")
    s.sql("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    assert s.sql("SELECT sum(v) FROM t").rows()[0][0] == 60
    s.sql("UPDATE t SET v = 0 WHERE k = 2")
    assert s.sql("SELECT sum(v) FROM t").rows()[0][0] == 40
    s.sql("DELETE FROM t WHERE k = 1")
    assert s.sql("SELECT sum(v) FROM t").rows()[0][0] == 30


def test_execute_take_early_stop():
    """LIMIT-only queries decode batches incrementally and stop early
    (ref: CachedDataFrame.executeTake:766) — not the whole table."""
    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry

    gp = config.global_properties()
    old_rows = gp.column_batch_rows
    gp.column_batch_rows = 1024  # table store reads the global properties
    try:
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE taketest (a BIGINT, b STRING) USING column")
        n = 20_000
        s.insert_arrays("taketest", [
            np.arange(n, dtype=np.int64),
            np.array([f"v{i % 97}" for i in range(n)], dtype=object)])
    finally:
        gp.column_batch_rows = old_rows
    assert len(s.catalog.describe("taketest").data.snapshot().views) >= 5
    reg = global_registry()
    before_dec = reg.snapshot()["counters"].get("take_batches_decoded", 0)
    before_stop = reg.snapshot()["counters"].get("take_early_stops", 0)

    r = s.sql("SELECT a, b FROM taketest LIMIT 5")
    assert r.num_rows == 5
    assert [row[0] for row in r.rows()] == [0, 1, 2, 3, 4]

    r2 = s.sql("SELECT a FROM taketest WHERE a >= 3000 LIMIT 7")
    assert [row[0] for row in r2.rows()] == list(range(3000, 3007))

    snap = reg.snapshot()["counters"]
    stops = snap.get("take_early_stops", 0) - before_stop
    decoded = snap.get("take_batches_decoded", 0) - before_dec
    assert stops == 2
    # ~20 batches exist; the two queries together must decode only a few
    assert decoded <= 6, decoded
