"""Mutable table storage with snapshot-isolation MVCC.

Re-provides, TPU-style, what the reference splits between the store engine
and the columnar layer:

- Row delta buffer + rollover into column batches at `column_max_delta_rows`
  (ref: ColumnBatchCreator.createAndStoreBatch core/.../columnar/
  ColumnBatchCreator.scala:46, fired from StoreCallbacksImpl.createColumnBatch:77).
- Update/delete deltas merged at scan time (ref: ColumnDeltaEncoder /
  UpdatedColumnDecoder / delete mask column -3, encoders/.../impl/
  ColumnFormatEntry.scala:89-95).
- Snapshot isolation: readers pin an immutable Manifest version; writers
  build a new Manifest and publish it atomically (ref: snapshot tx around
  store writes, JDBCSourceAsColumnarStore.scala:124-233 beginTx/commitTx).
  JAX arrays being immutable makes this design natural: a snapshot is just
  a tuple of references.

Device representation: per column a stacked [num_batches, capacity] jax
array (device dtype) plus a shared bool valid mask — one static shape for
the whole table so every query over it reuses one compiled executable.
Batch count is padded to a power of two (shape bucketing) so ingest doesn't
recompile every query (ref analogue: plan cache amortizing Janino codegen;
XLA compile is costlier still, SURVEY.md §7 hard part (d)).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from snappydata_tpu.utils import locks
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from snappydata_tpu import config
from snappydata_tpu import types as T
from snappydata_tpu.storage.batch import ColumnBatch
from snappydata_tpu.storage.encoding import decode_to_numpy, decode_validity


def _struct_get(cell: dict, fname: str):
    """Case-insensitive struct field read (analyzer semantics)."""
    got = cell.get(fname)
    if got is None:
        fl = fname.lower()
        for k, v in cell.items():
            if isinstance(k, str) and k.lower() == fl:
                return v
    return got


@dataclasses.dataclass(frozen=True)
class BatchView:
    """One batch as visible in a particular Manifest version."""

    batch: ColumnBatch
    delete_mask: Optional[np.ndarray] = None     # bool[capacity]; True = deleted
    # update deltas: col_idx -> (hit mask bool[capacity],
    #   values device-dtype[capacity], value-null mask bool[capacity] | None)
    deltas: Tuple[Tuple[int, np.ndarray, np.ndarray,
                        Optional[np.ndarray]], ...] = ()

    def decoded_column(self, col_idx: int, strings: bool = False) -> np.ndarray:
        """Base decode + delta merge (ref UpdatedColumnDecoder semantics)."""
        col = self.batch.columns[col_idx]
        out = decode_to_numpy(col, self.batch.capacity, strings=strings)
        for ci, mask, values, _ in self.deltas:
            if ci == col_idx:
                out = np.where(mask, values, out)
        return out

    def null_mask(self, col_idx: int) -> Optional[np.ndarray]:
        """Effective null mask after delta merge (a delta can both clear a
        NULL by assigning a value and set one by assigning NULL)."""
        base = decode_validity(self.batch.columns[col_idx],
                               self.batch.capacity)
        mask = (~base) if base is not None else None
        for ci, hit, _, value_nulls in self.deltas:
            if ci != col_idx:
                continue
            if mask is None:
                mask = np.zeros(self.batch.capacity, dtype=np.bool_)
            vn = value_nulls if value_nulls is not None else False
            mask = np.where(hit, vn, mask)
        if mask is not None and not mask.any():
            return None
        return mask

    def live_mask(self) -> np.ndarray:
        m = np.arange(self.batch.capacity) < self.batch.num_rows
        if self.delete_mask is not None:
            m = m & ~self.delete_mask
        return m

    def live_rows(self) -> int:
        return int(self.batch.num_rows - (0 if self.delete_mask is None
                                          else int(self.delete_mask.sum())))


@dataclasses.dataclass(frozen=True)
class Manifest:
    """Immutable table snapshot (the MVCC unit)."""

    version: int
    views: Tuple[BatchView, ...]
    # row-buffer snapshot: per-column host arrays of the delta rows
    row_arrays: Tuple[np.ndarray, ...]
    row_count: int
    # per-column bool null masks for the row-buffer rows (None = no nulls)
    row_nulls: Tuple[Optional[np.ndarray], ...] = ()
    # commit stamps (storage/mvcc.py): the process-wide epoch this
    # publish advanced to, and — on durable sessions — the WAL seq of
    # the committing statement (the commit timestamp; 0 for in-memory
    # publishes and recovery-loaded checkpoints, whose seq is the fence)
    epoch: int = 0
    wal_seq: int = 0

    def total_rows(self) -> int:
        return sum(v.live_rows() for v in self.views) + self.row_count


class RowBuffer:
    """Mutable per-table row delta buffer (ref: the table.SHADOW row table
    that small inserts land in, SURVEY.md §3.3). Columnar numpy storage,
    mutated in place under the table writer lock; snapshots copy (≤
    column_max_delta_rows rows, so copies are cheap)."""

    def __init__(self, schema: T.Schema, capacity: int):
        self.schema = schema
        self.capacity = capacity
        self._cols: List[np.ndarray] = [
            np.empty(capacity, dtype=f.dtype.np_dtype) for f in schema.fields]
        self._nulls: List[Optional[np.ndarray]] = [None] * len(schema.fields)
        self._valid = np.ones(capacity, dtype=np.bool_)  # False = deleted in place
        self.count = 0

    def append(self, arrays: Sequence[np.ndarray],
               nulls: Optional[Sequence[Optional[np.ndarray]]] = None) -> int:
        n = int(np.asarray(arrays[0]).shape[0])
        assert self.count + n <= self.capacity
        for i, (dst, src) in enumerate(zip(self._cols, arrays)):
            dst[self.count:self.count + n] = np.asarray(src)
            nm = nulls[i] if nulls is not None else None
            if nm is not None and nm.any():
                if self._nulls[i] is None:
                    self._nulls[i] = np.zeros(self.capacity, dtype=np.bool_)
                self._nulls[i][self.count:self.count + n] = nm
            elif self._nulls[i] is not None:
                self._nulls[i][self.count:self.count + n] = False
        self._valid[self.count:self.count + n] = True
        self.count += n
        return n

    def snapshot(self) -> Tuple[Tuple[np.ndarray, ...],
                                Tuple[Optional[np.ndarray], ...], int]:
        live = self._valid[:self.count]
        if live.all():
            arrs = tuple(c[:self.count].copy() for c in self._cols)
            nls = tuple(m[:self.count].copy() if m is not None else None
                        for m in self._nulls)
            return arrs, nls, self.count
        arrs = tuple(c[:self.count][live].copy() for c in self._cols)
        nls = tuple(m[:self.count][live].copy() if m is not None else None
                    for m in self._nulls)
        return arrs, nls, int(live.sum())

    def clear(self) -> None:
        self.count = 0
        self._nulls = [None] * len(self.schema.fields)

    def add_field(self, field: T.Field) -> None:
        """Schema evolution: existing buffered rows read NULL."""
        self.schema = T.Schema(tuple(self.schema.fields) + (field,))
        npd = field.dtype.np_dtype
        self._cols.append(np.empty(self.capacity, dtype=npd)
                          if npd == object
                          else np.zeros(self.capacity, dtype=npd))
        nm = None
        if self.count:
            nm = np.zeros(self.capacity, dtype=np.bool_)
            nm[:self.count] = True
        self._nulls.append(nm)

    def drop_field(self, idx: int) -> None:
        self.schema = T.Schema(tuple(
            f for i, f in enumerate(self.schema.fields) if i != idx))
        del self._cols[idx]
        del self._nulls[idx]


class ColumnTableData:
    """Storage for one COLUMN table: immutable batches + row delta buffer +
    manifest chain. Thread-safe: one writer lock, lock-free readers."""

    def __init__(self, schema: T.Schema, capacity: Optional[int] = None,
                 max_delta_rows: Optional[int] = None):
        props = config.global_properties()
        self.schema = schema
        self.capacity = capacity or props.column_batch_rows
        self.max_delta_rows = max_delta_rows or props.column_max_delta_rows
        self._lock = locks.named_lock("storage.column_table")
        self._batch_ids = itertools.count()
        self._row_buffer = RowBuffer(schema, max(self.max_delta_rows * 2,
                                                 self.capacity))
        # table-level shared dictionaries for string columns: codes stay
        # comparable across batches (device group-by/join runs on codes)
        self._dicts: Dict[int, List] = {
            i: [] for i, f in enumerate(schema.fields) if f.dtype.name == "string"}
        self._dict_lookup: Dict[int, Dict] = {i: {} for i in self._dicts}
        # ARRAY<STRING> columns: append-only ELEMENT dictionaries (same
        # protocol as scalar strings — codes never shift, so device
        # plates built under any pinned manifest stay decodable by every
        # later dictionary read)
        self._elem_dicts: Dict[int, List] = {
            i: [] for i, f in enumerate(schema.fields)
            if f.dtype.name == "array"
            and getattr(f.dtype, "element", None) is not None
            and f.dtype.element.name == "string"}
        self._elem_lookup: Dict[int, Dict] = {i: {}
                                              for i in self._elem_dicts}
        # MAP<STRING, V> columns: append-only KEY dictionaries, plus
        # VALUE dictionaries when V is also string
        self._map_key_dicts: Dict[int, List] = {
            i: [] for i, f in enumerate(schema.fields)
            if f.dtype.name == "map"
            and getattr(f.dtype, "key", None) is not None
            and f.dtype.key.name == "string"}
        self._map_key_lookup: Dict[int, Dict] = {
            i: {} for i in self._map_key_dicts}
        self._map_val_dicts: Dict[int, List] = {
            i: [] for i, f in enumerate(schema.fields)
            if i in self._map_key_dicts
            and f.dtype.value.name == "string"}
        self._map_val_lookup: Dict[int, Dict] = {
            i: {} for i in self._map_val_dicts}
        # STRUCT columns: per-(column, field-name) value dictionaries
        # for string fields, created lazily at the first intern
        self._struct_dicts: Dict[int, Dict[str, List]] = {}
        self._struct_lookup: Dict[int, Dict[str, Dict]] = {}
        self._manifest = Manifest(
            0, (), tuple(np.empty(0, dtype=f.dtype.np_dtype)
                         for f in schema.fields), 0,
            tuple(None for _ in schema.fields))
        # post-insert observers (AQP sample/TopK maintainers; ref:
        # SampleInsertExec keeps samples in sync with base inserts)
        self.on_insert = []
        # device cache: manifest version -> {key: device arrays}. Keyed per
        # version so concurrent readers of different snapshots never mix
        # entries (review finding: clear+overwrite raced).
        self._device_cache: Dict[int, Dict] = {}

    # --- snapshots -------------------------------------------------------

    def snapshot(self) -> Manifest:
        return self._manifest

    def _publish(self, views: Tuple[BatchView, ...]) -> Manifest:
        from snappydata_tpu.storage import mvcc

        row_arrays, row_nulls, row_count = self._row_buffer.snapshot()
        # the epoch stamp and the reference swap happen under ONE clock
        # hold so a pin capturing a cross-table cut can never observe
        # half a commit (mvcc.SnapshotPin.pin_many holds the same lock)
        with mvcc.clock():
            m = Manifest(self._manifest.version + 1, views, row_arrays,
                         row_count, row_nulls,
                         epoch=mvcc._bump_epoch_locked(),
                         wal_seq=mvcc.current_commit_seq())
            mvcc.retain_locked(self, self._manifest)
            self._manifest = m
        return m

    # --- dictionaries ----------------------------------------------------

    def _intern_strings(self, col_idx: int, values: np.ndarray) -> np.ndarray:
        """Extend the shared dictionary with unseen values; old codes stay
        valid because the dictionary is append-only. Delegates to the
        native fused encoder (single implementation of the intern
        protocol — review finding)."""
        from snappydata_tpu.native import fast_encode_strings

        fast_encode_strings(np.asarray(values, dtype=object),
                            self._dict_lookup[col_idx],
                            self._dicts[col_idx])
        return np.array(self._dicts[col_idx], dtype=object)

    def dictionary(self, col_idx: int) -> Optional[np.ndarray]:
        if col_idx in self._dicts:
            return np.array(self._dicts[col_idx], dtype=object)
        return None

    def intern_array_elements(self, col_idx: int, cells) -> Dict:
        """Append-only intern of an ARRAY<STRING> column's element
        values (device binds call this over their PINNED manifest's
        cells, so a bind is always self-sufficient — recovery included).
        Returns a point-in-time copy of the lookup for code assignment."""
        lk = self._elem_lookup[col_idx]
        d = self._elem_dicts[col_idx]
        with self._lock:
            for cell in cells:
                if isinstance(cell, (list, tuple, np.ndarray)):
                    for el in cell:
                        if el is not None:
                            key = str(el)
                            if key not in lk:
                                lk[key] = len(d)
                                d.append(key)
            return dict(lk)

    def array_element_dictionary(self, col_idx: int) -> np.ndarray:
        """Element dictionary of an ARRAY<STRING> column. Append-only:
        a superset of the values any existing device plates encode."""
        with self._lock:
            return np.array(self._elem_dicts[col_idx], dtype=object)

    def intern_map_entries(self, col_idx: int, cells
                           ) -> Tuple[Dict, Optional[Dict]]:
        """Append-only intern of a MAP<STRING, V> column's keys (and
        values when V is string). Returns point-in-time copies of the
        (key lookup, value lookup | None) for code assignment."""
        klk = self._map_key_lookup[col_idx]
        kd = self._map_key_dicts[col_idx]
        vlk = self._map_val_lookup.get(col_idx)
        vd = self._map_val_dicts.get(col_idx)
        with self._lock:
            for cell in cells:
                if isinstance(cell, dict):
                    for k, v in cell.items():
                        ks = str(k)
                        if ks not in klk:
                            klk[ks] = len(kd)
                            kd.append(ks)
                        if vlk is not None and v is not None:
                            vs = str(v)
                            if vs not in vlk:
                                vlk[vs] = len(vd)
                                vd.append(vs)
            return dict(klk), (dict(vlk) if vlk is not None else None)

    def map_key_dictionary(self, col_idx: int) -> np.ndarray:
        with self._lock:
            return np.array(self._map_key_dicts[col_idx], dtype=object)

    def map_value_dictionary(self, col_idx: int) -> Optional[np.ndarray]:
        with self._lock:
            if col_idx not in self._map_val_dicts:
                return None
            return np.array(self._map_val_dicts[col_idx], dtype=object)

    def intern_struct_fields(self, col_idx: int, fnames, cells
                             ) -> Dict[str, Dict]:
        """Append-only intern of a STRUCT column's string-field values
        — ALL fields in one pass over the cells (case-insensitive field
        resolution like the analyzer). Returns {field: point-in-time
        lookup copy}."""
        with self._lock:
            col_lk = self._struct_lookup.setdefault(col_idx, {})
            col_d = self._struct_dicts.setdefault(col_idx, {})
            lks = {fn: col_lk.setdefault(fn, {}) for fn in fnames}
            ds = {fn: col_d.setdefault(fn, []) for fn in fnames}
            for cell in cells:
                if isinstance(cell, dict):
                    for fn in fnames:
                        v = _struct_get(cell, fn)
                        if v is not None:
                            key = str(v)
                            lk = lks[fn]
                            if key not in lk:
                                d = ds[fn]
                                lk[key] = len(d)
                                d.append(key)
            return {fn: dict(lk) for fn, lk in lks.items()}

    def struct_field_dictionary(self, col_idx: int, fname: str
                                ) -> np.ndarray:
        with self._lock:
            d = self._struct_dicts.get(col_idx, {}).get(fname, [])
            return np.array(d, dtype=object)

    # --- writes ----------------------------------------------------------

    def insert_arrays(self, arrays: Sequence[np.ndarray],
                      nulls: Optional[Sequence[Optional[np.ndarray]]] = None
                      ) -> int:
        """Bulk/small insert. Large inserts cut column batches directly
        (ref ColumnInsertExec bulk path); small ones land in the row buffer
        and roll over when it exceeds max_delta_rows (ref §3.3).

        `nulls[i]` is an optional bool mask marking SQL NULLs in column i
        (values at those positions are fillers)."""
        from snappydata_tpu.storage import hoststore

        hoststore.check_critical_memory()
        arrays = [np.asarray(a) for a in arrays]
        if len(arrays) != len(self.schema.fields):
            raise ValueError(
                f"expected {len(self.schema.fields)} columns, got {len(arrays)}")
        n = int(arrays[0].shape[0])
        for a, f in zip(arrays, self.schema.fields):
            if int(a.shape[0]) != n:
                raise ValueError(
                    f"column {f.name}: length {a.shape[0]} != {n}")
        if nulls is None:
            nulls = [None] * len(arrays)
        with self._lock:
            # intern + dictionary-encode strings in ONE fused pass (native
            # C++ kernel when available; vectorized pandas otherwise) so
            # batch cutting below just slices the precomputed codes
            from snappydata_tpu.native import fast_encode_strings

            nulls = list(nulls)
            str_codes: Dict[int, np.ndarray] = {}
            for i in self._dicts:
                arrays[i] = np.asarray(arrays[i], dtype=object)
                codes, cnulls = fast_encode_strings(
                    arrays[i], self._dict_lookup[i], self._dicts[i])
                str_codes[i] = codes
                if cnulls is not None:
                    nulls[i] = cnulls if nulls[i] is None \
                        else (nulls[i] | cnulls)
            views = list(self._manifest.views)
            pos = 0
            if n >= self.max_delta_rows:
                slices = []
                while n - pos >= self.max_delta_rows:
                    take = min(self.capacity, n - pos)
                    slices.append(slice(pos, pos + take))
                    pos += take
                views.extend(self._cut_batches_pipelined(
                    arrays, nulls, str_codes, slices))
            if pos < n:
                self._row_buffer.append(
                    [a[pos:] for a in arrays],
                    [m[pos:] if m is not None else None for m in nulls])
            if self._row_buffer.count >= self.max_delta_rows:
                views.extend(self._rollover_locked())
            self._publish(tuple(views))
        self._maybe_spill()
        for cb in self.on_insert:
            cb(arrays, nulls)
        return n

    def _maybe_spill(self) -> None:
        """Evict the coldest batches to disk when the host budget is
        exceeded (ref: SnappyStorageEvictor region eviction,
        SnappyUnifiedMemoryManager.scala:379-401). A per-table
        EVICTION-clause analogue (OPTIONS eviction_bytes 'N') overrides
        the global budget."""
        from snappydata_tpu import config

        budget = getattr(self, "eviction_bytes", None) \
            or config.global_properties().host_store_bytes
        if budget:
            from snappydata_tpu.storage import hoststore

            hoststore.spill_to_budget(self, budget)

    # rows below which the pipelined cut isn't worth its thread overhead
    _PIPELINE_MIN_ROWS = 1 << 16

    def _cut_batches_pipelined(self, arrays, nulls, str_codes, slices
                               ) -> List[BatchView]:
        """Ingest fast lane: encode the batches of one bulk insert on a
        two-worker pipeline (double-buffered) so batch k+1's CRC/encode
        CPU work overlaps batch k's — and, on the durable path, overlaps
        the WAL group fsync the background flusher is running for this
        statement's journal record. Safe because the fused string encode
        already interned every value (str_codes covers all dictionary
        columns), so workers only READ the append-only dictionaries.
        Batch ids are pre-assigned in slice order; views keep insertion
        order."""
        if not slices:
            return []
        total = sum(sl.stop - sl.start for sl in slices)
        pipelined = (len(slices) > 1 and total >= self._PIPELINE_MIN_ROWS
                     and all(i in str_codes for i in self._dicts))

        def args_for(sl):
            return ([a[sl] for a in arrays],
                    [m[sl] if m is not None else None for m in nulls],
                    {i: c[sl] for i, c in str_codes.items()})

        if not pipelined:
            return [self._cut_batch(*args_for(sl)) for sl in slices]
        from concurrent.futures import ThreadPoolExecutor

        ids = [next(self._batch_ids) for _ in slices]
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [ex.submit(self._cut_batch, *args_for(sl), batch_id=bid)
                    for sl, bid in zip(slices, ids)]
            return [f.result() for f in futs]

    def _cut_batch(self, arrays: List[np.ndarray],
                   nulls: Optional[List[Optional[np.ndarray]]] = None,
                   str_codes: Optional[Dict[int, np.ndarray]] = None,
                   batch_id: Optional[int] = None) -> BatchView:
        from snappydata_tpu.storage import bitmask
        from snappydata_tpu.storage.encoding import (ColumnStats,
                                                     EncodedColumn, Encoding)

        dicts = {}
        precoded: Dict[int, EncodedColumn] = {}
        for i in self._dicts:
            if str_codes is not None and i in str_codes:
                # fused-encode fast path: codes are ready, just wrap them
                codes = np.ascontiguousarray(str_codes[i], dtype=np.int32)
                cn = nulls[i] if nulls is not None else None
                n_rows = int(codes.shape[0])
                packed = bitmask.pack(~cn) \
                    if cn is not None and cn.any() else None
                precoded[i] = EncodedColumn(
                    Encoding.DICTIONARY, self.schema.fields[i].dtype,
                    n_rows, codes,
                    dictionary=np.array(self._dicts[i], dtype=object),
                    validity=packed,
                    stats=ColumnStats(None, None,
                                      int(cn.sum()) if cn is not None else 0,
                                      n_rows))
            else:
                dicts[i] = self._intern_strings(i, arrays[i])
        validities = None
        if nulls is not None and any(m is not None and m.any() for m in nulls):
            validities = [~m if m is not None else None for m in nulls]
        batch = ColumnBatch.from_arrays(
            next(self._batch_ids) if batch_id is None else batch_id,
            0, self.schema, arrays, self.capacity,
            validities=validities, dictionaries=dicts,
            precoded=precoded)
        return BatchView(batch)

    def _rollover_locked(self) -> List[BatchView]:
        arrays, nulls, cnt = self._row_buffer.snapshot()
        self._row_buffer.clear()
        out = []
        pos = 0
        while pos < cnt:
            take = min(self.capacity, cnt - pos)
            sl = slice(pos, pos + take)
            out.append(self._cut_batch(
                [a[sl] for a in arrays],
                [m[sl] if m is not None else None for m in nulls]))
            pos += take
        return out

    # --- schema evolution (ref: AlterTableAddColumnCommand /
    # AlterTableDropColumnCommand, SnappySession.alterTable:1628; we extend
    # it to column tables — existing rows read the new column as NULL) ---

    def _all_null_column(self, col_idx: int, dtype: T.DataType,
                         n: int):
        from snappydata_tpu.storage import bitmask
        from snappydata_tpu.storage.encoding import (ColumnStats,
                                                     EncodedColumn, Encoding)

        validity = bitmask.pack(np.zeros(n, dtype=np.bool_))
        stats = ColumnStats(None, None, n, n)
        if dtype.name == "string":
            return EncodedColumn(
                Encoding.DICTIONARY, dtype, n, np.zeros(n, dtype=np.int32),
                dictionary=np.array(self._dicts[col_idx], dtype=object),
                validity=validity, stats=stats)
        if dtype.name in ("array", "map"):
            return EncodedColumn(Encoding.OBJECT, dtype, n,
                                 np.full(n, None, dtype=object),
                                 validity=validity, stats=stats)
        if dtype.name == "boolean":
            return EncodedColumn(Encoding.BOOLEAN_BITSET, dtype, n,
                                 bitmask.pack(np.zeros(n, dtype=np.bool_)),
                                 validity=validity, stats=stats)
        # run-length [0]*n: one cell regardless of batch size (at-rest
        # bytes live in the HOST domain: np_dtype for decimals)
        return EncodedColumn(Encoding.RUN_LENGTH, dtype, n,
                             np.zeros(1, dtype=dtype.np_dtype
                                      if dtype.name == "decimal"
                                      else dtype.device_dtype()),
                             runs=np.array([n], dtype=np.int32),
                             validity=validity, stats=stats)

    def add_column(self, field: T.Field) -> None:
        """ALTER TABLE ADD COLUMN: existing rows read NULL. Existing
        batches get a constant-size all-null encoded column; the manifest
        version bump invalidates device caches and compiled plans."""
        with self._lock:
            idx = len(self.schema.fields)
            self.schema = T.Schema(tuple(self.schema.fields) + (field,))
            if field.dtype.name == "string":
                # non-empty shared dictionary so device LUTs over it are
                # never zero-sized (codes are masked null anyway)
                self._dicts[idx] = [""]
                self._dict_lookup[idx] = {"": 0}
            # the per-column complex-type dictionary families need
            # entries too, or the first device bind of an ALTER-added
            # column dies on a raw KeyError (review finding)
            if field.dtype.name == "array" \
                    and getattr(field.dtype, "element", None) is not None \
                    and field.dtype.element.name == "string":
                self._elem_dicts[idx] = []
                self._elem_lookup[idx] = {}
            if field.dtype.name == "map" \
                    and getattr(field.dtype, "key", None) is not None \
                    and field.dtype.key.name == "string":
                self._map_key_dicts[idx] = []
                self._map_key_lookup[idx] = {}
                if field.dtype.value.name == "string":
                    self._map_val_dicts[idx] = []
                    self._map_val_lookup[idx] = {}
            self._row_buffer.add_field(field)
            views = []
            for v in self._manifest.views:
                b = v.batch
                nb = dataclasses.replace(
                    b, columns=b.columns + (self._all_null_column(
                        idx, field.dtype, b.num_rows),))
                views.append(dataclasses.replace(v, batch=nb))
            self._publish(tuple(views))

    def drop_column(self, name: str) -> None:
        from snappydata_tpu.storage import mvcc

        # DROP COLUMN remaps the shared dictionaries IN PLACE and shifts
        # ordinals — state a pinned reader may be traversing right now.
        # Unlike TRUNCATE/ADD COLUMN (which publish fresh manifests and
        # leave pinned epochs intact) this cannot be made snapshot-safe,
        # so it fails typed-and-retryable while snapshots are active —
        # and ddl_scope blocks NEW pins for the remap's duration (a pin
        # admitted mid-remap would traverse half-shifted state)
        with mvcc.ddl_scope(self, "ALTER TABLE DROP COLUMN"), self._lock:
            idx = self.schema.index(name)
            if len(self.schema.fields) == 1:
                raise ValueError("cannot drop the only column")
            self.schema = T.Schema(tuple(
                f for i, f in enumerate(self.schema.fields) if i != idx))

            def remap(i):
                return i - 1 if i > idx else i

            self._dicts = {remap(i): d for i, d in self._dicts.items()
                           if i != idx}
            self._dict_lookup = {remap(i): d
                                 for i, d in self._dict_lookup.items()
                                 if i != idx}
            # remap the complex-type dictionary families the same way
            # (review finding: stale ordinals made a survivor column
            # intern into its neighbour's dictionary)
            for attr in ("_elem_dicts", "_elem_lookup", "_map_key_dicts",
                         "_map_key_lookup", "_map_val_dicts",
                         "_map_val_lookup", "_struct_dicts",
                         "_struct_lookup"):
                setattr(self, attr,
                        {remap(i): d
                         for i, d in getattr(self, attr).items()
                         if i != idx})
            self._row_buffer.drop_field(idx)
            views = []
            for v in self._manifest.views:
                b = v.batch
                nb = dataclasses.replace(b, columns=tuple(
                    c for i, c in enumerate(b.columns) if i != idx))
                deltas = tuple((remap(ci), hit, vals, vn)
                               for ci, hit, vals, vn in v.deltas if ci != idx)
                views.append(dataclasses.replace(v, batch=nb, deltas=deltas))
            self._publish(tuple(views))

    def force_rollover(self) -> None:
        with self._lock:
            views = list(self._manifest.views)
            views.extend(self._rollover_locked())
            self._publish(tuple(views))

    def update(self, predicate: Callable[[Dict[str, np.ndarray]], np.ndarray],
               assignments: Dict[str, Callable[[Dict[str, np.ndarray]], np.ndarray]],
               ) -> int:
        """UPDATE ... SET: write per-batch replacement deltas
        (ref ColumnUpdateExec → ColumnDelta entries) and mutate row-buffer
        rows in place. `predicate`/assignment callables take {col_name:
        decoded host values} and return bool mask / new values."""
        with self._lock:
            touched = 0
            new_views = []
            for view in self._manifest.views:
                cols = self._decode_all(view)
                hit = np.asarray(predicate(cols)) & view.live_mask()
                if not hit.any():
                    new_views.append(view)
                    continue
                touched += int(hit.sum())
                deltas = list(view.deltas)
                for name, fn in assignments.items():
                    ci = self.schema.index(name)
                    # locklint: callback-under-lock assignment evaluators
                    # are pure host functions over the captured arrays;
                    # they never touch storage locks or this table
                    raw = fn(cols)
                    values, vnulls = self._to_device_domain(
                        ci, raw, cols[self.schema.fields[ci].name])
                    deltas.append((ci, hit.copy(), values, vnulls))
                new_views.append(dataclasses.replace(view, deltas=tuple(deltas)))
            # row buffer in place
            rb_cols = self._row_buffer_dict()
            if rb_cols is not None:
                hit = np.asarray(predicate(rb_cols)) & \
                    self._row_buffer._valid[:self._row_buffer.count]
                if hit.any():
                    touched += int(hit.sum())
                    rb = self._row_buffer
                    for name, fn in assignments.items():
                        ci = self.schema.index(name)
                        col = rb._cols[ci][:rb.count]
                        # locklint: callback-under-lock assignment
                        # evaluators are pure host functions over the
                        # captured arrays (compiled by the executor);
                        # they never touch storage locks or this table
                        raw = fn(rb_cols)
                        if raw is None:  # SQL NULL assignment
                            if rb._nulls[ci] is None:
                                rb._nulls[ci] = np.zeros(rb.capacity,
                                                         dtype=np.bool_)
                            rb._nulls[ci][:rb.count][hit] = True
                            continue
                        vals = np.asarray(raw)
                        new = np.broadcast_to(
                            np.asarray(vals, dtype=col.dtype), col.shape)[hit] \
                            if vals.shape == () else vals[hit]
                        if ci in self._dicts:
                            # intern so device build can resolve the codes
                            self._intern_strings(
                                ci, np.asarray(new, dtype=object))
                        col[hit] = new
                        if rb._nulls[ci] is not None:
                            rb._nulls[ci][:rb.count][hit] = False
            self._publish(tuple(new_views))
            return touched

    def delete(self, predicate) -> int:
        """DELETE: new delete-mask arrays per batch (ref ColumnDeleteExec →
        ColumnDeleteDelta bitmap, meta column -3)."""
        with self._lock:
            touched = 0
            new_views = []
            for view in self._manifest.views:
                cols = self._decode_all(view)
                hit = np.asarray(predicate(cols)) & view.live_mask()
                if not hit.any():
                    new_views.append(view)
                    continue
                touched += int(hit.sum())
                mask = hit if view.delete_mask is None else (view.delete_mask | hit)
                new_views.append(dataclasses.replace(view, delete_mask=mask))
            rb_cols = self._row_buffer_dict()
            if rb_cols is not None:
                hit = np.asarray(predicate(rb_cols)) & \
                    self._row_buffer._valid[:self._row_buffer.count]
                if hit.any():
                    touched += int(hit.sum())
                    self._row_buffer._valid[:self._row_buffer.count][hit] = False
            self._publish(tuple(new_views))
            return touched

    def truncate(self) -> None:
        with self._lock:
            self._row_buffer.clear()
            self._publish(())

    # --- helpers ---------------------------------------------------------

    def _decode_all(self, view: BatchView) -> "LazyBatchColumns":
        """Lazily-decoding column mapping for mutation predicates: only the
        columns a predicate/assignment actually touches get decoded. String
        columns decode in CODE domain first (so update deltas — stored as
        codes — merge correctly), then map through the table dictionary."""
        return LazyBatchColumns(self, view)

    def _row_buffer_dict(self) -> Optional["_RowBufferCols"]:
        if self._row_buffer.count == 0:
            return None
        out = _RowBufferCols(
            {f.name: self._row_buffer._cols[i][:self._row_buffer.count]
             for i, f in enumerate(self.schema.fields)})
        out._rb = self._row_buffer
        out._schema = self.schema
        return out

    def _to_device_domain(self, col_idx: int, values,
                          like: np.ndarray
                          ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Assignment values → (device-domain array, null mask | None).
        Accepts python scalars (incl. None = SQL NULL) or arrays with
        None entries for string columns."""
        f = self.schema.fields[col_idx]
        shape = like.shape
        # deltas live in the HOST storage domain: dictionary CODES for
        # strings, plain float64 for decimals (the scaled-int64 form is
        # device-only, produced at bind — types.DecimalType docstring)
        if values is None:
            dt = np.int32 if f.dtype.name == "string" \
                else (f.dtype.np_dtype if f.dtype.name == "decimal"
                      else f.dtype.device_dtype())
            return (np.zeros(shape, dtype=dt),
                    np.ones(shape, dtype=np.bool_))
        values = np.asarray(values)
        if f.dtype.name == "string":
            vals = np.broadcast_to(values, shape) if values.shape == () \
                else values
            vals = np.asarray(vals, dtype=object)
            self._intern_strings(col_idx, vals)
            lookup = self._dict_lookup[col_idx]
            codes = np.fromiter(
                (lookup[v] if v is not None else 0 for v in vals),
                dtype=np.int32, count=len(vals))
            vnulls = np.fromiter((v is None for v in vals), dtype=np.bool_,
                                 count=len(vals))
            return codes, (vnulls if vnulls.any() else None)
        dt = f.dtype.np_dtype if f.dtype.name == "decimal" \
            else f.dtype.device_dtype()
        if values.shape == ():
            return np.full(shape, values, dtype=dt), None
        return values.astype(dt), None


class LazyBatchColumns:
    """dict-like {column name -> decoded host values} that decodes on first
    access (review finding: eager decode of every column made single-column
    DELETEs O(num_cols))."""

    def __init__(self, data: "ColumnTableData", view: BatchView):
        self._data = data
        self._view = view
        self._cache: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        got = self._cache.get(name)
        if got is None:
            i = self._data.schema.index(name)
            f = self._data.schema.fields[i]
            if f.dtype.name == "string":
                codes = self._view.decoded_column(i, strings=False)
                dictionary = self._data.dictionary(i)
                if dictionary is None or dictionary.size == 0:
                    got = np.full(codes.shape, None, dtype=object)
                else:
                    got = dictionary[np.clip(codes, 0, dictionary.size - 1)]
            else:
                got = self._view.decoded_column(i)
            self._cache[name] = got
        return got

    def null_mask(self, name: str) -> Optional[np.ndarray]:
        """Delta-aware SQL-NULL mask for one column (the delete-capture
        path needs it: view subtraction must skip the same values the
        original fold skipped)."""
        return self._view.null_mask(self._data.schema.index(name))

    def live_mask(self) -> np.ndarray:
        """Rows a DELETE can actually remove (excludes capacity padding
        and already-deleted rows) — the delete-capture path must
        intersect with this or a re-matching predicate would subtract
        dead/padded rows from dependent views a second time."""
        return self._view.live_mask()

    def keys(self):
        return self._data.schema.names()


class _RowBufferCols(dict):
    """Row-buffer column mapping for mutation predicates, carrying the
    buffer's null masks so delete-capture sees SQL NULLs exactly."""

    _rb = None
    _schema = None

    def null_mask(self, name: str) -> Optional[np.ndarray]:
        if self._rb is None:
            return None
        i = self._schema.index(name)
        m = self._rb._nulls[i]
        return m[:self._rb.count] if m is not None else None

    def live_mask(self) -> Optional[np.ndarray]:
        if self._rb is None:
            return None
        return self._rb._valid[:self._rb.count]


class _LiveRowCols(dict):
    """Row-table column mapping for delete predicates, carrying the
    live-row mask so delete-capture skips already-deleted rows, and the
    SQL-NULL masks so captured subtraction skips exactly the values the
    original fold skipped (None coerces to NaN/garbage in the typed
    arrays — without the mask a view would subtract a phantom non-null
    contribution)."""

    _live = None
    _nulls = None

    def live_mask(self) -> Optional[np.ndarray]:
        return self._live

    def null_mask(self, name: str) -> Optional[np.ndarray]:
        if self._nulls is None:
            return None
        return self._nulls.get(name)


class RowTableData:
    """Storage for a ROW table: pure host-RAM rows with optional primary-key
    hash index for point ops that bypass the XLA engine entirely (ref:
    ExecutionEngineArbiter routing, docs/architecture/
    cluster_architecture.md:31-33; row store GemFireContainer rows)."""

    def __init__(self, schema: T.Schema, key_columns: Sequence[str] = ()):
        self.schema = schema
        self.key_columns = [k.lower() for k in key_columns]
        self._key_idx = [schema.index(k) for k in self.key_columns]
        self._lock = locks.named_lock("storage.row_table")
        self._cols: List[List] = [[] for _ in schema.fields]
        self._live: List[bool] = []
        self._pk: Dict[tuple, int] = {}
        self._version = 0
        self.on_insert = []

    @property
    def version(self) -> int:
        return self._version

    def insert_arrays(self, arrays: Sequence[np.ndarray]) -> int:
        from snappydata_tpu.storage import hoststore

        hoststore.check_critical_memory()
        arrays = [np.asarray(a) for a in arrays]
        n = int(arrays[0].shape[0])
        with self._lock:
            if self._key_idx:
                # validate the whole batch before touching state so a PK
                # violation leaves the table unchanged (atomic insert)
                seen = set()
                for i in range(n):
                    key = tuple(arrays[j][i] for j in self._key_idx)
                    old = self._pk.get(key)
                    if (old is not None and self._live[old]) or key in seen:
                        raise ValueError(f"primary key violation: {key}")
                    seen.add(key)
            for i in range(n):
                row = tuple(a[i] for a in arrays)
                self._append_row(row, upsert=False)
            self._version += 1
        for cb in self.on_insert:
            cb(arrays, None)
        return n

    def put_arrays(self, arrays: Sequence[np.ndarray]) -> int:
        """PUT INTO upsert by primary key (ref: SnappySession.put:2024)."""
        arrays = [np.asarray(a) for a in arrays]
        n = int(arrays[0].shape[0])
        with self._lock:
            for i in range(n):
                row = tuple(a[i] for a in arrays)
                self._append_row(row, upsert=True)
            self._version += 1
        return n

    def _append_row(self, row: tuple, upsert: bool) -> None:
        if self._key_idx:
            key = tuple(row[i] for i in self._key_idx)
            old = self._pk.get(key)
            if old is not None and self._live[old]:
                if not upsert:
                    raise ValueError(f"primary key violation: {key}")
                self._live[old] = False
            self._pk[key] = len(self._live)
        for c, v in zip(self._cols, row):
            c.append(v)
        self._live.append(True)

    def get(self, key: tuple):
        """Point lookup — the fast path that never enters the query engine."""
        ordinal = self._pk.get(tuple(key))
        if ordinal is None or not self._live[ordinal]:
            return None
        return tuple(c[ordinal] for c in self._cols)

    def to_arrays(self) -> Tuple[List[np.ndarray], int]:
        arrays, _nulls, n = self.to_arrays_with_nulls()
        return arrays, n

    def to_arrays_with_nulls(self):
        """(arrays, null masks, count): rows store python values incl.
        None; numeric Nones fill as 0 with the mask set."""
        with self._lock:
            live = np.array(self._live, dtype=np.bool_)
            out: List[np.ndarray] = []
            masks: List[Optional[np.ndarray]] = []
            for f, c in zip(self.schema.fields, self._cols):
                nm = np.array([v is None for v in c], dtype=np.bool_)
                if f.dtype.name == "string":
                    arr = np.array(c, dtype=object)
                else:
                    arr = np.array([0 if v is None else v for v in c],
                                   dtype=f.dtype.np_dtype)
                if len(live):
                    arr = arr[live]
                    nm = nm[live]
                out.append(arr)
                masks.append(nm if nm.any() else None)
            n = int(live.sum()) if len(live) else 0
            return out, masks, n

    def update(self, predicate, assignments) -> int:
        with self._lock:
            cols = {f.name: np.array(c, dtype=f.dtype.np_dtype)
                    for f, c in zip(self.schema.fields, self._cols)}
            if not self._live:
                return 0
            hit = np.asarray(predicate(cols)) & np.array(self._live)
            for name, fn in assignments.items():
                ci = self.schema.index(name)
                # locklint: callback-under-lock assignment evaluators are
                # pure host functions over the captured arrays; they
                # never touch storage locks or this table
                vals = np.asarray(fn(cols))
                for ordinal in np.flatnonzero(hit):
                    v = vals if vals.shape == () else vals[ordinal]
                    self._cols[ci][ordinal] = v.item() if hasattr(v, "item") else v
            if self._key_idx and any(self.schema.index(n) in self._key_idx
                                     for n in assignments):
                self._rebuild_pk_locked()
            self._version += 1
            return int(hit.sum())

    def _rebuild_pk_locked(self) -> None:
        """Key-column updates invalidate the hash index; rebuild and verify
        uniqueness (raising restores nothing — callers treat it as a
        constraint violation surfaced post-hoc, like the reference's row
        store would on a key change)."""
        pk: Dict[tuple, int] = {}
        for ordinal, live in enumerate(self._live):
            if not live:
                continue
            key = tuple(self._cols[i][ordinal] for i in self._key_idx)
            if key in pk:
                raise ValueError(f"primary key violation after update: {key}")
            pk[key] = ordinal
        self._pk = pk

    def delete(self, predicate) -> int:
        with self._lock:
            if not self._live:
                return 0
            typed, nmasks = {}, {}
            for f, c in zip(self.schema.fields, self._cols):
                if any(v is None for v in c):
                    m = np.fromiter((v is None for v in c),
                                    dtype=np.bool_, count=len(c))
                    nmasks[f.name] = m
                    dt = f.dtype.np_dtype
                    if dt != np.dtype(object):
                        # NaN keeps float predicate semantics (NULL
                        # never compares equal); other dtypes can't
                        # hold a sentinel, so 0-fill + the mask above.
                        # Object (string) columns keep embedded None.
                        fill = (np.nan if np.issubdtype(dt, np.floating)
                                else 0)
                        c = [fill if v is None else v for v in c]
                typed[f.name] = np.array(c, dtype=f.dtype.np_dtype)
            cols = _LiveRowCols(typed)
            cols._live = np.array(self._live)
            cols._nulls = nmasks or None
            hit = np.asarray(predicate(cols)) & np.array(self._live)
            for ordinal in np.flatnonzero(hit):
                self._live[ordinal] = False
                if self._key_idx:
                    key = tuple(self._cols[i][ordinal] for i in self._key_idx)
                    if self._pk.get(key) == ordinal:
                        del self._pk[key]
            self._version += 1
            return int(hit.sum())

    def truncate(self) -> None:
        with self._lock:
            self._cols = [[] for _ in self.schema.fields]
            self._live = []
            self._pk = {}
            self._version += 1

    def count(self) -> int:
        return int(sum(self._live))

    def add_column(self, field: T.Field) -> None:
        """ALTER TABLE ADD COLUMN (ref SnappySession.alterTable:1628):
        existing rows read NULL for the new column."""
        with self._lock:
            n = len(self._live)
            self.schema = T.Schema(tuple(self.schema.fields) + (field,))
            self._cols.append([None] * n)
            self._version += 1

    def drop_column(self, name: str) -> None:
        from snappydata_tpu.storage import mvcc

        # row tables mutate columns in place: a pinned reader that has
        # not yet captured its host snapshot would resolve stale
        # ordinals against the shifted layout — same typed refusal as
        # the column-table form, and the same new-pin fence for the
        # shift's duration
        with mvcc.ddl_scope(self, "ALTER TABLE DROP COLUMN"), self._lock:
            idx = self.schema.index(name)
            if len(self.schema.fields) == 1:
                raise ValueError("cannot drop the only column")
            if idx in self._key_idx:
                raise ValueError(f"cannot drop primary key column {name}")
            for iname, icols in getattr(self, "_indexes", {}).items():
                if name.lower() in icols:
                    raise ValueError(
                        f"column {name} is referenced by index {iname}")
            self.schema = T.Schema(tuple(
                f for i, f in enumerate(self.schema.fields) if i != idx))
            del self._cols[idx]
            self._key_idx = [i - 1 if i > idx else i for i in self._key_idx]
            self._version += 1

    def create_index(self, name: str, columns: Sequence[str]) -> None:
        """Secondary index (ref: row-store indexes, CreateIndexTest).
        Lazily rebuilt per version — point lookups are O(1) after the
        first access following a mutation."""
        if not hasattr(self, "_indexes"):
            self._indexes: Dict[str, tuple] = {}
            self._index_maps: Dict[str, tuple] = {}
        self._indexes[name.lower()] = tuple(c.lower() for c in columns)

    def drop_index(self, name: str) -> None:
        getattr(self, "_indexes", {}).pop(name.lower(), None)
        getattr(self, "_index_maps", {}).pop(name.lower(), None)

    def index_for_columns(self, columns: Sequence[str]):
        want = {c.lower() for c in columns}
        for name, cols in getattr(self, "_indexes", {}).items():
            if set(cols) == want:
                return name
        return None

    def index_lookup(self, name: str, key: tuple) -> List[tuple]:
        """All live rows whose indexed columns equal `key`."""
        cols = self._indexes[name.lower()]
        cached = getattr(self, "_index_maps", {}).get(name.lower())
        if cached is None or cached[0] != self._version:
            idx_cols = [self.schema.index(c) for c in cols]
            mapping: Dict[tuple, List[int]] = {}
            with self._lock:
                for ordinal, live in enumerate(self._live):
                    if live:
                        k = tuple(self._cols[i][ordinal] for i in idx_cols)
                        mapping.setdefault(k, []).append(ordinal)
                cached = (self._version, mapping)
            self._index_maps[name.lower()] = cached
        ordinals = cached[1].get(tuple(key), [])
        return [tuple(c[o] for c in self._cols) for o in ordinals]

    def string_dict(self, col_idx: int) -> "np.ndarray":
        """Version-cached sorted dictionary for a string column, so device
        binding and result assembly agree on codes within one version."""
        with self._lock:
            cache = getattr(self, "_sdict_cache", None)
            if cache is None or cache[0] != self._version:
                cache = (self._version, {})
                self._sdict_cache = cache
            if col_idx not in cache[1]:
                vals = [v for v, live in zip(self._cols[col_idx], self._live)
                        if live]
                d = np.unique(np.array(
                    [v if v is not None else "" for v in vals],
                    dtype=object)) if vals else np.empty(0, dtype=object)
                cache[1][col_idx] = d
            return cache[1][col_idx]
