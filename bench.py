"""Headline benchmark: TPC-H Q1 + Q6 scan+aggregate throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`python bench.py --check [candidate.json]` instead compares a bench
result against the previous BENCH_r*.json record and exits nonzero when
the Q1/Q6 geomean or load_s regresses beyond tolerance — the CI guard
that keeps either from silently sliding again (the r04→r05 load_s 4×
record turned out to be bench-machine contention, but nothing TRIPPED).
With no candidate argument it checks the newest record against the one
before it.  Tolerances (fractional, env-overridable): geomean may drop
up to SNAPPY_BENCH_GEOMEAN_TOL (default 0.35 — measured machine noise
on this container is ~25%), load_s may grow up to
SNAPPY_BENCH_LOAD_TOL (default 1.0, i.e. 2× — the r05 slide was 2.9×),
and the serving axis's detail.qps.prepared_qps may drop up to
SNAPPY_BENCH_QPS_TOL (default 0.5 — concurrency benches are noisier
than single-stream scans; skipped against pre-qps records).

Baseline context (BASELINE.md): the reference's headline claim is the
quickstart scan+group-by over a 100M-row column table at 16-20x a Spark
2.1.1 cached DataFrame on a laptop-class JVM (docs/quickstart/
performance_apache_spark.md:2-6). No absolute rows/sec is published
in-repo; we peg the baseline at 66M rows/s (100M rows in ~1.5s, the
midpoint implied by that scenario) and report vs_baseline against it.

Scale via SNAPPY_BENCH_SF (default 16.0 → 96M lineitem rows, matching the
reference's 100M-row quickstart scenario; ~2.7GB of touched columns in
HBM, ~2min load through the native ingest path).

Round-1 result on one v5e chip: 1.02B rows/s geomean (Q1 827M, Q6 1.25B),
vs_baseline 15.4.
"""

import json
import os
import sys
import time

import numpy as np


def _probe_backend(timeout_s: float, attempts: int):
    """Verify the accelerator backend ONCE, up front, in a SUBPROCESS —
    never lazily mid-ingest (round-1 failure mode: the axon TPU relay went
    'Unavailable' ~2min into the load and a per-query backend probe crashed
    the run; a sick relay can also HANG backend init >300s while holding
    jax's global backend lock, which would poison this process too).
    Returns the platform name, or None if the accelerator is unreachable."""
    import subprocess

    code = ("import jax, json, jax.numpy as jnp; d = jax.devices(); "
            "jax.device_get(jnp.arange(4) + 1); "
            "print(json.dumps({'platform': d[0].platform, 'n': len(d)}))")
    for attempt in range(1, attempts + 1):
        try:
            proc = subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, timeout=timeout_s,
                                  text=True)
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe attempt {attempt}/{attempts} hung "
                  f">{timeout_s}s (accelerator relay down?)",
                  file=sys.stderr, flush=True)
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            info = json.loads(proc.stdout.strip().splitlines()[-1])
            print(f"bench: backend ready — {info['n']}x {info['platform']}",
                  file=sys.stderr, flush=True)
            return info["platform"]
        print(f"bench: backend probe attempt {attempt}/{attempts} failed: "
              f"{(proc.stderr or '').strip()[-400:]}",
              file=sys.stderr, flush=True)
        time.sleep(min(10.0, 2.0 * attempt))
    return None


def check_regression(candidate: dict, baseline: dict,
                     geomean_tol: float = 0.35,
                     load_tol: float = 1.0,
                     qps_tol: float = 0.5,
                     resident_tol: float = 0.25,
                     trace_tol: float = 3.0,
                     htap_tol: float = 10.0,
                     mesh_eff: float = 0.7,
                     outofcore_ratio: float = 0.5,
                     fault_recovery: float = 1.0,
                     code_agg_ratio: float = 0.8) -> list:
    """Pure comparison used by `--check`: returns a list of human-readable
    failure strings (empty = no regression).  `candidate`/`baseline` are
    bench result records ({"value", "detail": {"load_s", ...}}).  The
    serving axis guards like the others: detail.qps.prepared_qps may drop
    at most qps_tol vs the previous record (skipped when either record
    predates the qps section — older BENCH_r*.json stay comparable).
    Compressed-domain guards (skipped on pre-compressed records): the
    stock workload must keep batches_device_decoded > 0 AND
    code_domain_predicates > 0 (the scan path actually ran over encoded
    batches), and detail.compressed.resident_bytes_per_row may grow at
    most resident_tol vs the previous record — the capacity win can't
    silently slide back to decoded plates."""
    # driver-written BENCH_r*.json wraps the bench's own record under
    # "parsed" (alongside the runner's cmd/rc/tail); accept either shape
    candidate = candidate.get("parsed") or candidate
    baseline = baseline.get("parsed") or baseline
    fails = []
    new_v, old_v = candidate.get("value"), baseline.get("value")
    if isinstance(new_v, (int, float)) and isinstance(old_v, (int, float)) \
            and old_v > 0 and new_v < old_v * (1.0 - geomean_tol):
        fails.append(
            f"geomean rows/s regressed {old_v:,.0f} -> {new_v:,.0f} "
            f"({new_v / old_v - 1.0:+.1%}; tolerance -{geomean_tol:.0%})")
    new_l = (candidate.get("detail") or {}).get("load_s")
    old_l = (baseline.get("detail") or {}).get("load_s")
    if isinstance(new_l, (int, float)) and isinstance(old_l, (int, float)) \
            and old_l > 0 and new_l > old_l * (1.0 + load_tol):
        fails.append(
            f"load_s regressed {old_l} -> {new_l} "
            f"({new_l / old_l - 1.0:+.1%}; tolerance +{load_tol:.0%})")
    new_q = (((candidate.get("detail") or {}).get("qps")) or {}) \
        .get("prepared_qps")
    old_q = (((baseline.get("detail") or {}).get("qps")) or {}) \
        .get("prepared_qps")
    if isinstance(new_q, (int, float)) and isinstance(old_q, (int, float)) \
            and old_q > 0 and new_q < old_q * (1.0 - qps_tol):
        fails.append(
            f"prepared_qps regressed {old_q:,.0f} -> {new_q:,.0f} "
            f"({new_q / old_q - 1.0:+.1%}; tolerance -{qps_tol:.0%})")
    # --- compressed-domain axes (skipped on records predating them) -----
    comp = ((candidate.get("detail") or {}).get("compressed")) or {}
    if comp and "error" not in comp:
        dd = ((candidate.get("detail") or {}).get("device_decode")) or {}
        if not dd.get("batches_device_decoded"):
            fails.append("batches_device_decoded is 0 — the default scan "
                         "path stopped engaging device decode")
        if not comp.get("code_domain_predicates"):
            fails.append("code_domain_predicates is 0 — the stock TPC-H "
                         "workload stopped evaluating predicates in the "
                         "code domain")
        new_r = comp.get("resident_bytes_per_row")
        old_r = (((baseline.get("detail") or {}).get("compressed")) or {}) \
            .get("resident_bytes_per_row")
        if isinstance(new_r, (int, float)) and \
                isinstance(old_r, (int, float)) and old_r > 0 \
                and new_r > old_r * (1.0 + resident_tol):
            fails.append(
                f"resident_bytes_per_row regressed {old_r} -> {new_r} "
                f"({new_r / old_r - 1.0:+.1%}; tolerance "
                f"+{resident_tol:.0%})")
        # aggregate-on-codes lane (skipped on records predating it):
        # all three lane counters must fire on the stock workload, and
        # measured throughput must reach code_agg_ratio of what the
        # decode-throughput law predicts from the decoded run
        ca = comp.get("code_agg") or {}
        if ca and "error" not in ca:
            lanes = ca.get("lane_counters") or {}
            for k in ("agg_code_domain", "agg_dict_space",
                      "agg_rle_runs"):
                if not lanes.get(k):
                    fails.append(
                        f"{k} is 0 — the aggregate-on-codes lane "
                        f"stopped engaging on the stock workload")
            meas = ca.get("grouped_rows_per_s_auto")
            pred = ca.get("predicted_rows_per_s")
            if isinstance(meas, (int, float)) and \
                    isinstance(pred, (int, float)) and pred > 0 \
                    and meas < pred * code_agg_ratio:
                fails.append(
                    f"aggregate-on-codes {meas:,.0f} rows/s is below "
                    f"{code_agg_ratio:.0%} of the decode-throughput-law "
                    f"prediction {pred:,.0f}")
    # --- tracing-overhead axis (skipped on records predating it) --------
    # enabling request tracing must cost < trace_tol percent on the
    # stock Q1/Q6 geomean — the span layer stays cheap enough to leave
    # ON in production (candidate-only: an absolute bound, no baseline)
    trc = ((candidate.get("detail") or {}).get("tracing")) or {}
    ov = trc.get("overhead_pct")
    if isinstance(ov, (int, float)) and ov > trace_tol:
        fails.append(
            f"tracing overhead {ov:.2f}% exceeds {trace_tol:.2f}% on the "
            f"stock workload geomean (on={trc.get('geomean_on')}, "
            f"off={trc.get('geomean_off')} rows/s)")
    # --- HTAP axis (skipped on records predating it) --------------------
    # concurrent scan+ingest is the MVCC claim: every snapshot read must
    # be value-correct (mismatches are a hard fail, candidate-only), and
    # the concurrent scan p50 may blow up at most htap_tol× over the
    # serialized baseline's p50 — isolation can't silently regress into
    # readers stalling behind the write path again (p99 stays unguarded:
    # it legitimately absorbs a batch-bucket re-specialization)
    ht = ((candidate.get("detail") or {}).get("htap")) or {}
    if ht and "error" not in ht:
        if ht.get("value_mismatches"):
            fails.append(
                f"htap snapshot reads diverged from the serialized "
                f"replay ({ht['value_mismatches']} mismatches)")
        new_p = (ht.get("concurrent") or {}).get("scan_p50_ms")
        ser_p = (ht.get("serialized") or {}).get("scan_p50_ms")
        if isinstance(new_p, (int, float)) and \
                isinstance(ser_p, (int, float)) and ser_p > 0 \
                and new_p > ser_p * htap_tol:
            fails.append(
                f"htap concurrent scan p50 {new_p}ms exceeds "
                f"{htap_tol:.0f}x the serialized baseline ({ser_p}ms) — "
                f"scans are stalling behind ingest again")
    # --- out-of-core axis (skipped on records predating it) -------------
    # the tiered-storage claim: capping the device budget below 10% of
    # the table must stream answers that are VALUE-IDENTICAL (hard
    # fail), the double buffer must actually overlap upload with
    # compute (prefetch_overlap_ms > 0), and the constricted scan keeps
    # >= outofcore_ratio of the in-HBM rows/s (candidate-only guards)
    oc = ((candidate.get("detail") or {}).get("outofcore")) or {}
    if oc and "error" not in oc:
        if oc.get("value_mismatches"):
            fails.append(
                f"out-of-core answers diverged from in-HBM "
                f"({oc['value_mismatches']} mismatches)")
        if not oc.get("prefetch_overlap_ms"):
            fails.append("prefetch_overlap_ms is 0 — the double-buffered "
                         "prefetcher never overlapped an upload with "
                         "compute on the constricted scan")
        ratio = oc.get("throughput_ratio")
        if isinstance(ratio, (int, float)) and ratio < outofcore_ratio:
            fails.append(
                f"out-of-core throughput ratio {ratio} below "
                f"{outofcore_ratio} of in-HBM "
                f"({oc.get('outofcore_rows_per_s')} vs "
                f"{oc.get('inhbm_rows_per_s')} rows/s at "
                f"{oc.get('budget_fraction')} device budget)")
    # --- mesh axis (skipped on records predating it) --------------------
    # sharded execution is the scale claim: every mesh answer must equal
    # single-device (hard fail), the shard_map lane must actually run,
    # per-device scaling efficiency at 8 devices (aggregate-throughput
    # retention on a serialized-core rig) must hold >= mesh_eff, and the
    # sharded per-device residency must stay at ENCODED parity with the
    # single-device number (candidate-only guards — the whole section
    # is self-contained evidence)
    mc = ((candidate.get("detail") or {}).get("multichip")) or {}
    if mc and "error" not in mc:
        if mc.get("value_mismatches"):
            fails.append(
                f"multichip sharded answers diverged from single-device "
                f"({mc['value_mismatches']} mismatches)")
        if not mc.get("mesh_shard_execs"):
            fails.append("mesh_shard_execs is 0 — the shard_map partial "
                         "lane never ran on the mesh workload")
        e8 = (mc.get("scaling_efficiency") or {}).get("8")
        if isinstance(e8, (int, float)) and e8 < mesh_eff:
            fails.append(
                f"mesh scaling efficiency at 8 devices {e8} below "
                f"{mesh_eff} (per-device throughput retention)")
        shr = mc.get("resident_bytes_per_row_sharded")
        sgl = mc.get("resident_bytes_per_row_single")
        if isinstance(shr, (int, float)) and isinstance(sgl, (int, float)) \
                and sgl > 0 and shr > sgl * (1.0 + resident_tol):
            fails.append(
                f"sharded resident bytes/row {shr} exceeds single-device "
                f"{sgl} by more than {resident_tol:.0%} — sharded tables "
                f"stopped staying encoded per device")
    # --- fault-storm axis (skipped on records predating it) -------------
    # the self-healing claim: every fault the seeded storm injects must
    # end in recovery or a typed retryable error — never a wrong row
    # (value_mismatches is a hard fail) and never unaccounted
    # (recovered + typed_errors >= fault_recovery * injected, default
    # 1.0 via SNAPPY_BENCH_FAULT_RECOVERY — fully accounted)
    fs = ((candidate.get("detail") or {}).get("faultstorm")) or {}
    if fs and "error" not in fs:
        if fs.get("value_mismatches"):
            fails.append(
                f"fault storm produced wrong rows "
                f"({fs['value_mismatches']} value mismatches: "
                f"{(fs.get('unexpected') or ['?'])[:3]})")
        if fs.get("unexpected"):
            fails.append(
                f"fault storm hit untyped/unaccounted failures: "
                f"{fs['unexpected'][:3]}")
        ratio = fs.get("recovery_ratio")
        if isinstance(ratio, (int, float)) and fs.get("injected") \
                and ratio < fault_recovery:
            fails.append(
                f"fault storm recovery ratio {ratio} below "
                f"{fault_recovery} ({fs.get('accounted')} of "
                f"{fs.get('injected')} injected faults accounted as "
                f"recovered or typed-retryable)")
    return fails


def _bench_records(root: str) -> list:
    """BENCH_r*.json paths in round order."""
    import glob
    import re

    paths = glob.glob(os.path.join(root, "BENCH_r*.json"))
    return sorted(paths, key=lambda p: int(
        re.search(r"BENCH_r(\d+)", p).group(1)))


def run_check(argv: list) -> int:
    root = os.path.dirname(os.path.abspath(__file__))
    records = _bench_records(root)
    if argv:
        cand_path = argv[0]
        # baseline = newest record that is NOT the candidate itself: a
        # just-written BENCH_r*.json checked by path must compare against
        # its predecessor, never against itself (always-pass)
        cand_real = os.path.realpath(cand_path)
        others = [p for p in records
                  if os.path.realpath(p) != cand_real]
        base_path = others[-1] if others else None
    else:
        cand_path = records[-1] if len(records) >= 2 else None
        base_path = records[-2] if len(records) >= 2 else None
    if cand_path is None or base_path is None:
        print("bench --check: need at least two records (or a candidate "
              "file + one BENCH_r*.json)", file=sys.stderr)
        return 2
    with open(cand_path) as fh:
        candidate = json.load(fh)
    with open(base_path) as fh:
        baseline = json.load(fh)
    fails = check_regression(
        candidate, baseline,
        geomean_tol=float(os.environ.get("SNAPPY_BENCH_GEOMEAN_TOL",
                                         "0.35")),
        load_tol=float(os.environ.get("SNAPPY_BENCH_LOAD_TOL", "1.0")),
        qps_tol=float(os.environ.get("SNAPPY_BENCH_QPS_TOL", "0.5")),
        resident_tol=float(os.environ.get("SNAPPY_BENCH_RESIDENT_TOL",
                                          "0.25")),
        trace_tol=float(os.environ.get("SNAPPY_BENCH_TRACE_TOL", "3.0")),
        htap_tol=float(os.environ.get("SNAPPY_BENCH_HTAP_TOL", "10.0")),
        mesh_eff=float(os.environ.get("SNAPPY_BENCH_MESH_EFF", "0.7")),
        outofcore_ratio=float(os.environ.get(
            "SNAPPY_BENCH_OUTOFCORE_RATIO", "0.5")),
        fault_recovery=float(os.environ.get(
            "SNAPPY_BENCH_FAULT_RECOVERY", "1.0")),
        code_agg_ratio=float(os.environ.get(
            "SNAPPY_BENCH_CODE_AGG_RATIO", "0.8")))
    rel = os.path.basename
    if fails:
        for f in fails:
            print(f"bench --check FAIL ({rel(cand_path)} vs "
                  f"{rel(base_path)}): {f}", file=sys.stderr)
        return 1
    print(f"bench --check OK: {rel(cand_path)} within tolerance of "
          f"{rel(base_path)}", file=sys.stderr)
    return 0


def main() -> None:
    repeats = int(os.environ.get("SNAPPY_BENCH_REPEATS", "5"))

    platform = _probe_backend(
        timeout_s=float(os.environ.get("SNAPPY_BENCH_INIT_TIMEOUT", "120")),
        attempts=int(os.environ.get("SNAPPY_BENCH_INIT_ATTEMPTS", "3")))
    tpu_unreachable = platform is None
    if tpu_unreachable:
        # The record must still be green and honest: run on CPU, say so.
        print("bench: WARNING — accelerator unreachable; falling back to "
              "CPU (result will carry tpu_unreachable=true)",
              file=sys.stderr, flush=True)
        import jax

        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    sf_default = "4.0" if platform == "cpu" else "16.0"
    sf = float(os.environ.get("SNAPPY_BENCH_SF", sf_default))

    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.utils import tpch

    # pin the dtype policy NOW so nothing re-queries backend state mid-run
    config.global_properties().decimal_as_float64 = platform == "cpu"

    # TPU smoke: one small query compiled + executed + VALUE-ASSERTED on
    # the real backend before the big load, so numeric regressions surface
    # here with a clear message instead of as a wrong headline number
    smoke = SnappySession(catalog=Catalog())
    smoke.sql("CREATE TABLE smoke (g BIGINT, v DOUBLE) USING column")
    smoke.insert_arrays("smoke", [
        np.arange(1000, dtype=np.int64) % 4,
        np.arange(1000, dtype=np.float64)])
    row = smoke.sql("SELECT g, count(*), sum(v) FROM smoke GROUP BY g "
                    "ORDER BY g").rows()
    assert [r[0] for r in row] == [0, 1, 2, 3], row
    assert all(r[1] == 250 for r in row), row
    exp = [float(sum(range(g, 1000, 4))) for g in range(4)]
    for r, e in zip(row, exp):
        assert abs(r[2] - e) <= 1e-6 * e, (r, e)
    print(f"bench: {platform} smoke OK (grouped agg value-asserted)",
          file=sys.stderr, flush=True)

    s = SnappySession(catalog=Catalog())
    t0 = time.time()
    tpch.load_tpch(s, sf=sf, seed=17)
    load_s = time.time() - t0
    n_rows = s.catalog.lookup_table("lineitem").data.snapshot().total_rows()

    # ---- full-value Q1 assertion against an exact float64 oracle -------
    # (round-3 verdict task 2: the shipping TPU dtype policy — f32 plates
    # + f64 accumulators — must keep TPC-H aggregates within 1e-6)
    q1_max_rel_err = _assert_q1_values(s, sf)
    print(f"bench: Q1 full-value check OK (max rel err "
          f"{q1_max_rel_err:.2e})", file=sys.stderr, flush=True)

    from snappydata_tpu.observability.metrics import global_registry

    timings = {}
    agg_detail = {}
    for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
        s.sql(q)  # compile + first run
        c0 = dict(global_registry().snapshot()["counters"])
        best = float("inf")
        for _ in range(repeats):
            t0 = time.time()
            s.sql(q)
            best = min(best, time.time() - t0)
        timings[name] = best
        # chosen reduction strategy + fused-pass counts, so the bench
        # trajectory explains ITSELF (which strategy the auto table
        # picked, whether the group-index cache carried the repeats)
        c1 = global_registry().snapshot()["counters"]

        def delta(key):
            return c1.get(key, 0) - c0.get(key, 0)

        agg_detail[name] = {
            "reduce_passes_per_run":
                round(delta("agg_reduce_passes") / repeats, 2),
            "strategies": {
                st: delta(f"agg_strategy_{st}")
                for st in ("unroll", "scatter", "matmul", "pallas")
                if delta(f"agg_strategy_{st}")},
            "gidx_cache_hits": delta("gidx_cache_hits"),
            "gidx_cache_misses": delta("gidx_cache_misses"),
        }

    # ---- tracing: per-query phase breakdown + enabling-cost guard ------
    # one traced run per headline query pulls the span tree apart into
    # compile/bind/execute/transfer seconds (device_execute ≈ async
    # dispatch; transfer absorbs the compute wait — see executor notes),
    # then the SAME best-of-repeats loop re-runs with tracing disabled:
    # the on-vs-off geomean delta is the enabling cost `--check` guards
    # at < SNAPPY_BENCH_TRACE_TOL percent (default 3)
    from snappydata_tpu.observability import tracing as _tracing

    props = config.global_properties()
    saved_tracing = props.tracing_enabled
    phases_detail = {}
    try:
        props.tracing_enabled = True   # phase capture needs a trace
        for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
            s.sql(q)
            tr = _tracing.ring().last()
            ph = tr.phase_seconds() if tr is not None else {}
            phases_detail[name] = {
                "compile_s": round(ph.get("compile", 0.0)
                                   + ph.get("jit_compile", 0.0), 6),
                "bind_s": round(ph.get("bind", 0.0), 6),
                "execute_s": round(ph.get("device_execute", 0.0), 6),
                "transfer_s": round(ph.get("transfer", 0.0), 6),
            }
    except Exception as e:
        phases_detail = {"error": str(e)}
    finally:
        props.tracing_enabled = saved_tracing

    tracing_detail = None
    try:
        # measure BOTH legs explicitly (never reuse the headline loop:
        # it ran under whatever the operator configured) and restore
        # the configured value, whatever it was
        legs = {}
        try:
            for flag in (True, False):
                props.tracing_enabled = flag
                dest = legs.setdefault(flag, {})
                for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
                    s.sql(q)
                    best = float("inf")
                    for _ in range(repeats):
                        t0 = time.time()
                        s.sql(q)
                        best = min(best, time.time() - t0)
                    dest[name] = best
        finally:
            props.tracing_enabled = saved_tracing
        geo_on = float(np.sqrt((n_rows / legs[True]["q1"])
                               * (n_rows / legs[True]["q6"])))
        geo_off = float(np.sqrt((n_rows / legs[False]["q1"])
                                * (n_rows / legs[False]["q6"])))
        tracing_detail = {
            "geomean_on": round(geo_on, 1),
            "geomean_off": round(geo_off, 1),
            "overhead_pct":
                round(max(0.0, (geo_off - geo_on) / geo_off * 100.0), 3),
        }
        print(f"bench: tracing overhead "
              f"{tracing_detail['overhead_pct']}% (on "
              f"{geo_on:,.0f} vs off {geo_off:,.0f} rows/s geomean)",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: tracing overhead bench failed: {e}",
              file=sys.stderr, flush=True)
        tracing_detail = {"error": str(e)}

    # ---- device-only timings (jitted fn on resident arrays) ------------
    # separates XLA execute time from the session/bind/host overhead the
    # end-to-end numbers include (round-2/3 instrumentation ask)
    device = {}
    for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
        try:
            device[name] = _device_only_best(s, q, repeats)
        except Exception as e:  # instrumentation must not kill the bench
            print(f"bench: device-only timing for {name} failed: {e}",
                  file=sys.stderr, flush=True)
            device[name] = None

    # Compressed-domain evidence: code-domain predicates + dictionary
    # batch skipping + resident-bytes-per-row vs the decoded path, with
    # full Q1/Q6 value assertions between the two (the knob rides the
    # compiled plan's STATIC key, so flipping it re-specializes without
    # cache flushes)
    compressed = None
    try:
        compressed = _compressed_bench(s)
        compressed["code_agg"] = _code_agg_bench(s, repeats)
        ca = compressed["code_agg"]
        print(f"bench: aggregate-on-codes "
              f"{ca['grouped_rows_per_s_on']:,.0f} rows/s on vs "
              f"{ca['grouped_rows_per_s_off']:,.0f} off, auto "
              f"{ca['grouped_rows_per_s_auto']:,.0f} (predicted "
              f"{ca['predicted_rows_per_s']:,.0f}, byte ratio "
              f"{ca['byte_ratio']}x), lanes {ca['lane_counters']}",
              file=sys.stderr, flush=True)
        print(f"bench: compressed-domain resident "
              f"{compressed['resident_bytes_per_row']} B/row vs decoded "
              f"{compressed['resident_bytes_per_row_decoded']} "
              f"({compressed['resident_reduction']}x), "
              f"{compressed['code_domain_predicates']} code preds, "
              f"{compressed['batches_skipped_dict']} dict-skipped "
              f"batches, values asserted identical",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: compressed-domain bench failed: {e}",
              file=sys.stderr, flush=True)
        compressed = {"error": str(e)}

    # Pallas lanes: on TPU, the engine-level side-by-sides (default-off
    # knobs); elsewhere the fused decode+filter+aggregate CODE-DOMAIN
    # kernels run in interpreter mode under opt-in SNAPPY_BENCH_PALLAS=1
    # (correctness + a trajectory, not hardware speed) — with row-count
    # sanity asserts against the engine's own answers.
    pallas = {"q6_pallas_s": "skipped (set SNAPPY_BENCH_PALLAS=1 for "
                             "cpu interpret)",
              "q1_pallas_s": "skipped (set SNAPPY_BENCH_PALLAS=1 for "
                             "cpu interpret)"}
    if platform != "tpu" and os.environ.get("SNAPPY_BENCH_PALLAS") == "1":
        try:
            pallas = _pallas_fused_bench(s, repeats)
            print(f"bench: fused code-domain kernels (interpret) q6 "
                  f"{pallas['q6_pallas_s']}s q1 {pallas['q1_pallas_s']}s, "
                  f"row counts asserted", file=sys.stderr, flush=True)
        except Exception as e:
            print(f"bench: fused pallas bench failed: {e}",
                  file=sys.stderr, flush=True)
            pallas = {"q6_pallas_s": f"failed: {e}",
                      "q1_pallas_s": f"failed: {e}"}
    if platform == "tpu":
        pallas = {"q6_pallas_s": None, "q1_pallas_s": None}
        for field, flag, q in (
                ("q6_pallas_s", "pallas_reduce", tpch.Q6),
                ("q1_pallas_s", "pallas_group_reduce", tpch.Q1)):
            try:
                setattr(config.global_properties(), flag, True)
                s.executor.clear_cache()
                s.sql(q)
                best = float("inf")
                for _ in range(repeats):
                    t0 = time.time()
                    s.sql(q)
                    best = min(best, time.time() - t0)
                pallas[field] = round(best, 4)
            except Exception as e:
                print(f"bench: pallas {field} timing failed: {e}",
                      file=sys.stderr, flush=True)
            finally:
                setattr(config.global_properties(), flag, False)
                s.executor.clear_cache()

    # Q3-class device join+aggregate (the one-to-many expansion path)
    # vs the r05-era host pandas-merge path, value-asserted
    q3 = None
    try:
        q3 = _join_bench(s, n_rows, repeats)
        print(f"bench: Q3C device {q3['q3_s']}s vs host "
              f"{q3['q3_host_s']}s ({q3['q3_speedup']}x), "
              f"fallbacks={q3['q3_join']['host_fallbacks']}",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: join bench failed: {e}", file=sys.stderr,
              flush=True)
        q3 = {"q3_error": str(e)}

    # materialized-view maintenance: delta appends fold O(delta) while
    # repeated view reads stay O(G) — vs re-running the aggregate O(N)
    matview = None
    try:
        matview = _matview_bench(s, repeats)
        print(f"bench: matview read {matview['view_read_s']}s vs "
              f"re-aggregate {matview['equiv_agg_s']}s "
              f"({matview['view_read_speedup']}x), "
              f"{matview['view_delta_folds']} delta folds / "
              f"{matview['full_refreshes_during_folds']} rescans",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: matview bench failed: {e}", file=sys.stderr,
              flush=True)
        matview = {"matview_error": str(e)}

    # high-QPS serving: prepared+micro-batched vs naive per-query sql()
    # on a mixed point-lookup/small-agg workload, N concurrent clients
    qps = None
    try:
        qps = _qps_bench()
        print(f"bench: qps naive {qps['naive_qps']} vs prepared+batched "
              f"{qps['prepared_qps']} ({qps['qps_speedup']}x, "
              f"occupancy {qps['batch_occupancy']}, p50 {qps['p50_ms']}ms "
              f"p99 {qps['p99_ms']}ms, "
              f"{qps['recompiles_after_warmup']} recompiles after warmup)",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: qps bench failed: {e}", file=sys.stderr,
              flush=True)
        qps = {"qps_error": str(e)}

    # availability trajectory: QPS/p99 through a scripted kill/rejoin
    # window (steady → degraded → recovered), value-asserted throughout
    resilience = None
    try:
        resilience = _resilience_bench()
        print(f"bench: resilience qps steady "
              f"{resilience['steady']['qps']} → degraded "
              f"{resilience['degraded']['qps']} → recovered "
              f"{resilience['recovered']['qps']} (p99 "
              f"{resilience['steady']['p99_ms']}/"
              f"{resilience['degraded']['p99_ms']}/"
              f"{resilience['recovered']['p99_ms']}ms, "
              f"{resilience['rejoin_clean_buckets']} clean + "
              f"{resilience['rejoin_copied_buckets']} copied buckets on "
              f"rejoin, {resilience['degraded_buckets_after_rejoin']} "
              f"degraded after)", file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: resilience bench failed: {e}", file=sys.stderr,
              flush=True)
        resilience = {"resilience_error": str(e)}

    # HTAP: concurrent scan+ingest on one table under MVCC snapshot
    # pins vs the serialized schedule, value-asserted per scan
    htap = None
    try:
        htap = _htap_bench()
        print(f"bench: htap scan p50/p99 "
              f"{htap['concurrent']['scan_p50_ms']}/"
              f"{htap['concurrent']['scan_p99_ms']}ms concurrent vs "
              f"{htap['serialized']['scan_p50_ms']}/"
              f"{htap['serialized']['scan_p99_ms']}ms serialized, "
              f"ingest {htap['concurrent']['ingest_rows_per_s']} vs "
              f"{htap['serialized']['ingest_rows_per_s']} rows/s, "
              f"{htap['value_mismatches']} value mismatches, "
              f"{htap['retained_epoch_bytes_after']} retained bytes "
              f"after drain", file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: htap bench failed: {e}", file=sys.stderr,
              flush=True)
        htap = {"error": str(e)}

    # Out-of-core: same scan in-HBM vs device budget capped < 10% of
    # the table (tier ladder + double-buffered host→HBM tile prefetch),
    # value-asserted
    outofcore = None
    try:
        outofcore = _outofcore_bench()
        print(f"bench: outofcore {outofcore['outofcore_rows_per_s']:,} "
              f"rows/s at {outofcore['budget_fraction']:.1%} device "
              f"budget vs {outofcore['inhbm_rows_per_s']:,} in-HBM "
              f"(ratio {outofcore['throughput_ratio']}, "
              f"{outofcore['scan_tiles']} tiles, "
              f"{outofcore['prefetch_windows_warmed']} windows warmed, "
              f"overlap {outofcore['prefetch_overlap_ms']}ms, "
              f"{outofcore['tier_demotions_hbm']} HBM demotions, "
              f"{outofcore['value_mismatches']} value mismatches)",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: outofcore bench failed: {e}", file=sys.stderr,
              flush=True)
        outofcore = {"error": str(e)}

    # Fault storm: seeded fault injection over the constricted HTAP
    # workload; every injected fault must be accounted as recovered or
    # typed-retryable, with zero wrong rows (guarded by --check)
    faultstorm = None
    try:
        faultstorm = _faultstorm_bench()
        print(f"bench: faultstorm {faultstorm['injected']} faults "
              f"injected (seed {faultstorm['seed']}), "
              f"{faultstorm['recovered']} recovered in place, "
              f"{faultstorm['typed_errors']} typed errors, ratio "
              f"{faultstorm['recovery_ratio']}, "
              f"{faultstorm['crash_recoveries']} crash-recoveries, "
              f"{faultstorm['value_mismatches']} value mismatches, "
              f"scan p50/p99 {faultstorm['scan_p50_ms']}/"
              f"{faultstorm['scan_p99_ms']}ms vs clean "
              f"{faultstorm['clean']['scan_p50_ms']}/"
              f"{faultstorm['clean']['scan_p99_ms']}ms, "
              f"tier {faultstorm['tier']}, in {faultstorm['storm_s']}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"bench: faultstorm bench failed: {e}", file=sys.stderr,
              flush=True)
        faultstorm = {"error": str(e)}

    # Mesh-sharded execution: REAL measured Q1/Q6/Q3C rows/s at 1/2/4/8
    # devices (a forced-topology subprocess — XLA's device-count flag
    # must precede backend init), every sharded answer value-asserted
    # against single-device, with per-device resident-bytes parity and
    # scaling-efficiency evidence `--check` guards
    multichip = None
    if os.environ.get("SNAPPY_BENCH_MULTICHIP", "1") != "0":
        try:
            multichip = _multichip_bench()
            print(f"bench: multichip sf={multichip['sf']} efficiency "
                  f"2/4/8 dev = "
                  f"{multichip['scaling_efficiency']['2']}/"
                  f"{multichip['scaling_efficiency']['4']}/"
                  f"{multichip['scaling_efficiency']['8']}, "
                  f"{multichip['value_mismatches']} value mismatches, "
                  f"resident {multichip['resident_bytes_per_row_sharded']}"
                  f" B/row sharded vs "
                  f"{multichip['resident_bytes_per_row_single']} single, "
                  f"{multichip['mesh_shard_execs']} shard_map execs",
                  file=sys.stderr, flush=True)
        except Exception as e:
            print(f"bench: multichip bench failed: {e}", file=sys.stderr,
                  flush=True)
            multichip = {"error": str(e)}

    ingest_rows_per_s = sink_events_per_s = durable_ingest = None
    try:   # secondary benches must not kill the headline numbers
        ingest_rows_per_s = _ingest_bench()
        sink_events_per_s = _sink_bench()
        durable_ingest = _durable_ingest_bench()
    except Exception as e:
        print(f"bench: ingest/sink bench failed: {e}",
              file=sys.stderr, flush=True)

    rows_per_s = {k: n_rows / v for k, v in timings.items()}
    geomean = float(np.sqrt(rows_per_s["q1"] * rows_per_s["q6"]))
    baseline = 66e6  # see module docstring
    print(json.dumps({
        "metric": "rows/sec scanned+aggregated (TPC-H Q1/Q6 geomean, "
                  f"{n_rows}-row column table)",
        "value": round(geomean, 1),
        "unit": "rows/s",
        "vs_baseline": round(geomean / baseline, 3),
        "detail": {
            "platform": platform,
            "tpu_unreachable": tpu_unreachable,
            "sf": sf,
            "rows": n_rows,
            "load_s": round(load_s, 2),
            # ingest throughput tracked alongside Q1/Q6 (the r04→r05
            # per-append-fsync regression was only visible by diffing
            # load_s by hand)
            "load_rows_per_s": round(n_rows / load_s, 1),
            "q1_s": round(timings["q1"], 4),
            "q6_s": round(timings["q6"], 4),
            "q1_rows_per_s": round(rows_per_s["q1"], 1),
            "q6_rows_per_s": round(rows_per_s["q6"], 1),
            "q1_device_s": None if device.get("q1") is None
            else round(device["q1"], 4),
            "q6_device_s": None if device.get("q6") is None
            else round(device["q6"], 4),
            "q1_device_rows_per_s": None if device.get("q1") is None
            else round(n_rows / device["q1"], 1),
            "q6_device_rows_per_s": None if device.get("q6") is None
            else round(n_rows / device["q6"], 1),
            "q1_max_rel_err": q1_max_rel_err,
            "q6_pallas_s": pallas["q6_pallas_s"],
            "q1_pallas_s": pallas["q1_pallas_s"],
            # reduction-strategy evidence per headline query (strategy
            # picked by the auto table, fused passes per run, gidx
            # cache behavior across the repeats)
            "agg": agg_detail,
            # per-query phase breakdown read off the request trace's
            # span tree (compile_s sums plan compile + first-dispatch
            # jit; execute_s is the async dispatch; transfer_s absorbs
            # the compute wait — the device_s fields above are the
            # blocking ground truth)
            "phases": phases_detail,
            # enabling-cost evidence for the --check guard: the stock
            # Q1/Q6 geomean with tracing on (the headline) vs off,
            # overhead_pct guarded < SNAPPY_BENCH_TRACE_TOL (3%)
            "tracing": tracing_detail,
            # Q3-class join+aggregate evidence (device join engine):
            # q3_s/q3_rows_per_s time the DEVICE path (best of repeats),
            # q3_host_s the r05-era pandas host join (one timed run,
            # device_join=off), q3_speedup their ratio; q3_join carries
            # the per-run strategy detail — host_fallbacks MUST be 0
            # (the query stayed on device), build_sorts counts argsorts
            # across all repeats (1 = the artifact cache carried the
            # rest), expand_factor is output rows per probe row
            "q3": q3,
            # materialized-view maintenance evidence: view_read_s times
            # SELECT * over the maintained state (O(G)), equiv_agg_s
            # re-runs the defining aggregate over the base (O(N));
            # view_delta_folds counts one fold per delta append with
            # full_refreshes_during_folds == 0 proving no rescans, and
            # rows_folded == the delta rows (O(delta) maintenance)
            "matview": matview,
            # serving-axis evidence: naive_qps times per-query sql()
            # (parse+plan every statement), prepared_qps the serving
            # registry + micro-batcher on the SAME workload (results
            # value-asserted identical inside the bench);
            # batch_occupancy is fused requests per device dispatch,
            # recompiles_after_warmup MUST be 0 (compile-once claim) and
            # plan_key_builds 0 (no per-execute re-tokenization)
            "qps": qps,
            # availability-axis evidence: point-read qps + p99 through a
            # scripted kill → rejoin window on a redundancy-1 cluster.
            # steady/degraded/recovered give availability a TRAJECTORY
            # next to rows/s and qps; every query in every phase is
            # value-asserted, and degraded_buckets_after_rejoin MUST be
            # 0 (the watermark resync restored redundancy without a
            # manual restore_redundancy())
            "resilience": resilience,
            # HTAP-axis evidence (MVCC snapshot isolation): scan p50/p99
            # + ingest rows/s with both workloads hammering ONE table
            # concurrently vs serialized; every concurrent scan reads a
            # pinned epoch and is value-asserted (value_mismatches MUST
            # be 0, guarded by --check along with a p99-blowup bound);
            # retained_epoch_bytes_after proves retention drains once
            # readers release
            "htap": htap,
            # out-of-core-axis evidence (tiered storage): the same scan
            # with the device budget capped < 10% of the table, streamed
            # tile-by-tile through the double-buffered host→HBM
            # prefetcher; value_mismatches MUST be 0 and
            # prefetch_overlap_ms > 0 (upload really overlapped
            # compute), with outofcore/in-HBM rows/s guarded ≥
            # SNAPPY_BENCH_OUTOFCORE_RATIO by --check
            "outofcore": outofcore,
            # fault-storm-axis evidence (failpoints + self-healing):
            # seeded injection across WAL/checkpoint/tier/prefetch/
            # admission seams; recovery_ratio is recovered+typed over
            # injected (guarded ≥ SNAPPY_BENCH_FAULT_RECOVERY by
            # --check, default 1.0) and value_mismatches MUST be 0 —
            # an injected fault may slow an answer or fail it with a
            # typed error, never change it
            "faultstorm": faultstorm,
            # mesh-axis evidence: sharded Q1/Q6/Q3C at 1/2/4/8 virtual
            # CPU devices, value-asserted vs single-device.
            # scaling_efficiency is aggregate-throughput RETENTION per
            # mesh size (serialized-core rig: ideal = 1.0; real
            # multi-chip lanes show >1) guarded ≥ SNAPPY_BENCH_MESH_EFF;
            # resident_bytes_per_row_sharded proves plates stay ENCODED
            # per device (guarded vs the single-device number)
            "multichip": multichip,
            "ingest_rows_per_s": ingest_rows_per_s,
            "sink_events_per_s": sink_events_per_s,
            # durable (WAL'd) ingest per wal_fsync_mode, with the fsync
            # count each mode paid — the group-commit write path's
            # evidence record
            "durable_ingest": durable_ingest,
            # in-trace decode counters: bytes actually shipped over the
            # host->device link for RLE/bitset binds vs the decoded
            # plate bytes they replaced (round-4 device_decode feature,
            # now evidenced in the bench record)
            "device_decode": _decode_counters(),
            # compressed-domain execution evidence: predicates served on
            # codes/runs, dictionary-domain batch skipping, per-reason
            # decode-first fallbacks, and resident HBM bytes/row vs the
            # decoded path (the capacity lever) — all value-asserted
            # against the decoded path inside _compressed_bench
            "compressed": compressed,
        },
    }))


def _multichip_child() -> None:
    """Child process for the multichip detail: forces an 8-virtual-CPU
    device topology (XLA_FLAGS must precede jax init — hence the
    subprocess), loads the mesh workload once, and measures REAL sharded
    Q1/Q6/Q3C execution at 1/2/4/8 devices — every mesh answer
    value-asserted against the single-device run of the same data.
    Prints ONE JSON line; the parent embeds it as detail.multichip and
    the committed MULTICHIP_r*.json record."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.parallel import MeshContext, data_mesh
    from snappydata_tpu.storage.device import device_cache_bytes_by_device
    from snappydata_tpu.utils import tpch

    config.global_properties().decimal_as_float64 = True
    sf = float(os.environ.get("SNAPPY_BENCH_MESH_SF", "1.0"))
    reps = int(os.environ.get("SNAPPY_BENCH_MESH_REPEATS", "3"))
    s = SnappySession(catalog=Catalog())
    t0 = time.time()
    tpch.load_tpch(s, sf=sf, seed=17)
    load_s = time.time() - t0
    n_rows = s.catalog.lookup_table(
        "lineitem").data.snapshot().total_rows()
    reg = global_registry()
    queries = (("q1", tpch.Q1), ("q6", tpch.Q6), ("q3c", tpch.Q3C))

    def _clear_caches():
        s.executor.clear_cache()
        for ti in s.catalog.list_tables():
            if hasattr(ti.data, "_device_cache"):
                ti.data._device_cache.clear()

    def _resident_per_row() -> float:
        per_dev = device_cache_bytes_by_device(
            (i.name, i.data) for i in s.catalog.list_tables())
        return round(sum(per_dev.values()) / max(1, n_rows), 2)

    def _rows_cmp(a, b) -> int:
        bad = 0
        if len(a) != len(b):
            return max(1, abs(len(a) - len(b)))
        for ra, rb in zip(a, b):
            for x, y in zip(ra, rb):
                if isinstance(x, float) or isinstance(y, float):
                    if not (abs(float(x) - float(y))
                            <= 1e-9 * max(1.0, abs(float(x)))):
                        bad += 1
                elif x != y:
                    bad += 1
        return bad

    def _measure():
        """best-of-reps per query + resident bytes/row measured from a
        fresh cache after the SCAN queries only (Q3C's decoded join
        plates must not pollute the encoded-residency comparison —
        the r06 compressed-bench review finding)."""
        out = {}
        _clear_caches()
        for name, q in queries[:2]:
            rows = s.sql(q).rows()   # compile + warm
            best = float("inf")
            for _ in range(reps):
                t1 = time.time()
                s.sql(q)
                best = min(best, time.time() - t1)
            out[name] = {"s": round(best, 4),
                         "rows_per_s": round(n_rows / best, 1),
                         "rows": rows}
        out["resident_bytes_per_row"] = _resident_per_row()
        for name, q in queries[2:]:
            rows = s.sql(q).rows()
            best = float("inf")
            for _ in range(reps):
                t1 = time.time()
                s.sql(q)
                best = min(best, time.time() - t1)
            out[name] = {"s": round(best, 4),
                         "rows_per_s": round(n_rows / best, 1),
                         "rows": rows}
        return out

    single = _measure()
    mesh_runs = {}
    mismatches = 0
    c0 = dict(reg.snapshot()["counters"])
    for nd in (1, 2, 4, 8):
        with MeshContext(data_mesh(nd)):
            m = _measure()
        for name, _q in queries:
            mismatches += _rows_cmp(single[name]["rows"], m[name]["rows"])
            m[name].pop("rows")
        mesh_runs[str(nd)] = m
    c1 = reg.snapshot()["counters"]
    for name, _q in queries:
        single[name].pop("rows")

    def eff(nd: str) -> float:
        vals = [mesh_runs[nd][n]["rows_per_s"]
                / max(1e-9, mesh_runs["1"][n]["rows_per_s"])
                for n, _ in queries]
        return round(float(np.prod(vals) ** (1.0 / len(vals))), 3)

    result = {
        "sf": sf,
        "rows": int(n_rows),
        "load_s": round(load_s, 2),
        "n_devices": 8,
        "single": single,
        "mesh": mesh_runs,
        "value_mismatches": int(mismatches),
        # aggregate-throughput retention per mesh size (geomean over
        # Q1/Q6/Q3C of rows/s at D vs the 1-device mesh run): on a
        # serialized-core CPU rig ideal scaling is FLAT (1.0 — the
        # collectives and padding are the only cost), on a real
        # multi-chip lane the same number shows true speedup.  Per-device
        # efficiency at D is retention(D): each device retains that
        # fraction of its fair share.
        "scaling_efficiency": {nd: eff(nd) for nd in ("2", "4", "8")},
        "resident_bytes_per_row_single":
            single["resident_bytes_per_row"],
        "resident_bytes_per_row_sharded":
            mesh_runs["8"]["resident_bytes_per_row"],
        "mesh_shard_execs":
            c1.get("mesh_shard_execs", 0) - c0.get("mesh_shard_execs", 0),
        "mesh_psum_merges":
            c1.get("mesh_psum_merges", 0) - c0.get("mesh_psum_merges", 0),
        "mesh_join_broadcast":
            c1.get("mesh_join_broadcast", 0)
            - c0.get("mesh_join_broadcast", 0),
        "mesh_join_shuffle":
            c1.get("mesh_join_shuffle", 0)
            - c0.get("mesh_join_shuffle", 0),
        "mesh_fallbacks": {
            k[len("mesh_fallback_"):]: c1.get(k, 0) - c0.get(k, 0)
            for k in c1 if k.startswith("mesh_fallback_")
            and c1.get(k, 0) - c0.get(k, 0)},
    }
    print(json.dumps(result))


def _multichip_bench() -> dict:
    """Run the multichip child under the forced 8-device CPU topology
    and parse its record — real measured sharded rows/s, replacing the
    dry-run-only MULTICHIP record shape."""
    import subprocess

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multichip-child"],
        capture_output=True, text=True, env=env,
        timeout=float(os.environ.get("SNAPPY_BENCH_MESH_TIMEOUT", "1800")))
    if proc.returncode != 0 or not proc.stdout.strip():
        raise RuntimeError(
            f"multichip child rc={proc.returncode}: "
            f"{(proc.stderr or '')[-400:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _join_bench(s, n_rows: int, repeats: int) -> dict:
    """Q3-class join+aggregate (tpch.Q3C: orders LEFT JOIN lineitem —
    a one-to-many expansion on a NON-unique build) on the device join
    engine vs the r05-era host pandas-merge path, value-asserted.

    The host baseline flips the `device_join` knob (a per-bind check,
    no cache flush needed) for ONE timed run; the device side reports
    best-of-repeats plus the join engine's own evidence counters."""
    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.utils import tpch

    props = config.global_properties()
    reg = global_registry()
    saved_cap = props.join_expand_max_bytes
    # expanded output ~ (lineitem + orders) rows x ~40B/row: at SF16 the
    # default 2GB cap would reroute to host — size it for the bench
    props.join_expand_max_bytes = 8 << 30
    try:
        props.set("device_join", False)
        t0 = time.time()
        host_rows = s.sql(tpch.Q3C).rows()
        host_s = time.time() - t0
        props.set("device_join", True)
        c0 = dict(reg.snapshot()["counters"])
        s.sql(tpch.Q3C)  # compile + first run (pays the ONE build argsort)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.time()
            dev_rows = s.sql(tpch.Q3C).rows()
            best = min(best, time.time() - t0)
        c1 = reg.snapshot()["counters"]

        def delta(key):
            return c1.get(key, 0) - c0.get(key, 0)

        # full value assertion against the host join (counts exact,
        # revenue within float tolerance — TPU plates are f32)
        assert len(dev_rows) == len(host_rows), (dev_rows, host_rows)
        max_rel = 0.0
        for h, d in zip(host_rows, dev_rows):
            assert h[0] == d[0] and h[1] == d[1], (h, d)
            rel = abs(h[2] - d[2]) / max(abs(h[2]), 1.0)
            max_rel = max(max_rel, rel)
            assert rel <= 5e-5, (h, d, rel)
        out_rows = delta("join_expand_out_rows")
        probe_rows = delta("join_expand_probe_rows")
        return {
            "q3_s": round(best, 4),
            "q3_host_s": round(host_s, 4),
            "q3_speedup": round(host_s / best, 2),
            "q3_rows_per_s": round(n_rows / best, 1),
            "q3_max_rel_err": max_rel,
            "q3_join": {
                "host_fallbacks": delta("join_host_fallbacks"),
                "device_joins": delta("join_device_joins"),
                "build_sorts": delta("join_build_sorts"),
                "build_cache_hits": delta("join_build_cache_hits"),
                "expand_factor":
                    round(out_rows / probe_rows, 2) if probe_rows
                    else None,
            },
        }
    finally:
        props.join_expand_max_bytes = saved_cap
        props.set("device_join", True)


def _matview_bench(s, repeats: int, k_deltas: int = 8,
                   delta_rows: int = 50_000) -> dict:
    """Materialized-view maintenance over the loaded lineitem table:
    CREATE view (one full aggregation), K delta appends (each folds
    O(delta) through the compiled partial program), then repeated view
    reads vs re-running the defining aggregate, value-asserted.  Runs
    AFTER the Q1/Q6/Q3 sections — the appends grow lineitem."""
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.utils import tpch

    reg = global_registry()
    agg_sql = ("SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sq, "
               "sum(l_extendedprice) AS sp, "
               "sum(l_extendedprice * (1 - l_discount)) AS sd, "
               "count(*) AS cnt FROM lineitem "
               "GROUP BY l_returnflag, l_linestatus")
    s.sql("CREATE MATERIALIZED VIEW bench_mv AS " + agg_sql)
    try:
        c0 = dict(reg.snapshot()["counters"])
        t0 = time.time()
        for i in range(k_deltas):
            li = tpch.gen_lineitem(delta_rows, seed=1000 + i)
            s.insert_arrays("lineitem", list(li.values()))
        fold_s = time.time() - t0
        c1 = dict(reg.snapshot()["counters"])

        def delta(key):
            return c1.get(key, 0) - c0.get(key, 0)

        s.sql("SELECT * FROM bench_mv")   # pays the one O(G) re-merge
        best_view = float("inf")
        for _ in range(max(repeats, 3)):
            t0 = time.time()
            view_rows = s.sql("SELECT * FROM bench_mv ORDER BY "
                              "l_returnflag, l_linestatus").rows()
            best_view = min(best_view, time.time() - t0)
        best_agg = float("inf")
        for _ in range(max(repeats, 3)):
            t0 = time.time()
            agg_rows = s.sql(agg_sql + " ORDER BY l_returnflag, "
                             "l_linestatus").rows()
            best_agg = min(best_agg, time.time() - t0)
        # value assertion: maintained state == fresh aggregation (sums
        # within fp tolerance — fold order differs from scan order)
        assert len(view_rows) == len(agg_rows), (view_rows, agg_rows)
        for v, a in zip(view_rows, agg_rows):
            assert v[0] == a[0] and v[1] == a[1], (v, a)
            assert v[5] == a[5], (v, a)   # counts exact
            for x, y in zip(v[2:5], a[2:5]):
                assert abs(x - y) <= 1e-9 * max(abs(y), 1.0), (v, a)
        return {
            "view_read_s": round(best_view, 4),
            "equiv_agg_s": round(best_agg, 4),
            "view_read_speedup": round(best_agg / best_view, 1),
            "delta_append_total_s": round(fold_s, 3),
            "delta_rows_per_append": delta_rows,
            "view_delta_folds": delta("view_delta_folds"),
            "view_rows_folded": delta("view_rows_folded"),
            "full_refreshes_during_folds": delta("view_full_refreshes"),
            "groups": len(view_rows),
        }
    finally:
        s.sql("DROP MATERIALIZED VIEW IF EXISTS bench_mv")


def _qps_bench(n_clients: int = 8, point_rows: int = 50_000,
               txn_rows: int = 64_000, naive_iters: int = 60,
               prepared_iters: int = 250) -> dict:
    """High-QPS serving axis: a mixed point-lookup/small-aggregate
    workload under N concurrent clients, naive per-query `session.sql`
    (parse+plan every statement) vs the prepared+micro-batched serving
    path — results value-asserted identical between the two.  Reports
    qps for both sides, prepared-path p50/p99 latency, fused-dispatch
    occupancy, and the zero-recompile evidence (plan compiles + vmapped
    variants built DURING the timed run, after warmup primed them)."""
    import threading

    from snappydata_tpu import SnappySession
    from snappydata_tpu import types as T
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.observability.metrics import global_registry

    from snappydata_tpu import config as _config

    reg = global_registry()
    props = _config.global_properties()
    saved_batch_rows = props.column_batch_rows
    # serving-sized column batches: the default 128Ki-row capacity means
    # a 64k-row table still scans 128Ki padded lanes per query — a
    # serving deployment sizes batches to its small tables (both sides
    # of the comparison read the same tables, so this is neutral)
    props.column_batch_rows = 16384
    try:
        s = SnappySession(catalog=Catalog())
        rng = np.random.default_rng(29)
        ids = np.arange(point_rows, dtype=np.int64)
        balances = rng.random(point_rows) * 1e4
        s.create_table("accounts", [("id", T.LONG), ("balance", T.DOUBLE)],
                       provider="row", key_columns=("id",))
        s.insert_arrays("accounts", [ids, balances])
        region = rng.integers(0, 64, txn_rows).astype(np.int64)
        amount = rng.random(txn_rows)
        s.create_table("txns", [("region_id", T.LONG),
                                ("amount", T.DOUBLE)],
                       provider="column")
        s.insert_arrays("txns", [region, amount])
    finally:
        props.column_batch_rows = saved_batch_rows

    point_sql = "SELECT balance FROM accounts WHERE id = ?"
    agg_sql = ("SELECT count(*), sum(amount) FROM txns "
               "WHERE region_id = ?")
    # per-region oracle for the value assertions
    agg_expect = {r: (int((region == r).sum()),
                      float(amount[region == r].sum()))
                  for r in range(64)}

    def workload(client: int, iters: int):
        """Deterministic 70/30 point/small-agg mix per client (the
        millions-of-users shape: mostly per-user point reads, a steady
        minority of dashboard-tile aggregates)."""
        r = np.random.default_rng(1000 + client)
        out = []
        for _ in range(iters):
            if r.random() < 0.7:
                out.append(("point", int(r.integers(0, point_rows))))
            else:
                out.append(("agg", int(r.integers(0, 64))))
        return out

    def check(kind, arg, rows):
        if kind == "point":
            assert len(rows) == 1 and \
                abs(rows[0][0] - balances[arg]) <= 1e-9, (arg, rows)
        else:
            cnt, sm = agg_expect[arg]
            assert rows[0][0] == cnt and \
                abs(rows[0][1] - sm) <= 1e-6 * max(sm, 1.0), (arg, rows)

    def run_clients(iters, fn):
        lats: list = []
        errors: list = []
        barrier = threading.Barrier(n_clients)

        def client(ci):
            mine = []
            try:
                work = workload(ci, iters)
                barrier.wait()
                for kind, arg in work:
                    t0 = time.time()
                    rows = fn(kind, arg)
                    mine.append(time.time() - t0)
                    check(kind, arg, rows)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            lats.extend(mine)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.time() - t0
        if errors:
            raise errors[0]
        return wall, lats

    # ---- naive side: parse+analyze+plan per statement ------------------
    def naive(kind, arg):
        sql = point_sql if kind == "point" else agg_sql
        return s.sql(sql, (arg,)).rows()

    naive(  # one warm call per shape so the naive side isn't paying
        "point", 0)  # first-compile either (same courtesy as prepared)
    naive("agg", 0)
    # best-of-passes on BOTH sides, same convention as Q1/Q6/Q3: this
    # container's contention noise swings absolute wall times ~3x, and
    # the least-contended pass is the honest measure of each path
    naive_n = n_clients * naive_iters
    naive_qps = 0.0
    for _ in range(2):
        naive_wall, _ = run_clients(naive_iters, naive)
        naive_qps = max(naive_qps, naive_n / naive_wall)

    # ---- prepared + micro-batched side ---------------------------------
    ph = s.prepare(point_sql)
    ah = s.prepare(agg_sql)

    def prepared(kind, arg):
        h = ph if kind == "point" else ah
        return h.execute((arg,)).rows()

    # warmup: prime every vmapped batch-size bucket an N-client load can
    # hit (inference-server warmup), plus one straight execute per shape
    ah.warm_batches((0,))
    prepared("point", 0)
    prepared("agg", 0)
    c0 = dict(reg.snapshot()["counters"])
    t0_compiles = reg.snapshot()["timers"].get("plan_compile",
                                               {}).get("count", 0)
    prep_n = n_clients * prepared_iters
    prep_qps, lats = 0.0, []
    for _ in range(2):
        prep_wall, pass_lats = run_clients(prepared_iters, prepared)
        if prep_n / prep_wall > prep_qps:
            prep_qps, lats = prep_n / prep_wall, pass_lats
    c1 = dict(reg.snapshot()["counters"])
    t1_compiles = reg.snapshot()["timers"].get("plan_compile",
                                               {}).get("count", 0)

    def delta(key):
        return c1.get(key, 0) - c0.get(key, 0)

    dispatches = delta("serving_batched_dispatches")
    fused = delta("serving_batch_requests")
    lats_ms = np.asarray(lats) * 1e3
    out = {
        "clients": n_clients,
        "naive_queries": naive_n,
        "naive_qps": round(naive_qps, 1),
        "prepared_queries": prep_n,
        "prepared_qps": round(prep_qps, 1),
        "qps_speedup": round(prep_qps / naive_qps, 2),
        "p50_ms": round(float(np.percentile(lats_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lats_ms, 99)), 3),
        "serving_prepared_hits": delta("serving_prepared_hits"),
        "serving_batched_dispatches": dispatches,
        "batch_occupancy": round(fused / dispatches, 2) if dispatches
        else None,
        "straight_through": delta("serving_straight_through"),
        "batch_fallbacks": delta("serving_batch_fallbacks"),
        # zero-recompile evidence: XLA plan compiles + vmapped variants
        # built during the TIMED run (warmup primed them) — must be 0
        "recompiles_after_warmup":
            (t1_compiles - t0_compiles) + delta("serving_vmap_compiles"),
        # re-tokenization guard: plan-repr walks during the timed run
        # (the prepared path computes its key once at prepare)
        "plan_key_builds": delta("plan_key_builds"),
    }
    s.stop()
    return out


def _htap_bench(n_rows: int = 200_000, scans: int = 12,
                batch_rows: int = 5000, ingest_batches: int = 24) -> dict:
    """HTAP axis (MVCC snapshot isolation): an analytic scan stream and
    sustained ingest hammer ONE column table, concurrently vs
    serialized.  Every concurrent scan runs under a pinned snapshot
    epoch and is value-asserted against the single-epoch invariant
    (ingest batches are (0, 1.0)×batch_rows, so a consistent snapshot
    must satisfy count == n_rows + m·batch_rows AND sum == base_sum +
    (count − n_rows) — a scan mixing two epochs breaks the linkage).

    The CONCURRENT phase runs first (scans race a bounded, paced ingest
    budget — unbounded tight-loop ingest degenerates into measuring XLA
    re-specialization as the batch axis doubles, not isolation); the
    SERIALIZED phase then times the same scans alone and the same
    ingest alone on the settled table.  --check guards
    value_mismatches == 0 and the p50 blow-up (p99 is reported but
    unguarded: it legitimately absorbs a batch-bucket re-specialization
    when ingest crosses a shape boundary)."""
    import threading

    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.storage import mvcc

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE htap (k INT, v DOUBLE) USING column")
    ks = (np.arange(n_rows) % 16).astype(np.int32)
    vs = (np.arange(n_rows) % 100).astype(np.float64)
    s.catalog.describe("htap").data.insert_arrays([ks, vs])
    base_sum = float(vs.sum())
    scan_sql = "SELECT count(*), sum(v) FROM htap"
    s.sql(scan_sql)   # warm the compiled plan
    bk = np.zeros(batch_rows, dtype=np.int32)
    bv = np.ones(batch_rows, dtype=np.float64)
    mismatches = [0]

    def one_scan(sess):
        t0 = time.perf_counter()
        cnt, sm = sess.sql(scan_sql).rows()[0]
        dt = time.perf_counter() - t0
        cnt, sm = int(cnt), float(sm)
        extra = cnt - n_rows
        if extra % batch_rows or abs(sm - (base_sum + extra)) > 1e-6 * max(
                1.0, abs(sm)):
            mismatches[0] += 1
        return dt

    def ingest_run(stop=None, pace_s=0.01):
        """Paced ingest of the fixed budget; returns (rows, seconds of
        actual ingest work — pacing sleeps excluded, so rows/s measures
        the write path, not the pacing)."""
        w = SnappySession(catalog=s.catalog)
        work = 0.0
        done = 0
        for _ in range(ingest_batches):
            if stop is not None and stop.is_set():
                break
            t0 = time.perf_counter()
            w.insert_arrays("htap", [bk, bv])
            work += time.perf_counter() - t0
            done += batch_rows
            if pace_s:
                time.sleep(pace_s)
        return done, work

    def pcts(times):
        times = sorted(times)
        return (round(times[len(times) // 2] * 1e3, 3),
                round(times[min(len(times) - 1,
                               int(len(times) * 0.99))] * 1e3, 3))

    # ---- concurrent: scans race the paced ingest budget ---------------
    stop = threading.Event()
    ing_out = {}

    def ingest_thread():
        rows, work = ingest_run(stop=stop)
        ing_out["rows"], ing_out["work_s"] = rows, work

    th = threading.Thread(target=ingest_thread, daemon=True)
    th.start()
    conc_times = [one_scan(s) for _ in range(scans)]
    # signal BEFORE joining: a slow machine's paced ingest must stop at
    # the scans' end, not keep running into the serialized baseline
    # (which would inflate it and soften the p50 guard)
    stop.set()
    th.join(timeout=120)
    p50c, p99c = pcts(conc_times)
    concurrent = {
        "scan_p50_ms": p50c, "scan_p99_ms": p99c,
        "ingest_rows_per_s": round(
            ing_out.get("rows", 0) / max(ing_out.get("work_s", 0), 1e-9),
            1),
        "ingested_rows": ing_out.get("rows", 0),
    }
    # ---- serialized baseline: same scans alone, same ingest alone -----
    ser_times = [one_scan(s) for _ in range(scans)]
    rows, work = ingest_run()
    p50s, p99s = pcts(ser_times)
    serialized = {
        "scan_p50_ms": p50s, "scan_p99_ms": p99s,
        "ingest_rows_per_s": round(rows / max(work, 1e-9), 1),
        "ingested_rows": rows,
    }
    data = s.catalog.describe("htap").data
    mvcc.trim_unpinned([("htap", data)])
    retained_after = mvcc.retained_bytes_of(data)
    out = {
        "rows": n_rows,
        "scans": scans,
        "batch_rows": batch_rows,
        "serialized": serialized,
        "concurrent": concurrent,
        "value_mismatches": mismatches[0],
        # bounded-retention evidence: after readers drain (and the trim
        # the degradation ladder would run), old epochs hold no bytes
        "retained_epoch_bytes_after": int(retained_after),
    }
    s.stop()
    return out


def _outofcore_bench(n_rows: int = 3_200_000, repeats: int = 5) -> dict:
    """Out-of-core axis (tiered storage + double-buffered prefetch): the
    SAME filter+aggregate scan measured fully in-HBM vs with the device
    budget capped BELOW 10% of the table, so every pass streams tiles
    host→HBM through storage/prefetch.py while the tier ladder
    (storage/tier.py) demotes what falls cold.  On this CPU rig the cap
    is an emulation (`tier_device_bytes` + a tile-sized scan window) —
    the transfer/compute overlap it exercises is the real mechanism.
    --check guards: zero value mismatches (out-of-core must be invisible
    to answers), prefetch_overlap_ms > 0 (the double buffer actually
    overlapped upload with compute), and out-of-core rows/s >=
    SNAPPY_BENCH_OUTOFCORE_RATIO (default 0.5) of in-HBM — the
    streaming bound min(compute, transfer) can't silently decay into
    bind-per-tile serialization."""
    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.storage.hoststore import batch_resident_bytes

    props = config.global_properties()
    saved = (props.column_batch_rows, props.column_max_delta_rows,
             props.scan_tile_bytes, props.tier_device_bytes,
             props.tier_host_bytes, props.tier_prefetch_depth)
    mismatches = 0
    try:
        props.column_batch_rows = 65536
        props.column_max_delta_rows = 65536
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE oc (k INT, v DOUBLE) USING column")
        ks = (np.arange(n_rows) % 16).astype(np.int32)
        vs = ((np.arange(n_rows) * 7919) % 10_000).astype(np.float64)
        s.catalog.describe("oc").data.insert_arrays([ks, vs])
        data = s.catalog.describe("oc").data
        table_bytes = sum(batch_resident_bytes(v.batch)
                          for v in data._manifest.views)
        q = ("SELECT count(*), sum(v), min(v), max(v) FROM oc "
             "WHERE v < 9000")

        def best_of(runs):
            times = []
            for _ in range(runs):
                t0 = time.perf_counter()
                rows = s.sql(q).rows()
                times.append(time.perf_counter() - t0)
            return min(times), rows[0]

        # ---- in-HBM baseline: whole table bound, plates stay cached
        s.sql(q)  # warm compile + bind
        t_in, ref = best_of(repeats)

        # ---- constricted: device budget < 10% of the table ------------
        budget = max(1, table_bytes // 10)
        # tile = 4 of ~50 batches (8% of the table) — each pass streams
        # the table through a window under the cap, double-buffered two
        # windows deep so the upload hides behind the tile aggregate
        props.scan_tile_bytes = 4 * 65536 * (4 + 8)
        props.tier_device_bytes = budget
        props.tier_prefetch_depth = 2
        reg = global_registry()
        c0 = dict(reg.snapshot()["counters"])
        # the warm pass stays inside the counter window: it is where
        # the over-cap in-HBM plates get demoted off the device tier
        s.sql(q)
        t_oc, got = best_of(repeats)
        c1 = dict(reg.snapshot()["counters"])

        def delta(key):
            return c1.get(key, 0) - c0.get(key, 0)

        if int(got[0]) != int(ref[0]):
            mismatches += 1
        for gi, ri in zip(got[1:], ref[1:]):
            if abs(float(gi) - float(ri)) > 1e-9 * max(1.0,
                                                       abs(float(ri))):
                mismatches += 1
        in_rps = n_rows / t_in
        oc_rps = n_rows / t_oc
        return {
            "rows": n_rows,
            "table_bytes": int(table_bytes),
            "device_budget_bytes": int(budget),
            "budget_fraction": round(budget / table_bytes, 4),
            "inhbm_rows_per_s": round(in_rps, 1),
            "outofcore_rows_per_s": round(oc_rps, 1),
            "throughput_ratio": round(oc_rps / in_rps, 4),
            "scan_tiles": delta("scan_tiles"),
            "prefetch_windows_warmed": delta("prefetch_windows_warmed"),
            "prefetch_overlap_ms": delta("prefetch_overlap_ms"),
            "prefetch_window_waits": delta("prefetch_window_waits"),
            "tier_demotions_hbm": delta("tier_demotions_hbm"),
            "value_mismatches": mismatches,
        }
    finally:
        (props.column_batch_rows, props.column_max_delta_rows,
         props.scan_tile_bytes, props.tier_device_bytes,
         props.tier_host_bytes, props.tier_prefetch_depth) = saved


def _faultstorm_bench() -> dict:
    """Fault-storm axis (reliability/faultstorm.py): a seeded schedule
    injects one fault per round — WAL append/fsync, checkpoint
    write/publish, tier write corruption/short-write, memmap EIO,
    prefetch-worker death, admission failure — into the constricted
    HTAP workload and reconciles the ledger: every fired fault must end
    as `recovered` (self-healed in place: quarantine+rebuild, worker
    restart, bounded re-read) or `typed_errors` (a typed retryable
    failure followed by verified crash-recovery).  --check guards
    value_mismatches == 0, no untyped failures, and recovery_ratio >=
    SNAPPY_BENCH_FAULT_RECOVERY (default 1.0 — fully accounted)."""
    import shutil
    import tempfile

    from snappydata_tpu.reliability import faultstorm

    seed = int(os.environ.get("SNAPPY_FAILPOINT_SEED", "1717"))
    rounds = int(os.environ.get("SNAPPY_BENCH_FAULT_ROUNDS", "30"))
    tmp = tempfile.mkdtemp(prefix="snappy_faultstorm_")
    try:
        t0 = time.perf_counter()
        res = faultstorm.run_storm(tmp, seed=seed, rounds=rounds)
        res["storm_s"] = round(time.perf_counter() - t0, 2)
        # the clean baseline: the SAME seeded op schedule, no fault
        # armed — what the storm's scan p50/p99 and qps compare against
        clean_dir = tempfile.mkdtemp(prefix="snappy_faultstorm_clean_")
        try:
            clean = faultstorm.run_storm(clean_dir, seed=seed,
                                         rounds=rounds, inject=False)
            res["clean"] = {k: clean[k] for k in
                            ("scans", "scan_p50_ms", "scan_p99_ms",
                             "scans_per_s", "value_mismatches")}
        finally:
            shutil.rmtree(clean_dir, ignore_errors=True)
        return res
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _resilience_bench(n_rows: int = 20_000, phase_s: float = 1.5) -> dict:
    """Availability trajectory: point-read QPS and p99 through a
    scripted kill → rejoin window on a 2-server cluster with
    redundancy 1 — three measured phases:

      steady     both members up;
      degraded   one member hard-killed mid-phase (the first query pays
                 the failover probe + replica promotion; replicas keep
                 every answer complete);
      recovered  the member restarted from its recovered data dir and
                 re-admitted via rejoin_server (watermark delta resync)
                 — redundancy restored, no manual restore_redundancy().

    Every query in every phase is VALUE-asserted (v == k/2), so the
    availability numbers can't hide wrong answers; `correct` reports
    that every returned row checked out."""
    import shutil
    import tempfile

    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    tmp = tempfile.mkdtemp(prefix="snappy_resilience_")
    locator = LocatorNode().start()
    sessions = [SnappySession(catalog=Catalog(),
                              data_dir=os.path.join(tmp, f"srv{i}"),
                              recover=False) for i in range(2)]
    servers = [ServerNode(locator.address, s).start() for s in sessions]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers],
        locator=locator.address)
    rng = np.random.default_rng(47)
    try:
        ds.sql("CREATE TABLE res_kv (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        ks = np.arange(n_rows, dtype=np.int64)
        ds.insert_arrays("res_kv", [ks, ks * 0.5])

        def run_phase(seconds: float) -> dict:
            lats = []
            end = time.time() + seconds
            while time.time() < end:
                k = int(rng.integers(0, n_rows))
                t0 = time.time()
                rows = ds.sql(
                    f"SELECT v FROM res_kv WHERE k = {k}").rows()
                lats.append(time.time() - t0)
                assert len(rows) == 1 and \
                    abs(rows[0][0] - k * 0.5) <= 1e-9, (k, rows)
            lats_ms = np.asarray(lats) * 1e3
            return {"queries": len(lats),
                    "qps": round(len(lats) / seconds, 1),
                    "p50_ms": round(float(np.percentile(lats_ms, 50)), 2),
                    "p99_ms": round(float(np.percentile(lats_ms, 99)), 2)}

        ds.sql("SELECT count(*) FROM res_kv")   # warm compiles
        steady = run_phase(phase_s)

        # hard kill one member; the NEXT query pays the failover
        servers[1].stop()
        sessions[1].disk_store.close()
        degraded = run_phase(phase_s)

        # restart from the recovered data dir + automatic resync
        sessions[1] = SnappySession(data_dir=os.path.join(tmp, "srv1"),
                                    recover=True)
        servers[1] = ServerNode(locator.address, sessions[1]).start()
        rejoin = ds.rejoin_server(1, servers[1].flight_address)
        recovered = run_phase(phase_s)

        return {
            "rows": n_rows,
            "steady": steady,
            "degraded": degraded,
            "recovered": recovered,
            "rejoin_clean_buckets": rejoin["clean_primary_buckets"]
            + rejoin["clean_replica_buckets"],
            "rejoin_copied_buckets": rejoin["copied_buckets"],
            "degraded_buckets_after_rejoin": rejoin["degraded_buckets"],
            "correct": True,   # every phase value-asserted above
        }
    finally:
        ds.close()
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
        locator.stop()
        for s in sessions:
            try:
                s.disk_store.close()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _compressed_bench(s) -> dict:
    """Compressed-domain evidence over the loaded SF lineitem table:

    * a dictionary-skip probe (equality literal that misses every
      sorted VALUE_DICT dictionary) must skip whole batches at bind —
      `batches_skipped_dict > 0` on the stock workload;
    * Q1 + Q6 run once with the knob on and once with it OFF (decoded
      plates) and every value is asserted identical;
    * resident HBM bytes/row are measured for BOTH binds — the capacity
      lever the --check guard protects."""
    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry
    from snappydata_tpu.observability.stats_service import encoding_mix
    from snappydata_tpu.utils import tpch

    props = config.global_properties()
    reg = global_registry()
    data = s.catalog.lookup_table("lineitem").data

    # dictionary-skip probe: l_discount holds multiples of 0.01, so
    # 0.055 misses every batch dictionary — zero device work
    c0 = dict(reg.snapshot()["counters"])
    miss = s.sql(
        "SELECT count(*) FROM lineitem WHERE l_discount = 0.055"
    ).rows()[0][0]
    assert miss == 0, miss
    c1 = dict(reg.snapshot()["counters"])
    skipped = c1.get("batches_skipped_dict", 0) \
        - c0.get("batches_skipped_dict", 0)

    # SYMMETRIC residency measurement: both sides bind Q1/Q6's columns
    # into a FRESH device cache (earlier bench sections leave decoded
    # join plates and artifacts around that would inflate the 'on' side
    # and skew the guarded resident_bytes_per_row — review finding)
    data._device_cache.clear()
    q1_on = s.sql(tpch.Q1).rows()
    q6_on = s.sql(tpch.Q6).rows()
    mix_on = encoding_mix(s.catalog).get("lineitem", {})
    # counter snapshot AFTER this section's own queries, so the record
    # reflects this workload even if the section runs standalone
    counters = dict(reg.snapshot()["counters"])
    saved = props.get("scan_compressed_domain")
    try:
        props.set("scan_compressed_domain", "off")
        data._device_cache.clear()
        q1_off = s.sql(tpch.Q1).rows()
        q6_off = s.sql(tpch.Q6).rows()
        mix_off = encoding_mix(s.catalog).get("lineitem", {})
    finally:
        props.set("scan_compressed_domain", saved)
        data._device_cache.clear()
        s.sql(tpch.Q6)   # re-prime the compressed binds for later sections

    # full value assertion compressed vs decoded (identical inputs and
    # reduction order — tolerance only covers fp noise)
    assert len(q1_on) == len(q1_off), (q1_on, q1_off)
    for a, b in zip(q1_on, q1_off):
        assert a[0] == b[0] and a[1] == b[1] and a[9] == b[9], (a, b)
        for x, y in zip(a[2:9], b[2:9]):
            assert abs(x - y) <= 1e-9 * max(abs(y), 1.0), (a, b)
    assert abs(q6_on[0][0] - q6_off[0][0]) \
        <= 1e-9 * max(abs(q6_off[0][0]), 1.0), (q6_on, q6_off)

    rb_on = mix_on.get("resident_bytes_per_row")
    rb_off = mix_off.get("resident_bytes_per_row")
    return {
        "code_domain_predicates": counters.get("code_domain_predicates", 0),
        "rle_run_predicates": counters.get("rle_run_predicates", 0),
        "batches_skipped_dict": skipped,
        "fallback_reasons": {
            k[len("compressed_fallback_"):]: v
            for k, v in sorted(counters.items())
            if k.startswith("compressed_fallback_")},
        "encoding_mix": mix_on.get("encoding_mix"),
        "at_rest_ratio": mix_on.get("at_rest_ratio"),
        "resident_bytes_per_row": rb_on,
        "resident_bytes_per_row_decoded": rb_off,
        "resident_reduction":
            round(rb_off / rb_on, 2) if rb_on and rb_off else None,
        "values_asserted": True,
    }


def _code_agg_bench(s, repeats: int) -> dict:
    """Aggregate-on-codes lane (the dictionary-space tentpole): the SAME
    grouped aggregate runs once with `agg_on_codes` forced ON
    (code-domain group-by + dictionary-space sums) and once OFF (decoded
    gathers), every value asserted identical, rows/s recorded both ways;
    a dedicated sorted low-cardinality probe (TPC-H distributions leave
    lineitem with no RUN_LENGTH column) exercises the run-space lane the
    same way.

    The decode-throughput law prices the lane: the decoded path must
    move decoded-bytes/encoded-bytes more data over the same aggregate,
    so on a bandwidth-bound accelerator `predicted_on = off_rate x
    byte_ratio`; on compute-bound CPU the gather itself dominates and
    the law degenerates to `predicted_on = off_rate`.  `--check` guards
    measured >= SNAPPY_BENCH_CODE_AGG_RATIO (default 0.8x) of predicted,
    and that all three lane counters actually fired."""
    import jax

    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry

    props = config.global_properties()
    reg = global_registry()
    data = s.catalog.lookup_table("lineitem").data
    rows = data.snapshot().total_rows()

    # string dict keys -> code-domain group-by; VALUE_DICT measures ->
    # dictionary-space sums
    q_group = ("SELECT l_returnflag, l_linestatus, count(*), "
               "sum(l_quantity), sum(l_discount) FROM lineitem "
               "GROUP BY l_returnflag, l_linestatus "
               "ORDER BY l_returnflag, l_linestatus")
    # run-space probe: single RLE column, run-aligned filter
    nprobe = int(min(max(rows, 1 << 16), 1 << 22))
    rng = np.random.default_rng(7)
    s.sql("CREATE TABLE code_agg_rle (r DOUBLE) USING column")
    rvals = np.sort(rng.choice(
        np.array([1.0, 2.0, 5.0, 9.0, 12.0]), nprobe))
    s.insert_arrays("code_agg_rle", [rvals])
    s.catalog.describe("code_agg_rle").data.force_rollover()
    q_rle = "SELECT sum(r), count(r) FROM code_agg_rle WHERE r < 9.0"

    def best_of(q):
        s.sql(q)                      # compile + first run
        best = float("inf")
        for _ in range(repeats):
            t0 = time.time()
            out = s.sql(q).rows()
            best = min(best, time.time() - t0)
        return best, out

    saved = props.get("agg_on_codes")
    try:
        props.set("agg_on_codes", "on")
        c0 = dict(reg.snapshot()["counters"])
        tg_on, g_on = best_of(q_group)
        tr_on, r_on = best_of(q_rle)
        c1 = dict(reg.snapshot()["counters"])
        props.set("agg_on_codes", "off")
        tg_off, g_off = best_of(q_group)
        tr_off, r_off = best_of(q_rle)
        # the PRODUCTION leg the throughput guard prices: auto resolves
        # per backend (dictionary-space scatter is serial on CPU, so
        # auto keeps it for accelerators; forced-on above still proves
        # lane counters + value equality everywhere)
        props.set("agg_on_codes", "auto")
        tg_auto, g_auto = best_of(q_group)
    finally:
        props.set("agg_on_codes", saved)

    # identical values both ways (same inputs, fp-noise tolerance only)
    assert len(g_on) == len(g_off), (g_on, g_off)
    for a, b in zip(g_on, g_off):
        assert a[0] == b[0] and a[1] == b[1] and a[2] == b[2], (a, b)
        for x, y in zip(a[3:], b[3:]):
            assert abs(x - y) <= 1e-9 * max(abs(y), 1.0), (a, b)
    assert r_on[0][1] == r_off[0][1], (r_on, r_off)
    assert abs(r_on[0][0] - r_off[0][0]) \
        <= 1e-9 * max(abs(r_off[0][0]), 1.0), (r_on, r_off)
    assert [r[:3] for r in g_auto] == [r[:3] for r in g_off], \
        (g_auto, g_off)

    # decode-throughput law over the grouped query's columns: encoded
    # at-rest bytes vs the 8 B/row the decoded gather path must stream
    enc_b = dec_b = 0
    for v in data.snapshot().views:
        for ci in (4, 6, 8, 9):   # quantity, discount, returnflag, status
            enc_b += v.batch.columns[ci].nbytes
            dec_b += v.batch.num_rows * 8
    byte_ratio = round(dec_b / enc_b, 2) if enc_b else 1.0
    off_rate = rows / tg_off
    predicted = off_rate * (byte_ratio
                            if jax.default_backend() == "tpu" else 1.0)

    lanes = {k: c1.get(k, 0) - c0.get(k, 0)
             for k in ("agg_code_domain", "agg_dict_space",
                       "agg_rle_runs")}
    return {
        "grouped_rows_per_s_on": round(rows / tg_on, 1),
        "grouped_rows_per_s_off": round(off_rate, 1),
        "grouped_rows_per_s_auto": round(rows / tg_auto, 1),
        "rle_rows_per_s_on": round(nprobe / tr_on, 1),
        "rle_rows_per_s_off": round(nprobe / tr_off, 1),
        "byte_ratio": byte_ratio,
        "predicted_rows_per_s": round(predicted, 1),
        "lane_counters": lanes,
        "values_asserted": True,
    }


def _pallas_fused_bench(s, repeats: int) -> dict:
    """Fused decode+filter+aggregate kernels over the CODE-DOMAIN binds
    of the loaded lineitem table (interpret mode off-TPU): Q6 through
    ops/pallas_reduce.fused_code_filter_sum (code-threshold filters +
    in-kernel dictionary decode) and the Q1 shape through
    ops/pallas_group.grouped_code_reduce (per-group Kahan partials over
    code slots, host-TRANSFORMED dictionaries for the (1-disc)/(1+tax)
    factors).  Row counts and sums are asserted against the engine's own
    answers before anything is timed."""
    import datetime

    import jax

    from snappydata_tpu.ops.pallas_group import grouped_code_reduce
    from snappydata_tpu.ops.pallas_reduce import fused_code_filter_sum
    from snappydata_tpu.storage.device import build_device_table
    from snappydata_tpu.storage.device_decode import CodePlate
    from snappydata_tpu.utils import tpch

    QTY, PRICE, DISC, TAX, RF, LS, SHIP = 4, 5, 6, 7, 8, 9, 10
    data = s.catalog.lookup_table("lineitem").data
    dt = build_device_table(data, None,
                            [QTY, PRICE, DISC, TAX, RF, LS, SHIP])
    qp, dp, tp = dt.columns[QTY], dt.columns[DISC], dt.columns[TAX]
    if not all(isinstance(x, CodePlate) for x in (qp, dp, tp)):
        raise RuntimeError("lineitem measure columns are not code-bound "
                           "(scan_compressed_domain off?)")
    ship, price, valid = dt.columns[SHIP], dt.columns[PRICE], dt.valid
    B = int(valid.shape[0])

    def days(sdate: str) -> int:
        d = datetime.date.fromisoformat(sdate)
        return (d - datetime.date(1970, 1, 1)).days

    def thresh(ci, lit, side):
        dom, sizes = dt.dict_domains[ci]
        out = np.zeros(B, dtype=np.int32)
        for i in range(B):
            sz = int(sizes[i])
            out[i] = np.searchsorted(dom[i, :sz], lit, side) if sz else 0
        return out

    # ---- Q6: code-threshold filter + in-kernel discount decode ---------
    qty_hi = thresh(QTY, 24.0, "left")
    dlo = thresh(DISC, 0.05, "left")
    dhi = thresh(DISC, 0.07, "right") - 1
    slo, shi = days("1994-01-01"), days("1995-01-01")
    exp_cnt = s.sql(
        "SELECT count(*) FROM lineitem "
        "WHERE l_shipdate >= DATE '1994-01-01' "
        "AND l_shipdate < DATE '1995-01-01' "
        "AND l_discount BETWEEN 0.05 AND 0.07 "
        "AND l_quantity < 24").rows()[0][0]
    exp_rev = s.sql(tpch.Q6).rows()[0][0]

    def run_q6():
        return fused_code_filter_sum(qp.codes, dp.codes, ship, price,
                                     valid, dp.dicts, qty_hi, dlo, dhi,
                                     slo, shi)
    total, count = jax.block_until_ready(run_q6())   # compile + check
    assert int(count) == int(exp_cnt), (int(count), int(exp_cnt))
    rel = abs(float(total) - exp_rev) / max(abs(exp_rev), 1.0)
    assert rel <= 5e-5, (float(total), exp_rev, rel)
    best6 = float("inf")
    for _ in range(max(repeats, 3)):
        t0 = time.time()
        jax.block_until_ready(run_q6())
        best6 = min(best6, time.time() - t0)

    # ---- Q1 shape: grouped code reduction, dictionary-space factors ----
    rf, ls = dt.columns[RF], dt.columns[LS]
    rfd, lsd = dt.dictionaries[RF], dt.dictionaries[LS]
    nls = max(1, len(lsd))
    G = max(1, len(rfd)) * nls
    gidx = rf * nls + ls
    lim = days("1998-12-01") - 90
    mask = valid & (ship <= lim)
    qdom, _ = dt.dict_domains[QTY]
    ddom, _ = dt.dict_domains[DISC]
    tdom, _ = dt.dict_domains[TAX]

    def run_q1():
        return grouped_code_reduce(
            gidx, mask,
            [("count",),
             ("sum", None, [(qp.codes, qdom)]),
             ("sum", price, []),
             ("sum", price, [(dp.codes, 1.0 - ddom)]),
             ("sum", price, [(dp.codes, 1.0 - ddom),
                             (tp.codes, 1.0 + tdom)])],
            G)
    outs = jax.block_until_ready(run_q1())
    engine = {(r[0], r[1]): r for r in s.sql(tpch.Q1).rows()}
    for g in range(G):
        cnt = int(outs[0][g])
        key = (str(rfd[g // nls]), str(lsd[g % nls]))
        if key not in engine:
            assert cnt == 0, (key, cnt)
            continue
        row = engine[key]
        assert cnt == int(row[9]), (key, cnt, row[9])   # row-count sanity
        for got, exp in ((float(outs[1][g]), row[2]),
                         (float(outs[2][g]), row[3]),
                         (float(outs[3][g]), row[4]),
                         (float(outs[4][g]), row[5])):
            assert abs(got - exp) <= 5e-5 * max(abs(exp), 1.0), \
                (key, got, exp)
    best1 = float("inf")
    for _ in range(max(repeats, 3)):
        t0 = time.time()
        jax.block_until_ready(run_q1())
        best1 = min(best1, time.time() - t0)
    return {"q6_pallas_s": round(best6, 4), "q1_pallas_s": round(best1, 4),
            "pallas_mode": "interpret"
            if jax.default_backend() != "tpu" else "compiled"}


def _decode_counters():
    try:
        from snappydata_tpu.storage import device_decode

        return device_decode.counters()
    except Exception:  # pragma: no cover - instrumentation only
        return None


def _device_only_best(s, q: str, repeats: int) -> float:
    """Best wall time of the COMPILED query program on device-resident
    arrays (block_until_ready) — no session, no bind, no host decode."""
    import functools

    import jax
    import jax.numpy as jnp

    from snappydata_tpu.engine.executor import Compiler, _param_scalar
    from snappydata_tpu.sql.analyzer import tokenize_plan
    from snappydata_tpu.sql.optimizer import optimize
    from snappydata_tpu.sql.parser import parse

    plan = optimize(parse(q).plan, s.catalog)
    resolved, _ = s.analyzer.analyze_plan(plan)
    node = resolved
    while not hasattr(node, "agg_exprs"):
        node = node.children()[0]
    tokenized, params = tokenize_plan(node)
    compiled = Compiler(s.catalog, s.conf).compile(tokenized)
    tables = [r.bind() for r in compiled.relations]
    arrays = []
    for r, dt in zip(compiled.relations, tables):
        for ci in r.used:
            arrays.append((dt.columns[ci], dt.nulls.get(ci)))
        arrays.append(dt.valid)
    aux = tuple(jnp.asarray(b(params)) for b in compiled.aux_builders)
    static = tuple(p() for p in compiled.static_providers)
    pvals = tuple(_param_scalar(v) for v in params)
    fn = jax.jit(functools.partial(compiled.traced, static))
    jax.block_until_ready(fn(tuple(arrays), aux, pvals))  # compile
    best = float("inf")
    for _ in range(max(repeats, 3)):
        t0 = time.time()
        jax.block_until_ready(fn(tuple(arrays), aux, pvals))
        best = min(best, time.time() - t0)
    return best


def _assert_q1_values(s, sf: float) -> float:
    """Engine Q1 vs an exact numpy float64 oracle over the same
    (f32-rounded when on TPU) inputs; returns max relative error and
    raises if it exceeds 2e-6."""
    import datetime

    from snappydata_tpu import config
    from snappydata_tpu.utils import tpch

    n_l = max(1000, int(tpch.LINEITEM_ROWS_PER_SF * sf))
    col = tpch.gen_lineitem(n_l, 17)
    f32 = not config.use_float64()

    def dev(a):
        a = np.asarray(a, dtype=np.float64)
        return a.astype(np.float32).astype(np.float64) if f32 else a

    qty, price = dev(col["l_quantity"]), dev(col["l_extendedprice"])
    disc, tax = dev(col["l_discount"]), dev(col["l_tax"])
    rf, ls = col["l_returnflag"], col["l_linestatus"]
    lim = (datetime.date(1998, 12, 1) - datetime.timedelta(days=90)
           - datetime.date(1970, 1, 1)).days
    keep = col["l_shipdate"] <= lim
    if f32:
        dp = (price.astype(np.float32)
              * (1 - disc).astype(np.float32)).astype(np.float64)
        ch = (dp.astype(np.float32)
              * (1 + tax).astype(np.float32)).astype(np.float64)
    else:
        dp = price * (1 - disc)
        ch = dp * (1 + tax)
    got = {(r[0], r[1]): r for r in s.sql(tpch.Q1).rows()}
    max_rel = 0.0
    for key in {(a, b) for a, b in zip(rf[keep], ls[keep])}:
        m = keep & (rf == key[0]) & (ls == key[1])
        row = got[key]
        oracle = [qty[m].sum(), price[m].sum(), dp[m].sum(), ch[m].sum()]
        for got_v, exact_v in zip(row[2:6], oracle):
            rel = abs(got_v - exact_v) / max(abs(exact_v), 1.0)
            max_rel = max(max_rel, rel)
            assert rel <= 2e-6, (key, got_v, exact_v, rel)
        assert row[9] == int(m.sum()), key
    return max_rel


def _ingest_bench(n: int = 2_000_000) -> float:
    """Bulk columnar ingest rows/s through the native (_fastingest)
    path: ints + floats + a dictionary-encoded string column."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE ingest_t (k BIGINT, name STRING, v DOUBLE) "
          "USING column")
    rng = np.random.default_rng(23)
    k = np.arange(n, dtype=np.int64)
    name = np.array([f"n{i & 1023}" for i in range(n)], dtype=object)
    v = rng.random(n)
    t0 = time.time()
    s.insert_arrays("ingest_t", [k, name, v])
    dt = time.time() - t0
    s.stop()
    return round(n / dt, 1)


def _durable_ingest_bench(n_stmts: int = 64,
                          rows_per_stmt: int = 20_000) -> dict:
    """Durable ingest rows/s + WAL fsync count per wal_fsync_mode —
    `group` (default) vs `always` (the pre-group-commit behavior). The
    per-statement stream is the shape where grouping matters: `group`
    coalesces concurrent commits and pipelines encode against the
    fsync, `always` pays one fsync per record."""
    import shutil
    import tempfile
    import threading

    from snappydata_tpu import SnappySession, config
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.observability.metrics import global_registry

    out = {}
    props = config.global_properties()
    saved = props.get("wal_fsync_mode")
    # warmup outside the timed region: the first durable session pays
    # one-time import/encode costs that would bias whichever mode ran
    # first
    wd = tempfile.mkdtemp(prefix="snappy_bench_wal_warm_")
    w = SnappySession(catalog=Catalog(), data_dir=wd, recover=False)
    w.sql("CREATE TABLE w (k BIGINT, v DOUBLE) USING column")
    for i in range(8):
        w.insert_arrays("w", [np.arange(1000, dtype=np.int64),
                              np.ones(1000)])
    w.stop()
    w.disk_store.close()
    shutil.rmtree(wd, ignore_errors=True)
    try:
        for mode in ("group", "always"):
            props.set("wal_fsync_mode", mode)
            d = tempfile.mkdtemp(prefix=f"snappy_bench_wal_{mode}_")
            s = SnappySession(catalog=Catalog(), data_dir=d,
                              recover=False)
            s.sql("CREATE TABLE w (k BIGINT, v DOUBLE) USING column")
            fsync0 = global_registry().counter("wal_fsync_count")
            chunks = [np.arange(i * rows_per_stmt, (i + 1) * rows_per_stmt,
                                dtype=np.int64) for i in range(n_stmts)]
            t0 = time.time()
            # 4 concurrent committers: the group-commit coalescing shape
            workers = []
            for w in range(4):
                def run(lo=w):
                    for i in range(lo, n_stmts, 4):
                        s.insert_arrays("w", [chunks[i],
                                              chunks[i] * 0.5])
                workers.append(threading.Thread(target=run))
            for t in workers:
                t.start()
            for t in workers:
                t.join()
            dt = time.time() - t0
            fsyncs = global_registry().counter("wal_fsync_count") - fsync0
            out[mode] = {
                "rows_per_s": round(n_stmts * rows_per_stmt / dt, 1),
                "fsyncs": fsyncs,
                "statements": n_stmts,
            }
            s.stop()
            s.disk_store.close()
            shutil.rmtree(d, ignore_errors=True)
    finally:
        props.set("wal_fsync_mode", saved)
    return out


def _sink_bench(n: int = 200_000) -> float:
    """Kafka→table events/s through the exactly-once sink (BASELINE.md
    north-star: 1M events/s)."""
    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.streaming.kafka import InProcessBroker, KafkaSource
    from snappydata_tpu.streaming.query import StreamingQuery

    from snappydata_tpu import types as T

    s = SnappySession(catalog=Catalog())
    schema = T.Schema([T.Field("id", T.LONG, False),
                       T.Field("v", T.DOUBLE, True)])
    s.catalog.create_table("sink_t", schema, "column", {},
                           key_columns=("id",))
    broker = InProcessBroker(num_partitions=8)
    broker.produce("ev", [{"id": i, "v": 1.0} for i in range(n)])
    src = KafkaSource(s, "bench_q", broker, "ev", ["id", "v"],
                      max_records_per_batch=100_000)
    q = StreamingQuery(s, "bench_q", src, "sink_t")
    t0 = time.time()
    q.process_available()
    dt = time.time() - t0
    got = s.sql("SELECT count(*) FROM sink_t").rows()[0][0]
    assert got == n, (got, n)
    s.stop()
    return round(n / dt, 1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--check":
        sys.exit(run_check(sys.argv[2:]))
    if len(sys.argv) > 1 and sys.argv[1] == "--multichip-child":
        _multichip_child()
        sys.exit(0)
    if len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        # standalone multichip run: prints the record and (with an
        # output path) writes the committed MULTICHIP_r*.json shape
        rec = _multichip_bench()
        rec_out = {"n_devices": rec.get("n_devices", 8), "rc": 0,
                   "ok": rec.get("value_mismatches", 1) == 0,
                   "skipped": False, "measured": rec}
        print(json.dumps(rec_out, indent=1))
        if len(sys.argv) > 2:
            with open(sys.argv[2], "w") as fh:
                json.dump(rec_out, fh, indent=1)
        sys.exit(0 if rec_out["ok"] else 1)
    main()
