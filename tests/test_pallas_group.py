"""Fused Pallas grouped-aggregate kernel (ops/pallas_group.py):
kernel-level accuracy vs exact f64 oracles, and the engine's Q1-shape
integration behind properties.pallas_group_reduce. On CPU the kernel
runs in interpreter mode — correctness only; the TPU timing story is
recorded by bench.py (`q1_pallas_s`) when hardware is reachable."""

import jax.numpy as jnp
import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.ops.pallas_group import grouped_reduce


def test_kernel_all_kinds_vs_oracle():
    rng = np.random.default_rng(0)
    n = 300_000
    G = 7
    gidx = rng.integers(0, G, n)
    v1 = (rng.random(n) * 2e4).astype(np.float32)  # same-sign: f32-hostile
    v2 = (rng.random(n) * 100 - 50).astype(np.float32)
    m1 = rng.random(n) < 0.9
    m2 = rng.random(n) < 0.7
    outs = grouped_reduce(
        [("sum", jnp.asarray(v1), jnp.asarray(m1)),
         ("count", None, jnp.asarray(m1)),
         ("min", jnp.asarray(v2), jnp.asarray(m2)),
         ("max", jnp.asarray(v2), jnp.asarray(m2)),
         ("sum", jnp.asarray(v2), jnp.asarray(m2))],
        jnp.asarray(gidx), G)
    for g in range(G):
        s1 = (gidx == g) & m1
        s2 = (gidx == g) & m2
        exact = v1.astype(np.float64)[s1].sum()
        assert float(outs[0][g]) == pytest.approx(exact, rel=1e-7)
        assert int(outs[1][g]) == int(s1.sum())
        assert float(outs[2][g]) == v2[s2].min()
        assert float(outs[3][g]) == v2[s2].max()
        # mixed-sign sum: compensated error is bounded vs sum(|v|)
        exact2 = v2.astype(np.float64)[s2].sum()
        assert abs(float(outs[4][g]) - exact2) \
            <= 1e-6 * np.abs(v2[s2].astype(np.float64)).sum()


def test_kernel_padding_and_empty_groups():
    rng = np.random.default_rng(1)
    for n in (1, 7, 1024, 131073):
        G = 5
        # group 4 stays empty: min/max must yield the +/-inf fillers
        # _seg_reduce produces so downstream gvalid handling matches
        gidx = rng.integers(0, 4, n)
        v = (rng.random(n) * 10).astype(np.float32)
        m = np.ones(n, dtype=bool)
        outs = grouped_reduce(
            [("sum", jnp.asarray(v), jnp.asarray(m)),
             ("count", None, jnp.asarray(m)),
             ("min", jnp.asarray(v), jnp.asarray(m)),
             ("max", jnp.asarray(v), jnp.asarray(m))],
            jnp.asarray(gidx), G)
        assert float(outs[0][4]) == 0.0
        assert int(outs[1][4]) == 0
        assert float(outs[2][4]) == np.inf
        assert float(outs[3][4]) == -np.inf
        for g in range(4):
            sel = gidx == g
            if not sel.any():
                continue
            assert float(outs[0][g]) == pytest.approx(
                v.astype(np.float64)[sel].sum(), rel=1e-6, abs=1e-6)
            assert int(outs[1][g]) == int(sel.sum())


def test_input_dedup_shares_masks_and_values(monkeypatch):
    """Ops sharing a mask (Q1: every slot) or a value array cross the
    host->VMEM boundary ONCE: the kernel spec must reference one
    deduplicated input, not per-op copies."""
    from snappydata_tpu.ops import pallas_group as pg

    captured = {}
    orig = pg._grouped_call

    def spy(gidx2d, ins, spec, G, interpret):
        captured["n_ins"] = len(ins)
        captured["spec"] = spec
        return orig(gidx2d, ins, spec, G, interpret)

    monkeypatch.setattr(pg, "_grouped_call", spy)
    rng = np.random.default_rng(3)
    n = 4096
    v = jnp.asarray((rng.random(n) * 10).astype(np.float32))
    m = jnp.asarray(np.ones(n, dtype=bool))
    gidx = jnp.asarray(rng.integers(0, 3, n))
    outs = pg.grouped_reduce(
        [("sum", v, m), ("count", None, m), ("min", v, m),
         ("max", v, m)], gidx, 3)
    # one value array + one mask array — not 3 values + 4 masks
    assert captured["n_ins"] == 2, captured
    kinds = [s[0] for s in captured["spec"]]
    assert kinds == ["sum", "count", "min", "max"]
    assert len({s[2] for s in captured["spec"]}) == 1   # shared mask
    vis = {s[1] for s in captured["spec"] if s[1] is not None}
    assert len(vis) == 1                                # shared values
    exact = np.asarray(v, dtype=np.float64)
    g = np.asarray(gidx)
    for gi in range(3):
        assert float(outs[0][gi]) == pytest.approx(
            exact[g == gi].sum(), rel=1e-7)
        assert int(outs[1][gi]) == int((g == gi).sum())


def test_executor_interns_shared_arg_arrays(monkeypatch):
    """Through the ENGINE, slots over the same argument (sum/min/max/
    avg of one column + count(*)) must reach grouped_reduce as shared
    array objects so the id()-keyed dedup fires (review finding: each
    slot's emit produced fresh arrays and the dedup never triggered)."""
    from snappydata_tpu.ops import pallas_group as pg

    captured = {}
    orig = pg._grouped_call

    def spy(gidx2d, ins, spec, G, interpret):
        captured["n_ins"] = len(ins)
        captured["spec"] = spec
        return orig(gidx2d, ins, spec, G, interpret)

    monkeypatch.setattr(pg, "_grouped_call", spy)
    old = config.global_properties().pallas_group_reduce
    old_f64 = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = False
    config.global_properties().pallas_group_reduce = True
    try:
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE sh (k STRING, x DOUBLE) USING column")
        rng = np.random.default_rng(8)
        n = 20_000
        s.insert_arrays("sh", [
            rng.choice(np.array(["a", "b"], dtype=object), n),
            np.round(rng.random(n) * 100, 2)])
        rows = s.sql("SELECT k, sum(x), min(x), max(x), avg(x), "
                     "count(*) FROM sh GROUP BY k ORDER BY k").rows()
        assert len(rows) == 2
        # one value block (x) + one mask block — not one pair per slot
        assert captured["n_ins"] == 2, captured
        assert len({sp[2] for sp in captured["spec"]}) == 1
        assert len({sp[1] for sp in captured["spec"]
                    if sp[1] is not None}) == 1
        s.stop()
    finally:
        config.global_properties().pallas_group_reduce = old
        config.global_properties().decimal_as_float64 = old_f64


def _q1_sessions():
    """Two identical sessions over a Q1-shaped table; one runs the
    fused pallas grouped path, one the _seg_reduce baseline."""
    rng = np.random.default_rng(2)
    n = 120_000
    flag = rng.choice(np.array(["A", "N", "R"], dtype=object), n)
    status = rng.choice(np.array(["F", "O"], dtype=object), n)
    qty = np.round(rng.random(n) * 50, 0)
    price = np.round(rng.random(n) * 2e4, 2)
    disc = np.round(rng.random(n) * 0.1, 2)

    def mk():
        s = SnappySession(catalog=Catalog())
        s.sql("CREATE TABLE li (flag STRING, status STRING, qty DOUBLE,"
              " price DOUBLE, disc DOUBLE) USING column")
        s.insert_arrays("li", [flag, status, qty, price, disc])
        return s

    return mk, (flag, status, qty, price, disc)


Q1 = ("SELECT flag, status, sum(qty), sum(price),"
      " sum(price * (1 - disc)), avg(qty), avg(disc), count(*),"
      " min(price), max(price)"
      " FROM li WHERE qty < 45 GROUP BY flag, status"
      " ORDER BY flag, status")


def test_engine_q1_shape_via_pallas():
    # f32 plates (the TPU storage policy) are required for eligibility —
    # force them on CPU so the fused path actually engages
    old = config.global_properties().pallas_group_reduce
    old_f64 = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = False
    try:
        mk, (flag, status, qty, price, disc) = _q1_sessions()
        s = mk()
        baseline = s.sql(Q1).rows()
        config.global_properties().pallas_group_reduce = True
        s2 = mk()
        got = s2.sql(Q1).rows()
        assert len(got) == len(baseline) == 6
        for rg, rb in zip(got, baseline):
            assert rg[0] == rb[0] and rg[1] == rb[1]
            for a, b in zip(rg[2:], rb[2:]):
                assert a == pytest.approx(b, rel=2e-6)
        # independent exact oracle for one group
        sel = (flag == "A") & (status == "F") & (qty < 45)
        row = [r for r in got if r[0] == "A" and r[1] == "F"][0]
        assert row[2] == pytest.approx(qty[sel].sum(), rel=1e-7)
        assert row[7] == int(sel.sum())
        assert row[8] == pytest.approx(price[sel].min(), rel=1e-6)
        s.stop()
        s2.stop()
    finally:
        config.global_properties().pallas_group_reduce = old
        config.global_properties().decimal_as_float64 = old_f64


def test_engine_wide_aggregate_respects_vmem_budget():
    """A wide slot batch must stop fusing at the VMEM budget and route
    the overflow slots through _seg_reduce — never fail the compile."""
    old = config.global_properties().pallas_group_reduce
    old_f64 = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = False
    try:
        rng = np.random.default_rng(5)
        n = 5_000
        k = rng.choice(np.array(["x", "y", "z"], dtype=object), n)
        cols = [np.round(rng.random(n) * 100, 2) for _ in range(12)]

        def mk():
            s = SnappySession(catalog=Catalog())
            decls = ", ".join(f"c{i} DOUBLE" for i in range(12))
            s.sql(f"CREATE TABLE w (k STRING, {decls}) USING column")
            s.insert_arrays("w", [k] + cols)
            return s

        sums = ", ".join(f"sum(c{i})" for i in range(12))
        mins = ", ".join(f"min(c{i})" for i in range(6))
        q = f"SELECT k, {sums}, {mins}, count(*) FROM w GROUP BY k ORDER BY k"
        s = mk()
        baseline = s.sql(q).rows()
        config.global_properties().pallas_group_reduce = True
        s2 = mk()
        got = s2.sql(q).rows()
        for rg, rb in zip(got, baseline):
            assert rg[0] == rb[0]
            for a, b in zip(rg[1:], rb[1:]):
                assert a == pytest.approx(b, rel=2e-6)
        s.stop()
        s2.stop()
    finally:
        config.global_properties().pallas_group_reduce = old
        config.global_properties().decimal_as_float64 = old_f64


def test_engine_nullable_key_and_empty_group():
    """Nullable group key (extra code slot) and int sums (ineligible —
    mixed fused/non-fused slot batch) stay correct under the flag."""
    old = config.global_properties().pallas_group_reduce
    old_f64 = config.global_properties().decimal_as_float64
    config.global_properties().decimal_as_float64 = False
    try:
        def mk():
            s = SnappySession(catalog=Catalog())
            s.sql("CREATE TABLE t (k STRING, v DOUBLE, i INT) USING column")
            s.sql("INSERT INTO t VALUES ('a', 1.5, 10), ('a', 2.5, 20),"
                  " (NULL, 4.0, 40), ('b', 8.0, 80), (NULL, 0.5, 5)")
            return s

        q = ("SELECT k, sum(v), sum(i), count(v), min(v), max(v) FROM t"
             " GROUP BY k ORDER BY k")
        s = mk()
        baseline = s.sql(q).rows()
        config.global_properties().pallas_group_reduce = True
        s2 = mk()
        got = s2.sql(q).rows()
        assert got == baseline
        assert [r[0] for r in got] == [None, "a", "b"]
        byk = {r[0]: r for r in got}
        assert byk["a"][1:] == (4.0, 30, 2, 1.5, 2.5)
        assert byk[None][1:] == (4.5, 45, 2, 0.5, 4.0)
        s.stop()
        s2.stop()
    finally:
        config.global_properties().pallas_group_reduce = old
        config.global_properties().decimal_as_float64 = old_f64
