"""Double-buffered host→HBM tile prefetch for the out-of-core scan.

A tiled pass over a table bigger than the device budget alternates
upload and compute: bind window k, aggregate window k, bind window k+1…
— paying min-transfer PLUS compute per tile.  The prefetcher overlaps
them: while the partial program aggregates tile k on device, a
background worker warms tile k+1's encoded plates through the SAME bind
path (`device.build_device_table` under its own per-thread
`scan_window`), so the device cache already holds window k+1 when the
consumer arrives and the steady-state rate approaches
min(compute, transfer) — the decode-throughput law's streaming bound
(PAPERS.md), with the PR 9 encoded plates (~25 B/row) as the wire
format.

Mesh-aware: the worker enters the consumer's captured `MeshContext`, so
its cache keys carry the same mesh token and its `device_put`s shard
per `ShardPlacement` — each device receives only its own buckets.  The
worker's placements run inside `parallel.mesh_dispatch`
(mesh.prefetch_fence) like every other multi-device dispatch: an
UNFENCED background upload interleaving with a foreground collective is
exactly the rendezvous-deadlock class PR 13's lock exists for.

Coordination is one module lock, `storage.prefetch` — a LEAF: nothing
is acquired while it is held (metric increments and thread joins happen
outside; the build itself runs unlocked).  The keep-window registry it
guards tells the device cache's window prune which tile entries are
live look-ahead — without it, the consumer binding window k would evict
the window k+1 entry the worker just paid for.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Set, Tuple

from snappydata_tpu.utils import locks

# one lock for every prefetcher AND the keep-window registry: prefetch
# passes are per-statement and coordination is rare (one wait per tile)
_pf_lock = locks.named_lock("storage.prefetch")
_KEEP: Dict[int, Set[Tuple[int, int]]] = {}   # id(data) -> live windows

_COL_KINDS = ("col", "ccol", "scol", "mcol", "acol")


def keep_windows(data) -> Set[Tuple[int, int]]:
    """Windows of `data` a live prefetch pass owns — the device cache's
    window prune must not evict these (storage/device.py consults this
    before dropping sibling tile entries)."""
    with _pf_lock:
        s = _KEEP.get(id(data))
        return set(s) if s else set()


def _reg():
    from snappydata_tpu.observability.metrics import global_registry

    return global_registry()


# live worker threads (for the dashboard storage section); mutated only
# under _pf_lock
_LIVE_WORKERS: Set[int] = set()


def worker_snapshot() -> dict:
    """Point-in-time prefetch-worker health for observability: live
    worker count plus the lifetime death/restart counters — the signal
    that distinguishes 'prefetcher restarting through faults' from
    'prefetcher silently degraded to inline binds'."""
    reg = _reg()
    with _pf_lock:
        live = len(_LIVE_WORKERS)
    return {"live_workers": live,
            "worker_deaths": reg.counter("prefetch_worker_deaths"),
            "worker_restarts": reg.counter("prefetch_worker_restarts"),
            "errors": reg.counter("prefetch_errors"),
            "windows_warmed": reg.counter("prefetch_windows_warmed")}


class TilePrefetcher:
    """Warms tile windows of one (data, manifest, columns) scan ahead of
    the consumer.  Protocol (both tiled lanes use it identically):

        pf = TilePrefetcher.maybe(data, manifest, units, tile_units, ctx)
        try:
            for lo in range(0, units, tile_units):
                if pf: pf.await_window(lo)        # block until warm
                with scan_window(...): dispatch(lo)
                if pf: pf.advance(lo)             # release look-ahead
        finally:
            if pf: pf.close()                     # join + drop tiles

    Window 0 binds inline on the consumer (its entry seeds the column
    set the worker warms); the worker stays `tier_prefetch_depth`
    windows ahead of the last advance.  A worker death (any exception)
    is absorbed: the consumer falls back to inline binds.
    """

    def __init__(self, data, manifest, units: int, tile_units: int,
                 depth: int, mesh_ctx=None) -> None:
        self._data = data
        self._manifest = manifest
        self._units = int(units)
        self._tile_units = int(tile_units)
        self._depth = max(1, int(depth))
        self._mesh_ctx = mesh_ctx
        self._cols: Optional[Tuple[int, ...]] = None
        self._cond = locks.named_condition("storage.prefetch",
                                           lock=_pf_lock)
        self._done: Dict[int, float] = {}   # lo -> build ms
        self._consumed = 0                  # last advanced lo
        self._next = self._tile_units       # next lo the worker builds
        self._stop = False
        self._dead = False
        self._worker: Optional[threading.Thread] = None
        self._overlap_ms = 0.0
        self._overlapped = False

    @classmethod
    def maybe(cls, data, manifest, units: int, tile_units: int,
              mesh_ctx=None) -> Optional["TilePrefetcher"]:
        from snappydata_tpu import config

        depth = int(config.global_properties().tier_prefetch_depth)
        if depth <= 0 or units <= tile_units or tile_units <= 0:
            return None
        return cls(data, manifest, units, tile_units, depth, mesh_ctx)

    # -- consumer side ---------------------------------------------------

    def await_window(self, lo: int) -> None:
        """Block (bounded) until window `lo` is warm in the device
        cache, and mark it the consumer's active window so neither
        side's prune evicts it.  Overlap won = the build time the
        consumer did NOT have to wait for."""
        self._keep((lo, min(lo + self._tile_units, self._units)))
        if lo < self._tile_units or self._worker is None:
            return
        reg = _reg()
        t0 = time.perf_counter()
        waited = False
        deadline = t0 + 30.0
        with self._cond:
            while lo not in self._done and not self._dead:
                waited = True
                if time.perf_counter() >= deadline:
                    self._dead = True   # wedged worker: inline fallback
                    break
                self._cond.wait(0.25)
            build_ms = self._done.get(lo)
        if waited:
            reg.inc("prefetch_window_waits")
        if build_ms is not None:
            waited_ms = (time.perf_counter() - t0) * 1000.0
            won = max(0.0, build_ms - waited_ms)
            if won > 0:
                self._overlap_ms += won
                self._overlapped = True

    def advance(self, lo: int) -> None:
        """Consumer dispatched window `lo`: retire older look-ahead and
        let the worker run up to `lo + depth * tile_units`.  advance(0)
        also infers the column set from the inline-bound window-0 cache
        entry and starts the worker."""
        horizon = lo
        with self._cond:
            self._consumed = lo
            ids = _KEEP.get(id(self._data))
            if ids:
                for w in [w for w in ids if w[0] < horizon]:
                    ids.discard(w)
            for k in [k for k in self._done if k < horizon]:
                self._done.pop(k)
            self._cond.notify_all()
        if lo == 0 and self._worker is None and not self._dead:
            self._start()

    def close(self) -> None:
        """End of pass: stop the worker, join OUTSIDE all locks, drop
        this pass's keep-windows and every orphaned tile entry (restores
        the ≤1-windowed-entry invariant), publish overlap counters."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        w = self._worker
        if w is not None:
            w.join(timeout=30.0)
        with self._cond:
            ids = _KEEP.get(id(self._data))
            if ids is not None:
                ids.clear()
                _KEEP.pop(id(self._data), None)
        kept = keep_windows(self._data)   # concurrent passes, if any
        cache = getattr(self._data, "_device_cache", None)
        if cache is not None:
            from snappydata_tpu.storage.device import _cache_budget

            # list(): C-atomic snapshot — another pass's worker may
            # still be inserting entries into this cache
            for k in [k for k in list(cache)
                      if k[2] is not None and k[2] not in kept]:
                cache.pop(k, None)
                _cache_budget.forget(cache, k)
        if self._overlapped:
            _reg().inc("prefetch_overlap_ms",
                       max(1, int(self._overlap_ms)))

    def overlap_ms(self) -> float:
        return self._overlap_ms

    # -- worker side -----------------------------------------------------

    def _keep(self, window: Tuple[int, int]) -> None:
        with self._cond:
            _KEEP.setdefault(id(self._data), set()).add(window)

    def _infer_cols(self) -> Optional[Tuple[int, ...]]:
        """Column set of the pass = the columns the consumer's inline
        window-0 bind cached (same manifest+token, window starting 0)."""
        cache = getattr(self._data, "_device_cache", None) or {}
        from snappydata_tpu.parallel.mesh import MeshContext

        ctx = self._mesh_ctx or MeshContext.current()
        token = ctx.token if ctx else None
        for key, entry in list(cache.items()):
            if key[0] != self._manifest.version or key[1] != token:
                continue
            if key[2] is None or key[2][0] != 0:
                continue
            cols = sorted({k[1] for k in list(entry)
                           if isinstance(k, tuple) and k[0] in _COL_KINDS})
            if cols:
                return tuple(cols)
        return None

    def _start(self) -> None:
        self._cols = self._infer_cols()
        if self._cols is None:
            self._dead = True   # nothing cached to mirror: stay inline
            return
        self._worker = threading.Thread(
            target=self._run, name="snappy-tile-prefetch", daemon=True)
        self._worker.start()

    def _run(self) -> None:
        """Worker body with SUPERVISION: an escaping exception (a real
        bug, an injected kill_worker, an OOM) no longer degrades the
        pass to inline binds forever — the worker restarts its loop with
        capped exponential backoff up to `tier_prefetch_max_restarts`
        times, and only an exhausted budget sets `_dead` (the bounded
        inline fallback the consumer already handles)."""
        from snappydata_tpu import config

        max_restarts = int(getattr(config.global_properties(),
                                   "tier_prefetch_max_restarts", 3))
        reg = _reg()
        tid = threading.get_ident()
        with _pf_lock:
            _LIVE_WORKERS.add(tid)
        try:
            attempt = 0
            while True:
                try:
                    if self._mesh_ctx is not None:
                        with self._mesh_ctx:
                            self._loop()
                    else:
                        self._loop()
                    return                       # clean stop
                except BaseException:
                    reg.inc("prefetch_errors")
                    reg.inc("prefetch_worker_deaths")
                    with self._cond:
                        stopped = self._stop
                    if stopped or attempt >= max_restarts:
                        with self._cond:
                            self._dead = True
                            self._cond.notify_all()
                        return
                    attempt += 1
                    reg.inc("prefetch_worker_restarts")
                    # capped backoff: fast enough that a one-shot
                    # injected death costs ~ms of look-ahead, slow
                    # enough that a hard-crashing loop can't spin
                    time.sleep(min(0.25, 0.02 * (2 ** (attempt - 1))))
        finally:
            with _pf_lock:
                _LIVE_WORKERS.discard(tid)

    def _loop(self) -> None:
        from snappydata_tpu.parallel import mesh
        from snappydata_tpu.storage import device as device_mod

        reg = _reg()
        while True:
            with self._cond:
                while not self._stop and not (
                        self._next < self._units
                        and self._next <= self._consumed
                        + self._depth * self._tile_units):
                    self._cond.wait(0.25)
                if self._stop:
                    return
                lo = self._next
                self._next += self._tile_units
            hi = min(lo + self._tile_units, self._units)
            self._keep((lo, hi))
            t0 = time.perf_counter()
            try:
                # the worker-body seam: kill_worker here escapes into
                # _run's supervision (restart w/ backoff), exactly the
                # uncaught-exception shape a real worker bug produces
                from snappydata_tpu.reliability import \
                    failpoints as rfail

                rfail.hit("prefetch.worker")
                # the worker's scan_window contextvar is PER-THREAD: the
                # consumer's window never sees this restriction
                with device_mod.scan_window(self._data, lo, hi,
                                            self._manifest,
                                            tile_units=self._tile_units):
                    with mesh.prefetch_fence():
                        device_mod.build_device_table(
                            self._data, self._manifest, self._cols,
                            code_ok=True)
            except BaseException:
                with self._cond:
                    # the restarted loop must rebuild THIS window — the
                    # consumer is (or will be) blocked on it; without
                    # the rewind a restart would skip it and the
                    # await_window deadline (30s) would pay for the kill
                    self._next = min(self._next, lo)
                    self._cond.notify_all()
                raise
            ms = (time.perf_counter() - t0) * 1000.0
            reg.inc("prefetch_windows_warmed")
            with self._cond:
                self._done[lo] = ms
                self._cond.notify_all()
