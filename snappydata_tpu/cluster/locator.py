"""Locator: membership, discovery, failure detection, lead election.

Re-provides the store engine's P2P membership surface the reference relies
on (SURVEY.md §2.5: locators + view management + `MembershipListener.
memberDeparted` that ExecutorInitiator.scala:71-90 uses to re-point
executors; `member-timeout` 5s default; the `__PRIMARY_LEADER_LS`
distributed lock LeadImpl.scala:100) — as a small TCP JSON-line service:

- members REGISTER (role, host, port) and HEARTBEAT; missing heartbeats
  past `member_timeout_s` → member departed, view version bumps, waiters
  notified on next poll.
- LOCK/UNLOCK implements lease-based named locks; the primary-lead lock is
  just the name "__PRIMARY_LEADER_LS" (standby leads block on it, exactly
  the reference's election).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import socket
import socketserver
import threading
from snappydata_tpu.utils import locks
import time
from typing import Dict, List, Optional, Tuple

from snappydata_tpu import config
from snappydata_tpu.fault import failpoints

_log = logging.getLogger("snappydata_tpu.cluster.locator")

PRIMARY_LEAD_LOCK = "__PRIMARY_LEADER_LS"

# bumped whenever the member-to-member wire contract changes (Flight
# request bodies, repartition/promote actions, WAL record format); the
# locator refuses registration from a member on a different generation
# (ref: SnappyDataVersion handshake)
PROTOCOL_VERSION = 2


@dataclasses.dataclass
class MemberInfo:
    member_id: str
    role: str          # locator | lead | server
    host: str
    port: int          # member's flight port (0 = none)
    last_heartbeat: float = 0.0


class _State:
    def __init__(self, timeout_s: float):
        self.lock = locks.named_lock("locator.state")
        self.members: Dict[str, MemberInfo] = {}
        self.view_version = 0
        self.locks: Dict[str, Tuple[str, float]] = {}  # name -> (owner, expiry)
        self.timeout_s = timeout_s
        self.departed_log: List[str] = []

    def sweep(self) -> None:
        now = time.time()
        with self.lock:
            dead = [m for m, info in self.members.items()
                    if info.role != "locator"
                    and now - info.last_heartbeat > self.timeout_s]
            for m in dead:
                del self.members[m]
                self.departed_log.append(m)
                self.view_version += 1
            # expire locks owned by departed members or past lease
            for name in list(self.locks):
                owner, expiry = self.locks[name]
                if owner not in self.members or now > expiry:
                    del self.locks[name]


class Locator:
    """The discovery/membership service (one per cluster; standby locators
    are a later round)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 member_timeout_s: Optional[float] = None):
        timeout = member_timeout_s or \
            config.global_properties().member_timeout_s
        self.state = _State(timeout)
        state = self.state

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line.decode("utf-8"))
                    except ValueError:
                        break
                    resp = _dispatch(state, req)
                    self.wfile.write(
                        (json.dumps(resp) + "\n").encode("utf-8"))
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Server((host, port), Handler)
        self.host, self.port = self.server.server_address
        self._thread: Optional[threading.Thread] = None
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> "Locator":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

        def sweep_loop():
            while not self._stop.wait(self.state.timeout_s / 4):
                self.state.sweep()

        self._sweeper = threading.Thread(target=sweep_loop, daemon=True)
        self._sweeper.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.server.shutdown()
        self.server.server_close()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


def _dispatch(state: _State, req: dict) -> dict:
    op = req.get("op")
    now = time.time()
    if op == "register":
        # version handshake (ref: SnappyDataVersion feature gating,
        # cluster/.../gemxd/SnappyDataVersion.scala): a member speaking a
        # different PROTOCOL generation is refused with a clear message
        # instead of failing later with undecodable exchanges
        peer = req.get("protocol", 0)
        if peer != PROTOCOL_VERSION:
            return {"ok": False,
                    "error": f"protocol version mismatch: member speaks "
                             f"{peer}, cluster speaks {PROTOCOL_VERSION}; "
                             f"upgrade/downgrade the member"}
        with state.lock:
            info = MemberInfo(req["member_id"], req["role"], req["host"],
                              req.get("port", 0), now)
            state.members[req["member_id"]] = info
            state.view_version += 1
            return {"ok": True, "view": state.view_version,
                    "protocol": PROTOCOL_VERSION}
    if op == "heartbeat":
        with state.lock:
            m = state.members.get(req["member_id"])
            if m is None:
                return {"ok": False, "rejoin": True}
            m.last_heartbeat = now
            return {"ok": True, "view": state.view_version}
    if op == "members":
        with state.lock:
            return {"ok": True, "view": state.view_version,
                    "members": [dataclasses.asdict(m)
                                for m in state.members.values()],
                    "departed": list(state.departed_log)}
    if op == "lock":
        name = req["name"]
        lease = float(req.get("lease_s", 30.0))
        with state.lock:
            cur = state.locks.get(name)
            if cur is not None and cur[0] != req["member_id"] \
                    and cur[1] > now and cur[0] in state.members:
                return {"ok": True, "acquired": False, "owner": cur[0]}
            state.locks[name] = (req["member_id"], now + lease)
            return {"ok": True, "acquired": True}
    if op == "unlock":
        with state.lock:
            cur = state.locks.get(req["name"])
            if cur is not None and cur[0] == req["member_id"]:
                del state.locks[req["name"]]
            return {"ok": True}
    if op == "deregister":
        with state.lock:
            state.members.pop(req["member_id"], None)
            state.view_version += 1
            return {"ok": True}
    return {"ok": False, "error": f"unknown op {op}"}


# members whose heartbeat loop GAVE UP (persistent protocol mismatch
# after retries): exposed as the `heartbeats_stopped` gauge so an
# operator can alarm on it — a silently-stopped heartbeat is how a
# healthy member gets swept out of the view
_HB_STOPPED: set = set()
_HB_GAUGE_REGISTERED = False


def _register_hb_gauge() -> None:
    global _HB_GAUGE_REGISTERED
    if not _HB_GAUGE_REGISTERED:
        from snappydata_tpu.observability.metrics import global_registry

        global_registry().gauge("heartbeats_stopped",
                                lambda: float(len(_HB_STOPPED)))
        _HB_GAUGE_REGISTERED = True


class LocatorClient:
    """A member's handle to the locator (persistent connection +
    heartbeat thread)."""

    # consecutive protocol-shaped (RuntimeError) heartbeat failures
    # tolerated with capped-backoff retries before the loop gives up —
    # a locator restart mid-upgrade answers the handshake wrong for a
    # few beats; a REAL version mismatch persists past the cap and
    # still stops loudly (gauge + error log)
    HEARTBEAT_GIVEUP = 5
    HEARTBEAT_BACKOFF_MAX_S = 30.0

    def __init__(self, address: str, member_id: str, role: str,
                 host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 5.0):
        self.address = address
        self.member_id = member_id
        self.role = role
        self.host = host
        self.port = port
        # connect AND read timeout: a wedged locator socket must not
        # park the heartbeat thread inside _lock forever (every other
        # locator call would then block on the lock behind it)
        self.request_timeout_s = request_timeout_s
        self._lock = locks.named_lock("locator.client")
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._hb: Optional[threading.Thread] = None
        self.last_view = -1

    def _close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _request(self, payload: dict) -> dict:
        with self._lock:
            if self._sock is None:
                h, p = self.address.rsplit(":", 1)
                self._sock = socket.create_connection(
                    (h, int(p)), timeout=self.request_timeout_s)
                # create_connection's timeout persists as the socket
                # timeout, but make the read deadline explicit — it is
                # the contract, not a connect-time leftover
                self._sock.settimeout(self.request_timeout_s)
                self._fh = self._sock.makefile("rwb")
            try:
                self._fh.write((json.dumps(payload) + "\n").encode("utf-8"))
                self._fh.flush()
                line = self._fh.readline()
            except (socket.timeout, OSError) as e:
                # timed-out/broken socket: its stream buffer is desynced,
                # drop it so the next request reconnects cleanly
                self._close_locked()
                raise ConnectionError(f"locator request failed: {e}")
            if not line:
                self._close_locked()
                raise ConnectionError("locator connection lost")
            try:
                return json.loads(line.decode("utf-8"))
            except ValueError:
                # partial/garbled response (locator died mid-write): the
                # stream is desynced — surface it as the connection loss
                # it is, so the heartbeat loop's re-register path (not a
                # silent thread death) handles it
                self._close_locked()
                raise ConnectionError("locator sent a garbled response")

    def register(self) -> dict:
        resp = self._request({"op": "register", "member_id": self.member_id,
                              "role": self.role, "host": self.host,
                              "port": self.port,
                              "protocol": PROTOCOL_VERSION})
        if not resp.get("ok", True) and "protocol" in str(
                resp.get("error", "")):
            raise RuntimeError(resp["error"])
        self.last_view = resp.get("view", -1)
        return resp

    def start_heartbeats(self, interval_s: float = 1.0) -> None:
        """Background heartbeat loop. Failures route through `logging`
        and the `member_heartbeat_failures` counter (a heartbeat thread
        that dies printing to stderr is how a member gets silently swept
        out — the metric is what an operator alarms on); transient
        connection errors re-register and keep beating.

        Protocol-shaped failures (RuntimeError — e.g. a locator restart
        mid-upgrade answering the version handshake wrong for a beat or
        two) used to STOP the loop on the first hit and the member got
        swept out of the view; now they retry with capped exponential
        backoff and only HEARTBEAT_GIVEUP consecutive failures stop the
        loop — visibly, on the `heartbeats_stopped` gauge."""
        from snappydata_tpu.observability.metrics import global_registry

        _register_hb_gauge()

        def giveup(e) -> bool:
            _log.error("member %s: %s; stopping heartbeats after %d "
                       "protocol retries", self.member_id, e,
                       self.HEARTBEAT_GIVEUP)
            _HB_STOPPED.add(self.member_id)
            global_registry().inc("member_heartbeats_stopped")
            return True

        def backoff_wait(fails: int) -> bool:
            """Capped-backoff sleep; True when the client was closed."""
            delay = min(interval_s * (2 ** max(0, fails - 1)),
                        self.HEARTBEAT_BACKOFF_MAX_S)
            _log.warning("member %s: transient heartbeat protocol "
                         "failure %d/%d; retrying in %.2fs",
                         self.member_id, fails, self.HEARTBEAT_GIVEUP,
                         delay)
            return self._stop.wait(delay)

        def loop():
            proto_fails = 0
            while not self._stop.wait(interval_s):
                try:
                    failpoints.hit("locator.heartbeat")
                    resp = self._request({"op": "heartbeat",
                                          "member_id": self.member_id})
                    if resp.get("rejoin"):
                        self.register()
                    self.last_view = resp.get("view", self.last_view)
                    proto_fails = 0
                    _HB_STOPPED.discard(self.member_id)
                except RuntimeError as e:
                    global_registry().inc("member_heartbeat_failures")
                    proto_fails += 1
                    if proto_fails >= self.HEARTBEAT_GIVEUP and giveup(e):
                        return
                    if backoff_wait(proto_fails):
                        return
                except (ConnectionError, OSError) as e:
                    global_registry().inc("member_heartbeat_failures")
                    _log.warning("member %s: heartbeat failed (%s); "
                                 "re-registering", self.member_id, e)
                    try:
                        self.register()
                        proto_fails = 0
                    except RuntimeError as e2:
                        proto_fails += 1
                        if proto_fails >= self.HEARTBEAT_GIVEUP \
                                and giveup(e2):
                            return
                        if backoff_wait(proto_fails):
                            return
                    except (ConnectionError, OSError):
                        pass   # locator still down: retry next tick

        self._hb = threading.Thread(target=loop, daemon=True)
        self._hb.start()

    def members(self) -> List[MemberInfo]:
        resp = self._request({"op": "members"})
        return [MemberInfo(**m) for m in resp["members"]]

    def try_lock(self, name: str, lease_s: float = 30.0) -> bool:
        resp = self._request({"op": "lock", "name": name,
                              "member_id": self.member_id,
                              "lease_s": lease_s})
        return bool(resp.get("acquired"))

    def unlock(self, name: str) -> None:
        self._request({"op": "unlock", "name": name,
                       "member_id": self.member_id})

    def close(self) -> None:
        self._stop.set()
        _HB_STOPPED.discard(self.member_id)  # deliberate shutdown ≠ alarm
        try:
            self._request({"op": "deregister", "member_id": self.member_id})
        except (ConnectionError, OSError):
            pass
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
