"""Static lock-order analysis.

Walks every module in the scanned tree and:

1. **Inventories locks** — every ``locks.named_lock/named_rlock/
   named_condition`` assignment (module global or ``self.attr``) maps an
   attribute/global to a stable lock NAME; raw ``threading.Lock()``
   constructions are themselves a finding (``unnamed-lock``) because an
   anonymous lock defeats both this pass and the runtime witness.

2. **Resolves acquisition sites** — ``with lock:`` items and
   ``.acquire()`` calls, through a lightweight type propagation
   (``self.x = C(...)``, parameter annotations, locals assigned from
   constructors / typed attributes / lock-returning helpers) with a
   unique-attribute fallback and a ``# locklint: lock=NAME`` escape
   hatch.

3. **Builds the inter-procedural held-while-acquiring graph** — per
   function: (lock, held-set) at each acquisition plus every call made
   under each held set; a fixed point propagates callee-acquired locks
   and callee-reachable blocking calls up through resolved calls (self
   methods, typed receivers, module/imported functions). Unresolvable
   calls are skipped: the pass is deliberately unsound-but-useful, and
   the runtime witness backstops it on the paths tests actually run.

4. **Reports** — edges not derivable from the committed manifest
   (``lock-order-undeclared``), cycles in the observed static graph
   (``lock-order-cycle``, the ABBA shape), blocking calls executed or
   reachable while a lock is held (``blocking-under-lock``: fsync /
   wal_sync / sleeps / socket & Flight calls / ``block_until_ready`` /
   thread joins / condition-or-event waits beyond the condition's own
   lock), and callbacks invoked under a lock (``callback-under-lock``,
   the PR 10 gauge-under-registry-lock shape: calling a value fetched
   from a container or parameter while holding the container's lock).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import (Finding, SourceFile, dotted, load_sources, module_name,
                     str_const, terminal_name)

NAMED_CTORS = {"named_lock", "named_rlock", "named_condition"}
RAW_CTORS = {"Lock", "RLock", "Condition"}

# blocking-call terminals: matched against the last component of the
# callee's dotted name; (terminal, extra-predicate description)
_BLOCKING_TERMINALS = {
    "sleep": "time.sleep",
    "fsync": "os.fsync",
    "wal_sync": "WAL fsync gate",
    "flush_wals": "cluster durability barrier",
    "block_until_ready": "device sync",
    "device_get": "device transfer",
    "urlopen": "HTTP round-trip",
    "sendall": "socket write",
    "recv": "socket read",
    "accept": "socket accept",
    "do_get": "Flight/gRPC call",
    "do_put": "Flight/gRPC call",
    "do_action": "Flight/gRPC call",
    "get_flight_info": "Flight/gRPC call",
}
_THREADISH_RE = re.compile(
    r"(thread|worker|flusher|poller|drainer|proc)", re.IGNORECASE)


class ClassInfo:
    def __init__(self, key: str, module: str, name: str):
        self.key = key
        self.module = module
        self.name = name
        self.node: Optional[ast.ClassDef] = None
        self.base_names: List[str] = []
        self.attr_locks: Dict[str, str] = {}
        self.attr_types: Dict[str, str] = {}     # attr -> class key
        self.methods: Dict[str, str] = {}        # name -> func key


class FuncInfo:
    def __init__(self, key: str, node: ast.AST, module: str,
                 class_key: Optional[str], src: SourceFile):
        self.key = key
        self.node = node
        self.module = module
        self.class_key = class_key
        self.src = src
        # analysis results
        self.direct_edges: List[Tuple[Tuple[str, ...], str, int]] = []
        self.acquired: Set[str] = set()
        self.calls: List[Tuple[str, Tuple[str, ...], int]] = []
        self.blocking: List[Tuple[str, int, bool]] = []  # (desc, line, held)
        self.callbacks: List[Tuple[Tuple[str, ...], int, str]] = []
        self.unresolved: List[Tuple[int, str]] = []
        # generator-based contextmanagers: locks held across the yield —
        # the caller's with-body runs under them
        self.yields_under: Set[str] = set()

    def reset_results(self) -> None:
        self.direct_edges = []
        self.acquired = set()
        self.calls = []
        self.blocking = []
        self.callbacks = []
        self.unresolved = []
        # fixed-point summaries
        self.reach_locks: Dict[str, Tuple[str, ...]] = {}   # lock -> chain
        self.reach_blocking: Dict[str, Tuple[str, ...]] = {}


class ModuleInfo:
    def __init__(self, modname: str, src: SourceFile):
        self.name = modname
        self.src = src
        self.import_mods: Dict[str, str] = {}        # alias -> dotted module
        self.import_names: Dict[str, Tuple[str, str]] = {}  # name->(mod,name)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, str] = {}          # name -> func key
        self.global_locks: Dict[str, str] = {}       # global var -> lock name
        self.global_types: Dict[str, str] = {}       # global var -> class key
        self.lock_returners: Dict[str, str] = {}     # func name -> lock name
        self.func_return_types: Dict[str, str] = {}  # func name -> class key


class Analysis:
    """Whole-tree analysis state + results."""

    def __init__(self, paths: Sequence[str]):
        self.sources = load_sources(list(paths))
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.findings: List[Finding] = []
        self.lock_names: Set[str] = set()
        # attr -> set of lock names (for the unique-attr fallback)
        self.attr_name_index: Dict[str, Set[str]] = {}
        # (held, acquired) -> (file, line, via)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    # ---------------- phase 1: module shells ----------------

    def build(self) -> None:
        for path, src in sorted(self.sources.items()):
            modname = module_name(path)
            mi = ModuleInfo(modname, src)
            self.modules[modname] = mi
            self._scan_imports(mi)
            self._scan_defs(mi)
        for mi in self.modules.values():
            self._scan_locks(mi)
        for mi in self.modules.values():
            self._scan_returners(mi)
        # two walker rounds: the first discovers which contextmanager
        # functions hold locks across their yield; the second re-walks
        # with that knowledge so callers' with-bodies count as held
        for fi in self.funcs.values():
            _FunctionWalker(self, fi).run()
        for fi in self.funcs.values():
            fi.reset_results()
        for fi in self.funcs.values():
            _FunctionWalker(self, fi).run()
        self._fixed_point()
        self._assemble_edges()

    def _scan_imports(self, mi: ModuleInfo) -> None:
        for node in ast.walk(mi.src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mi.import_mods[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = mi.name.split(".")
                    pkg = pkg[: len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module]
                                           if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = a.asname or a.name
                    mi.import_names[target] = (base, a.name)

    def _scan_defs(self, mi: ModuleInfo) -> None:
        def add_func(node, class_key, qual):
            key = "%s:%s" % (mi.name, qual)
            self.funcs[key] = FuncInfo(key, node, mi.name, class_key, mi.src)
            return key

        def walk_body(body, class_info: Optional[ClassInfo], prefix: str):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = prefix + node.name
                    key = add_func(
                        node, class_info.key if class_info else None, qual)
                    if class_info is not None and prefix.count(".") == 1:
                        class_info.methods[node.name] = key
                    elif class_info is None and prefix == "":
                        mi.functions[node.name] = key
                    # nested defs (thread bodies, closures)
                    walk_body(node.body, class_info, qual + ".")
                elif isinstance(node, ast.ClassDef) and prefix == "":
                    ck = "%s:%s" % (mi.name, node.name)
                    ci = ClassInfo(ck, mi.name, node.name)
                    ci.node = node
                    for b in node.bases:
                        d = dotted(b)
                        if d:
                            ci.base_names.append(d)
                    mi.classes[node.name] = ci
                    self.classes[ck] = ci
                    walk_body(node.body, ci, node.name + ".")

        walk_body(mi.src.tree.body, None, "")

    # ---------------- phase 2: lock + type inventory ----------------

    def _lock_ctor(self, value: ast.AST, mi: ModuleInfo,
                   owner_attrs: Optional[Dict[str, str]],
                   default_name: str, line: int) -> Optional[str]:
        """If `value` constructs a lock, return its name (registering
        findings for raw constructors)."""
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        term = terminal_name(fn)
        if term in NAMED_CTORS:
            name = str_const(value.args[0]) if value.args else None
            if name is None:
                self._finding("unnamed-lock", mi.src, line,
                              "named lock constructor needs a literal name")
                name = default_name
            if term == "named_condition" and len(value.args) > 1:
                # condition over an existing named lock: alias its name
                inner = dotted(value.args[1])
                if inner and owner_attrs is not None:
                    attr = inner.split(".")[-1]
                    if attr in owner_attrs:
                        name = owner_attrs[attr]
            return name
        if term in RAW_CTORS:
            d = dotted(fn) or term
            head = d.split(".")[0]
            if d == ("threading.%s" % term) or (
                    mi.import_mods.get(head) == "threading") or (
                    term in mi.import_names
                    and mi.import_names[term][0] == "threading"):
                self._finding(
                    "unnamed-lock", mi.src, line,
                    "raw threading.%s() — create it through "
                    "snappydata_tpu.utils.locks.named_* so the analyzer "
                    "and the runtime witness can name it" % term)
                return default_name
        return None

    def _scan_locks(self, mi: ModuleInfo) -> None:
        # module-level globals
        for node in mi.src.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                var = node.targets[0].id
                name = self._lock_ctor(node.value, mi, None,
                                       "%s.%s" % (mi.name, var), node.lineno)
                if name:
                    mi.global_locks[var] = name
                    self._register_lock(name, var)
                    continue
                ck = self._class_of_ctor(node.value, mi)
                if ck:
                    mi.global_types[var] = ck
        # class attributes + self.attr assignments in every method; two
        # passes so a named_condition(..., self._lock) alias resolves no
        # matter where the condition sits relative to the lock
        for ci in mi.classes.values():
            for conditions_pass in (False, True):
                for attr, value, line in self._class_attr_assigns(ci):
                    is_cond = (isinstance(value, ast.Call)
                               and terminal_name(value.func)
                               == "named_condition")
                    if is_cond != conditions_pass:
                        continue
                    name = self._lock_ctor(
                        value, mi, ci.attr_locks,
                        "%s.%s.%s" % (mi.name, ci.name, attr), line)
                    if name:
                        ci.attr_locks[attr] = name
                        self._register_lock(name, attr)
                    elif not conditions_pass:
                        ck = self._class_of_ctor(value, mi)
                        if ck:
                            ci.attr_types[attr] = ck

    def _class_attr_assigns(self, ci: ClassInfo):
        """(attr, value, line) for class-body assigns and `self.attr =`
        assigns in every method, in source order."""
        out = []
        if ci.node is not None:
            for node in ci.node.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    out.append((node.targets[0].id, node.value, node.lineno))
        for _mname, fkey in ci.methods.items():
            fi = self.funcs.get(fkey)
            if fi is None:
                continue
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                tgt = node.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    out.append((tgt.attr, node.value, node.lineno))
        out.sort(key=lambda t: t[2])
        return out

    def _class_of_ctor(self, value: ast.AST, mi: ModuleInfo) -> Optional[str]:
        if isinstance(value, ast.Call):
            d = dotted(value.func)
            if d:
                return self._resolve_class(d, mi)
        return None

    def _resolve_class(self, d: str, mi: ModuleInfo) -> Optional[str]:
        head, _, tail = d.partition(".")
        if not tail:
            if head in mi.classes:
                return mi.classes[head].key
            if head in mi.import_names:
                srcmod, srcname = mi.import_names[head]
                tgt = self._find_module(srcmod)
                if tgt and srcname in tgt.classes:
                    return tgt.classes[srcname].key
            return None
        if head in mi.import_mods:
            tgt = self._find_module(mi.import_mods[head])
            if tgt and tail in tgt.classes:
                return tgt.classes[tail].key
        return None

    def _find_module(self, dotted_name: str) -> Optional[ModuleInfo]:
        if dotted_name in self.modules:
            return self.modules[dotted_name]
        for name, mi in self.modules.items():
            if name.endswith("." + dotted_name) or dotted_name.endswith(
                    "." + name):
                return mi
        tail = dotted_name.split(".")[-1]
        for name, mi in self.modules.items():
            if name.split(".")[-1] == tail and (
                    dotted_name in name or name in dotted_name):
                return mi
        return None

    def _scan_returners(self, mi: ModuleInfo) -> None:
        """Module functions that just return a lock or a typed global —
        `clock_lock()` helpers, `global_registry()` accessors."""
        for fname, fkey in mi.functions.items():
            fi = self.funcs[fkey]
            node = fi.node
            rets = [n for n in ast.walk(node) if isinstance(n, ast.Return)
                    and n.value is not None]
            if len(rets) != 1:
                continue
            d = dotted(rets[0].value)
            if d and d in mi.global_locks:
                mi.lock_returners[fname] = mi.global_locks[d]
            elif d and d in mi.global_types:
                mi.func_return_types[fname] = mi.global_types[d]
            else:
                ck = self._class_of_ctor(rets[0].value, mi)
                if ck:
                    mi.func_return_types[fname] = ck

    def _register_lock(self, name: str, attr: str) -> None:
        self.lock_names.add(name)
        self.attr_name_index.setdefault(attr, set()).add(name)

    def _finding(self, rule: str, src: SourceFile, line: int,
                 message: str) -> None:
        if src.waived(line, rule):
            return
        self.findings.append(Finding(rule, src.path, line, message))

    # ---------------- phase 4: fixed point ----------------

    def _fixed_point(self) -> None:
        for fi in self.funcs.values():
            for lock in fi.acquired:
                fi.reach_locks.setdefault(lock, (fi.key,))
            for desc, line, _held in fi.blocking:
                fi.reach_blocking.setdefault(
                    desc, ("%s:%d" % (fi.key, line),))
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fi in self.funcs.values():
                for callee_key, _held, _line in fi.calls:
                    callee = self.funcs.get(callee_key)
                    if callee is None:
                        continue
                    for lock, chain in callee.reach_locks.items():
                        if lock not in fi.reach_locks:
                            fi.reach_locks[lock] = (fi.key,) + chain
                            changed = True
                    for desc, chain in callee.reach_blocking.items():
                        if desc not in fi.reach_blocking:
                            fi.reach_blocking[desc] = (fi.key,) + chain
                            changed = True

    def _assemble_edges(self) -> None:
        for fi in self.funcs.values():
            for held, lock, line in fi.direct_edges:
                for h in held:
                    if h != lock:
                        self._add_edge(h, lock, fi.src.path, line, "direct")
            for callee_key, held, line in fi.calls:
                if not held:
                    continue
                callee = self.funcs.get(callee_key)
                if callee is None:
                    continue
                for lock, chain in callee.reach_locks.items():
                    for h in held:
                        if h != lock:
                            self._add_edge(h, lock, fi.src.path, line,
                                           "via " + " -> ".join(chain))

    def _add_edge(self, held: str, lock: str, path: str, line: int,
                  via: str) -> None:
        key = (held, lock)
        if key not in self.edges:
            self.edges[key] = (path, line, via)

    # ---------------- phase 5: report ----------------

    def check(self, manifest) -> List[Finding]:
        out: List[Finding] = list(self.findings)
        # a waiver at the edge's recorded site removes it from the graph:
        # one annotation kills both the undeclared-edge and any cycle it
        # would close
        active = {}
        for key, (path, line, via) in self.edges.items():
            src = self.sources.get(path)
            if src and src.waived(line, "lock-order-undeclared"):
                continue
            active[key] = (path, line, via)
        for (held, lock), (path, line, via) in sorted(active.items()):
            if manifest is not None and not manifest.allows(held, lock):
                out.append(Finding(
                    "lock-order-undeclared", path, line,
                    "acquires '%s' while holding '%s' (%s) — edge not in "
                    "the declared hierarchy (lock_order.toml)"
                    % (lock, held, via)))
        out.extend(self._cycles(active))
        for fi in self.funcs.values():
            for line, msg in fi.unresolved:
                self._append(out, "unresolved-acquisition", fi.src, line, msg)
            for held, line, what in fi.callbacks:
                self._append(
                    out, "callback-under-lock", fi.src, line,
                    "invokes %s while holding %s — a callback that "
                    "touches the guarded structure self-deadlocks (the "
                    "gauge-under-registry-lock shape); call it outside "
                    "the lock or waive with the invariant"
                    % (what, "/".join(sorted(set(held)))))
            for desc, line, was_held in fi.blocking:
                if not was_held:
                    continue
                self._append(
                    out, "blocking-under-lock", fi.src, line,
                    "%s while holding a lock — blocks every sibling of "
                    "that lock for the call's full latency" % desc)
            for callee_key, held, line in fi.calls:
                if not held:
                    continue
                callee = self.funcs.get(callee_key)
                if callee is None:
                    continue
                for desc, chain in callee.reach_blocking.items():
                    self._append(
                        out, "blocking-under-lock", fi.src, line,
                        "%s reachable under lock %s (call chain %s)"
                        % (desc, "/".join(sorted(set(held))),
                           " -> ".join(chain)))
        return out

    def _append(self, out: List[Finding], rule: str, src: SourceFile,
                line: int, msg: str) -> None:
        if src.waived(line, rule):
            return
        out.append(Finding(rule, src.path, line, msg))

    def _cycles(self, edges) -> List[Finding]:
        adj: Dict[str, Set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        out: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for a, b in sorted(edges):
            # path b -> a closes a cycle through edge (a, b)
            path = self._path(adj, b, a)
            if path is None:
                continue
            cyc = [a, b] + path[1:-1]   # path ends at a; keep nodes unique
            k = min(tuple(cyc[i:] + cyc[:i]) for i in range(len(cyc)))
            if k in seen_cycles:
                continue
            seen_cycles.add(k)
            p, line, via = edges[(a, b)]
            sites = []
            for x, y in zip(cyc, cyc[1:] + [cyc[0]]):
                e = edges.get((x, y))
                if e:
                    sites.append("%s->%s at %s:%d" % (x, y, e[0], e[1]))
            out.append(Finding(
                "lock-order-cycle", p, line,
                "potential ABBA deadlock: cycle %s (%s)"
                % (" -> ".join(cyc + [cyc[0]]), "; ".join(sites))))
        return out

    @staticmethod
    def _path(adj, src, dst) -> Optional[List[str]]:
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # ---------------- shared resolution helpers ----------------

    def method_lookup(self, class_key: str, name: str,
                      _depth: int = 0) -> Optional[str]:
        ci = self.classes.get(class_key)
        if ci is None or _depth > 8:
            return None
        if name in ci.methods:
            return ci.methods[name]
        mi = self.modules.get(ci.module)
        for b in ci.base_names:
            bk = self._resolve_class(b, mi) if mi else None
            if bk:
                got = self.method_lookup(bk, name, _depth + 1)
                if got:
                    return got
        return None

    def attr_lock_lookup(self, class_key: str, attr: str,
                         _depth: int = 0) -> Optional[str]:
        ci = self.classes.get(class_key)
        if ci is None or _depth > 8:
            return None
        if attr in ci.attr_locks:
            return ci.attr_locks[attr]
        mi = self.modules.get(ci.module)
        for b in ci.base_names:
            bk = self._resolve_class(b, mi) if mi else None
            if bk:
                got = self.attr_lock_lookup(bk, attr, _depth + 1)
                if got:
                    return got
        return None

    def attr_type_lookup(self, class_key: str, attr: str) -> Optional[str]:
        ci = self.classes.get(class_key)
        if ci is None:
            return None
        if attr in ci.attr_types:
            return ci.attr_types[attr]
        return None


class _FunctionWalker:
    """Single-function pass: tracks the statically-held lock set through
    with-blocks and acquire/release pairs, records acquisitions, calls,
    blocking calls, and callback invocations."""

    def __init__(self, an: Analysis, fi: FuncInfo):
        self.an = an
        self.fi = fi
        self.mi = an.modules[fi.module]
        self.src = fi.src
        self.local_types: Dict[str, str] = {}
        self.local_lock_alias: Dict[str, str] = {}
        self.callable_locals: Set[str] = set()
        node = fi.node
        args = getattr(node, "args", None)
        if args is not None:
            allargs = list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs)
            for a in allargs:
                if a.arg in ("self", "cls"):
                    continue
                ck = self._annotation_class(a.annotation)
                if ck:
                    self.local_types[a.arg] = ck
            self.params = {a.arg for a in allargs
                           if a.arg not in ("self", "cls")}
        else:
            self.params = set()

    def _annotation_class(self, ann) -> Optional[str]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            d = ann.value.strip().strip('"')
        else:
            d = dotted(ann)
        if not d:
            return None
        d = d.replace("Optional[", "").replace("]", "").strip()
        return self.an._resolve_class(d, self.mi)

    def run(self) -> None:
        self.walk_block(self.fi.node.body, ())

    # -------- lock / type / callee resolution --------

    def resolve_type(self, expr: ast.AST, _depth: int = 0) -> Optional[str]:
        if _depth > 6:
            return None
        if isinstance(expr, ast.Name):
            if expr.id == "self" and self.fi.class_key:
                return self.fi.class_key
            if expr.id in self.local_types:
                return self.local_types[expr.id]
            if expr.id in self.mi.global_types:
                return self.mi.global_types[expr.id]
            return None
        if isinstance(expr, ast.Attribute):
            base = self.resolve_type(expr.value, _depth + 1)
            if base:
                return self.an.attr_type_lookup(base, expr.attr)
            return None
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            if d:
                ck = self.an._resolve_class(d, self.mi)
                if ck:
                    return ck
                rt = self._func_return_type(d)
                if rt:
                    return rt
            return None
        return None

    def _func_return_type(self, d: str) -> Optional[str]:
        head, _, tail = d.partition(".")
        if not tail:
            if head in self.mi.func_return_types:
                return self.mi.func_return_types[head]
            if head in self.mi.import_names:
                srcmod, srcname = self.mi.import_names[head]
                tgt = self.an._find_module(srcmod)
                if tgt and srcname in tgt.func_return_types:
                    return tgt.func_return_types[srcname]
            return None
        if head in self.mi.import_mods:
            tgt = self.an._find_module(self.mi.import_mods[head])
            if tgt and tail in tgt.func_return_types:
                return tgt.func_return_types[tail]
        return None

    def resolve_lock(self, expr: ast.AST, _depth: int = 0) -> Optional[str]:
        if _depth > 6:
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.local_lock_alias:
                return self.local_lock_alias[expr.id]
            if expr.id in self.mi.global_locks:
                return self.mi.global_locks[expr.id]
            if expr.id in self.mi.import_names:
                srcmod, srcname = self.mi.import_names[expr.id]
                tgt = self.an._find_module(srcmod)
                if tgt and srcname in tgt.global_locks:
                    return tgt.global_locks[srcname]
            return None
        if isinstance(expr, ast.Attribute):
            recv = expr.value
            attr = expr.attr
            if isinstance(recv, ast.Name) and recv.id in self.mi.import_mods:
                tgt = self.an._find_module(self.mi.import_mods[recv.id])
                if tgt and attr in tgt.global_locks:
                    return tgt.global_locks[attr]
            if isinstance(recv, ast.Name):
                # class attribute access: Mesh._lock / cls._lock
                ck = self.an._resolve_class(recv.id, self.mi)
                if ck:
                    got = self.an.attr_lock_lookup(ck, attr)
                    if got:
                        return got
                if recv.id == "cls" and self.fi.class_key:
                    got = self.an.attr_lock_lookup(self.fi.class_key, attr)
                    if got:
                        return got
            ck = self.resolve_type(recv, _depth + 1)
            if ck:
                got = self.an.attr_lock_lookup(ck, attr)
                if got:
                    return got
            # unique terminal attribute fallback
            cands = self.an.attr_name_index.get(attr, set())
            if len(cands) == 1:
                return next(iter(cands))
            return None
        if isinstance(expr, ast.Call):
            d = dotted(expr.func)
            if d:
                head, _, tail = d.partition(".")
                if not tail and head in self.mi.lock_returners:
                    return self.mi.lock_returners[head]
                if not tail and head in self.mi.import_names:
                    srcmod, srcname = self.mi.import_names[head]
                    tgt = self.an._find_module(srcmod)
                    if tgt and srcname in tgt.lock_returners:
                        return tgt.lock_returners[srcname]
                if tail and head in self.mi.import_mods:
                    tgt = self.an._find_module(self.mi.import_mods[head])
                    if tgt and tail in tgt.lock_returners:
                        return tgt.lock_returners[tail]
            return None
        if isinstance(expr, ast.IfExp):
            return self.resolve_lock(expr.body, _depth + 1) or \
                self.resolve_lock(expr.orelse, _depth + 1)
        return None

    def resolve_callee(self, func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            # locally-defined nested function (thread bodies, helpers)
            local_key = "%s.%s" % (self.fi.key, func.id)
            if local_key in self.an.funcs:
                return local_key
            if func.id in self.mi.functions:
                return self.mi.functions[func.id]
            if func.id in self.mi.import_names:
                srcmod, srcname = self.mi.import_names[func.id]
                tgt = self.an._find_module(srcmod)
                if tgt and srcname in tgt.functions:
                    return tgt.functions[srcname]
                # class constructor call -> its __init__
                if tgt and srcname in tgt.classes:
                    return self.an.method_lookup(
                        tgt.classes[srcname].key, "__init__")
            if func.id in self.mi.classes:
                return self.an.method_lookup(
                    self.mi.classes[func.id].key, "__init__")
            return None
        if isinstance(func, ast.Attribute):
            recv, meth = func.value, func.attr
            if isinstance(recv, ast.Name) and recv.id in self.mi.import_mods:
                tgt = self.an._find_module(self.mi.import_mods[recv.id])
                if tgt:
                    if meth in tgt.functions:
                        return tgt.functions[meth]
                    if meth in tgt.classes:
                        return self.an.method_lookup(
                            tgt.classes[meth].key, "__init__")
            ck = self.resolve_type(recv)
            if ck:
                return self.an.method_lookup(ck, meth)
            return None
        return None

    # -------- statement walking --------

    def walk_block(self, stmts: Sequence[ast.stmt],
                   held: Tuple[str, ...]) -> None:
        i = 0
        n = len(stmts)
        while i < n:
            s = stmts[i]
            acq = self._acquire_stmt(s)
            if acq is not None:
                expr_dump, lock = acq
                self._record_acquire(lock, held, s.lineno)
                end = self._find_release(stmts, i + 1, expr_dump)
                self.walk_block(stmts[i + 1:end], held + (lock,))
                i = end
                continue
            self.visit_stmt(s, held)
            i += 1

    def _acquire_stmt(self, s: ast.stmt):
        """`lock.acquire()` (or `ok = lock.acquire(...)`) as its own
        statement → (receiver-dump, lockname)."""
        call = None
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
        elif isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            call = s.value
        if call is None or not isinstance(call.func, ast.Attribute) \
                or call.func.attr != "acquire":
            return None
        lock = self.resolve_lock(call.func.value) \
            or self.src.lock_hint(s.lineno)
        if lock is None:
            term = terminal_name(call.func.value)
            if term and re.search(r"lock|cond|mutex|sem", term, re.I):
                self.fi.unresolved.append((
                    s.lineno,
                    "cannot resolve the lock behind %r.acquire() — add a "
                    "`# locklint: lock=NAME` hint" % (dotted(call.func.value)
                                                      or term)))
            return None
        return (ast.dump(call.func.value), lock)

    def _find_release(self, stmts, start: int, expr_dump: str) -> int:
        for j in range(start, len(stmts)):
            for node in ast.walk(stmts[j]):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "release" \
                        and ast.dump(node.func.value) == expr_dump:
                    return j + 1
        return len(stmts)

    def _record_acquire(self, lock: str, held: Tuple[str, ...],
                        line: int) -> None:
        self.fi.acquired.add(lock)
        if held:
            self.fi.direct_edges.append((held, lock, line))

    def visit_stmt(self, s: ast.stmt, held: Tuple[str, ...]) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return   # nested defs are separate FuncInfos
        if held and isinstance(s, ast.Expr) \
                and isinstance(s.value, (ast.Yield, ast.YieldFrom)):
            self.fi.yields_under.update(held)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            cur = held
            for item in s.items:
                self._scan_expr(item.context_expr, cur, skip_with_call=True)
                lock = self.resolve_lock(item.context_expr) \
                    or self.src.lock_hint(s.lineno)
                if lock is not None:
                    self._record_acquire(lock, cur, s.lineno)
                    cur = cur + (lock,)
                    continue
                # contextmanager holding lock(s) across its yield: the
                # with-body runs under them
                if isinstance(item.context_expr, ast.Call):
                    callee = self.resolve_callee(item.context_expr.func)
                    cfi = self.an.funcs.get(callee) if callee else None
                    if cfi is not None and cfi.yields_under:
                        for lk in sorted(cfi.yields_under):
                            self._record_acquire(lk, cur, s.lineno)
                            cur = cur + (lk,)
                        continue
                self._maybe_unresolved_with(item.context_expr, s.lineno)
            self.walk_block(s.body, cur)
            return
        if isinstance(s, ast.Assign):
            self._track_assign(s)
        elif isinstance(s, ast.AnnAssign) and s.value is not None \
                and isinstance(s.target, ast.Name):
            self._track_assign_target(s.target.id, s.value, s.annotation)
        # scan expressions in this statement (not nested blocks)
        for field in ast.iter_fields(s):
            val = field[1]
            if isinstance(val, ast.expr):
                self._scan_expr(val, held)
            elif isinstance(val, list):
                for v in val:
                    if isinstance(v, ast.expr):
                        self._scan_expr(v, held)
        if isinstance(s, ast.For):
            self._track_for(s)   # BEFORE the body: `for k, fn in ...`
        # nested blocks
        for attr in ("body", "orelse", "finalbody"):
            body = getattr(s, attr, None)
            if body:
                self.walk_block(body, held)
        for h in getattr(s, "handlers", []) or []:
            self.walk_block(h.body, held)

    def _maybe_unresolved_with(self, expr: ast.AST, line: int) -> None:
        term = terminal_name(expr)
        if term and re.search(r"(^|_)(lock|cond|mutex)", term, re.I):
            if self.src.waived(line, "unresolved-acquisition"):
                return
            self.fi.unresolved.append((
                line,
                "cannot resolve lock %r in with-statement — add a "
                "`# locklint: lock=NAME` hint or waive" % (dotted(expr)
                                                           or term)))

    def _track_assign(self, s: ast.Assign) -> None:
        if len(s.targets) != 1:
            return
        tgt = s.targets[0]
        if isinstance(tgt, ast.Name):
            self._track_assign_target(tgt.id, s.value, None)
        elif isinstance(tgt, ast.Tuple):
            # tuple unpack from .items()/zip: targets become callables
            for el in tgt.elts:
                if isinstance(el, ast.Name):
                    self.callable_locals.add(el.id)

    def _track_assign_target(self, name: str, value: ast.AST,
                             _ann) -> None:
        lock = self.resolve_lock(value)
        if lock is not None:
            self.local_lock_alias[name] = lock
            return
        ck = self.resolve_type(value)
        if ck:
            self.local_types[name] = ck
            return
        if isinstance(value, ast.Subscript):
            self.callable_locals.add(name)

    def _track_for(self, s: ast.For) -> None:
        tgt = s.target
        names = []
        if isinstance(tgt, ast.Name):
            names = [tgt.id]
        elif isinstance(tgt, ast.Tuple):
            names = [e.id for e in tgt.elts if isinstance(e, ast.Name)]
        for nm in names:
            self.callable_locals.add(nm)

    # -------- expression scanning --------

    def _scan_expr(self, expr: ast.AST, held: Tuple[str, ...],
                   skip_with_call: bool = False) -> None:
        # zero-arg calls compared with `is`/`is None` are weakref
        # liveness probes (`entry["plan"]() is not plan`), not callbacks
        probes = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Compare):
                for sub in [node.left] + list(node.comparators):
                    if isinstance(sub, ast.Call) and not sub.args \
                            and not sub.keywords:
                        probes.add(id(sub))
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Compare):
                        for c in [sub.left] + list(sub.comparators):
                            if isinstance(c, ast.Call) and not c.args \
                                    and not c.keywords:
                                probes.add(id(c))
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._handle_call(node, held, skip_top=skip_with_call
                              and node is expr,
                              is_probe=id(node) in probes)

    def _handle_call(self, call: ast.Call, held: Tuple[str, ...],
                     skip_top: bool = False, is_probe: bool = False) -> None:
        func = call.func
        term = terminal_name(func)
        if term in ("acquire", "release") and isinstance(
                func, ast.Attribute) and self.resolve_lock(
                func.value) is not None:
            return      # handled structurally
        line = call.lineno
        # blocking calls — a waiver at the SOURCE line suppresses the
        # direct finding AND stops propagation up the call chains (the
        # invariant is the callee's, not every caller's). Recorded even
        # when nothing is held here: a caller may hold a lock across us.
        if not skip_top:
            desc = self._blocking_desc(func, term, held)
            if desc and not self.src.waived(line, "blocking-under-lock"):
                self.fi.blocking.append((desc, line, bool(held)))
        # callback-under-lock: calling a value, not a known function
        if held and not is_probe and self._is_callback_call(func) \
                and not self.src.waived(line, "callback-under-lock"):
            self.fi.callbacks.append(
                (held, line, "callable value %r" % (dotted(func)
                                                    or "<subscript>")))
        # call graph
        callee = self.resolve_callee(func)
        if callee is not None:
            self.fi.calls.append((callee, held, line))

    def _blocking_desc(self, func, term, held) -> Optional[str]:
        if term is None:
            return None
        if term == "wait" and isinstance(func, ast.Attribute):
            own = self.resolve_lock(func.value)
            others = [h for h in held if h != own]
            if own is not None and others:
                return ("condition wait on '%s' under other lock(s) %s — "
                        "wait releases only its own lock"
                        % (own, "/".join(others)))
            if own is None:
                d = dotted(func.value) or ""
                if re.search(r"(event|ev|done|ready|stop|barrier|fut)",
                             d.split(".")[-1], re.I):
                    return "event/future wait (%s.wait)" % d
            return None
        if term == "join" and isinstance(func, ast.Attribute):
            d = dotted(func.value) or ""
            ck = self.resolve_type(func.value)
            tailid = d.split(".")[-1]
            if _THREADISH_RE.search(tailid) or tailid in ("t", "th") or (
                    ck or "").endswith(":Thread"):
                return "thread join (%s.join)" % d
            return None
        if term in _BLOCKING_TERMINALS:
            d = dotted(func) or term
            if term == "sleep":
                head = d.split(".")[0]
                if head not in ("time",) and d != "sleep":
                    return None
            if term == "recv":
                # only socket-ish receivers
                dd = (dotted(func.value) or "") if isinstance(
                    func, ast.Attribute) else ""
                if not re.search(r"sock|conn|chan", dd, re.I):
                    return None
            return "%s (%s)" % (_BLOCKING_TERMINALS[term], d)
        return None

    def _is_callback_call(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Subscript):
            return True
        if isinstance(func, ast.Name):
            nm = func.id
            if nm in self.callable_locals:
                return True
            if nm in self.params and nm not in self.local_types \
                    and re.search(r"(fn|func|callback|cb|hook)$", nm, re.I):
                return True
        return False


def analyze(paths: Sequence[str]) -> Analysis:
    an = Analysis(paths)
    an.build()
    return an
