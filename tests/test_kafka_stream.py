"""Kafka source: exactly-once offset-range protocol, consumer lag,
SIGKILL durability, throughput floor (ref: DirectKafkaStreamSource.scala:
29-40 direct offset-range consumption; SnappySinkCallback.scala:196-216
exactly-once sink; BASELINE.md north-star 1M events/s Kafka→table)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.streaming.kafka import (InProcessBroker, KafkaSource,
                                            OFFSETS_TABLE, register_broker)
from snappydata_tpu.streaming.query import StreamingQuery


def _consume_all(q):
    return q.process_available()


def _mk(table="kt", conflation=False, partitions=4):
    s = SnappySession(catalog=Catalog())
    s.sql(f"CREATE TABLE {table} (id BIGINT PRIMARY KEY, v DOUBLE) "
          f"USING row")
    broker = InProcessBroker(num_partitions=partitions)
    src = KafkaSource(s, "q1", broker, "events", ["id", "v"],
                      max_records_per_batch=1000)
    q = StreamingQuery(s, "q1", src, table)
    return s, broker, src, q


def test_basic_consumption_and_offsets():
    s, broker, src, q = _mk()
    broker.produce("events", [{"id": i, "v": float(i)} for i in range(2500)])
    _consume_all(q)
    assert s.sql("SELECT count(*) FROM kt").rows()[0][0] == 2500
    assert s.sql("SELECT sum(id) FROM kt").rows()[0][0] == \
        sum(range(2500))
    # lag drains to zero, then grows with new production
    assert src.lag() == 0
    broker.produce("events", [{"id": 9000 + i, "v": 0.0}
                              for i in range(10)])
    assert src.lag() == 10
    assert q.progress()["consumer_lag"] == 10
    s.stop()


def test_replay_same_ranges_after_crash_before_apply():
    """Crash point A: ranges logged, sink never applied. The restarted
    query must re-consume EXACTLY the logged ranges (no loss, no dup)."""
    s, broker, src, q = _mk()
    broker.produce("events", [{"id": i, "v": 1.0} for i in range(100)])
    batch_id = 0
    got = src.next_batch(batch_id)       # logs ranges durably
    assert got is not None
    # "crash": nothing applied. A fresh source over the same session
    # re-reads the log and returns the identical batch.
    src2 = KafkaSource(s, "q1", broker, "events", ["id", "v"],
                       max_records_per_batch=1000)
    # concurrent production between crash and restart must NOT leak into
    # the replayed batch
    broker.produce("events", [{"id": 500 + i, "v": 2.0}
                              for i in range(50)])
    got2 = src2.next_batch(batch_id)
    assert sorted(got2[0]["id"].tolist()) == sorted(got[0]["id"].tolist())
    q2 = StreamingQuery(s, "q1", src2, "kt")
    _consume_all(q2)
    assert s.sql("SELECT count(*) FROM kt").rows()[0][0] == 150
    s.stop()


def test_duplicate_batch_not_double_applied():
    """Crash point B: batch applied + state recorded, then the same batch
    id replays — the sink's exactly-once check drops it."""
    s, broker, src, q = _mk()
    broker.produce("events", [{"id": i, "v": 1.0} for i in range(40)])
    _consume_all(q)
    before = s.sql("SELECT count(*), sum(v) FROM kt").rows()[0]
    # replay an OLD batch id (ranges re-logged — equivalent to dying
    # before prune): strictly-older batches are dropped outright
    last = q.sink.last_batch_id()
    src._log_ranges(0, {p: [0, 10] for p in range(4)})
    cols, _ = src.next_batch(0)
    if 0 < last:
        assert q.sink.process_batch(0, cols) is False  # dropped
    # replay the LAST batch id: applied again as idempotent puts — the
    # keyed table state must not change (possible-duplicate contract)
    src._log_ranges(last, {p: [0, 10] for p in range(4)})
    cols2, _ = src.next_batch(last)
    q.sink.process_batch(last, cols2)
    after = s.sql("SELECT count(*), sum(v) FROM kt").rows()[0]
    assert after == before
    s.stop()


def test_offset_log_pruned_after_apply():
    s, broker, src, q = _mk()
    broker.produce("events", [{"id": i, "v": 1.0} for i in range(5000)])
    _consume_all(q)
    rows = s.sql(f"SELECT count(*) FROM {OFFSETS_TABLE} "
                 f"WHERE query_id = 'q1'").rows()[0][0]
    assert rows <= 1   # only the latest batch's ranges may remain
    s.stop()


def test_kafka_stream_ddl():
    s = SnappySession(catalog=Catalog())
    broker = InProcessBroker(num_partitions=2)
    register_broker("t1", broker)
    s.sql("CREATE STREAM TABLE clicks (id BIGINT, page STRING) "
          "USING kafka_stream OPTIONS (topic 'clicks', "
          "brokers 'inproc://t1', key_columns 'id', interval '0.01')")
    broker.produce("clicks", [{"id": i, "page": f"p{i % 3}"}
                              for i in range(300)])
    deadline = time.time() + 10
    while time.time() < deadline:
        if s.sql("SELECT count(*) FROM clicks").rows()[0][0] == 300:
            break
        time.sleep(0.05)
    assert s.sql("SELECT count(*) FROM clicks").rows()[0][0] == 300
    prog = [p for p in s.streaming_queries()
            if p["name"] == "stream_clicks"][0]
    assert prog["topic"] == "clicks"
    assert prog["consumer_lag"] == 0
    s.sql("DROP TABLE clicks")
    s.stop()


def test_throughput_floor():
    """Not the benchmark (bench.py measures the real number) — a floor
    that catches pathological slowness in the ingest path."""
    s, broker, src, q = _mk(partitions=8)
    n = 100_000
    src.max_records = 50_000
    broker.produce("events", [{"id": i, "v": 1.0} for i in range(n)])
    t0 = time.time()
    _consume_all(q)
    dt = time.time() - t0
    assert s.sql("SELECT count(*) FROM kt").rows()[0][0] == n
    assert n / dt > 5000, f"{n / dt:.0f} events/s"
    s.stop()


def test_kill9_exactly_once_across_process_death(tmp_path):
    """Consumer process is SIGKILLed mid-stream; the restarted consumer
    must land EVERY produced record exactly once (durable FileBroker +
    offset log + exactly-once sink)."""
    d = str(tmp_path / "store")
    bdir = str(tmp_path / "broker")
    from snappydata_tpu.streaming.kafka import FileBroker

    producer = FileBroker(bdir, num_partitions=4)
    total = 30_000
    chunk = 1000
    produced = 0
    code = f"""
import sys, time
import jax; jax.config.update("jax_platforms", "cpu")
from snappydata_tpu import SnappySession
s = SnappySession(data_dir={d!r})
s.sql("CREATE STREAM TABLE IF NOT EXISTS kt (id BIGINT, v DOUBLE) "
      "USING kafka_stream "
      "OPTIONS (topic 'events', brokers 'file://{bdir}', "
      "key_columns 'id', interval '0.01', maxRecordsPerBatch '2000')")
while True:
    n = s.sql("SELECT count(*) FROM kt").rows()[0][0]
    print(f"landed {{n}}", flush=True)
    time.sleep(0.1)
"""
    env = {**os.environ, "PYTHONPATH": "/root/.axon_site:/root/repo"}

    def spawn():
        return subprocess.Popen([sys.executable, "-u", "-c", code],
                                stdout=subprocess.PIPE, text=True, env=env)

    proc = spawn()
    landed = 0
    deadline = time.time() + 90
    while time.time() < deadline and produced < total:
        producer.produce("events",
                         [{"id": produced + i, "v": 1.0}
                          for i in range(chunk)])
        produced += chunk
        line = proc.stdout.readline()
        if line.startswith("landed "):
            landed = int(line.split()[1])
            if landed >= total // 3 and produced >= total // 2:
                break
    assert landed > 0, "consumer never made progress"
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    while produced < total:      # finish producing while consumer is dead
        producer.produce("events",
                         [{"id": produced + i, "v": 1.0}
                          for i in range(chunk)])
        produced += chunk

    proc = spawn()
    deadline = time.time() + 120
    final = 0
    while time.time() < deadline:
        line = proc.stdout.readline()
        if line.startswith("landed "):
            final = int(line.split()[1])
            if final >= total:
                break
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    assert final == total, f"{final} != {total}"

    # independent verification: every id exactly once
    s2 = SnappySession(data_dir=d)
    cnt, dcnt, ssum = s2.sql(
        "SELECT count(*), count(DISTINCT id), sum(v) FROM kt").rows()[0]
    assert cnt == total and dcnt == total
    assert ssum == pytest.approx(float(total))
    s2.disk_store.close()
