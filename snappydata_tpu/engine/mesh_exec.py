"""Mesh-sharded execution of compile-once partial-aggregate programs.

The read path's mesh dimension (ROADMAP item 1; PAPER.md L0's
partitioned regions + bucket placement): with a `jax.sharding` mesh
active, a tilable aggregate shape — single-relation scans (Q1/Q6) and
probe-leftmost join trees (Q3C) — runs its PARTIAL program (the PR 4
decomposition the tiled scan already compiles once) per-shard under
`shard_map`: every device scans only its batch slice of the
still-ENCODED plates, computes the group index in the shared [G] space
(dictionary codes are table-global, so per-shard gidx needs no
coordination), reduces its per-family [G] partials locally, and the
partials merge IN-TRACE with `psum`/`pmin`/`pmax` over the mesh axis —
the reference's partial aggregation + CollectAggregateExec merge
(SnappyStrategies.scala:347) expressed as collectives.

Joins pick a distribution strategy per bind, counted like the join
engine's fallback reasons:

* **broadcast-build** — the build side's plates + sorted artifact are
  replicated to every device (one explicit placement, cached per bind
  identity) and the probe stays batch-sharded: each shard probes the
  full build locally (ref: replicated-table HashJoinExec build
  broadcast, joins/HashJoinExec.scala:63).
* **shuffle-on-key** — both sides are exchanged BUCKET-WISE on the join
  key: the encoded int64 key domain (shared by both sides — string
  codes translate first) hashes through parallel/hashing's murmur3 into
  `num_devices` buckets, and each side's rows re-lay out so device d
  holds exactly bucket d of both sides.  Matching keys are then
  collocated, the per-shard trace sorts its LOCAL build slice in-trace
  (the `shuf_si` static specialization in _emit_join), and no probe or
  build row crosses a device during execution.  The exchange itself is
  one bucketed gather dispatched with sharded output — and it is
  CACHED per (bind identity, mesh, params), so repeated executions of
  an unchanged table re-exchange nothing.

Everything this lane cannot express falls back to plain GSPMD jit over
the sharded bind (still distributed, still value-correct), counted
`mesh_fallback_<reason>`.
"""

from __future__ import annotations

import collections
import contextvars
import functools
import weakref
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from snappydata_tpu.utils import locks

try:  # jax >= 0.4.35 re-exports; keep the experimental path for older
    from jax.experimental.shard_map import shard_map
except ImportError:  # pragma: no cover - newer jax moved it
    from jax import shard_map

from jax.sharding import PartitionSpec as P

# -- shuffle specialization flag ------------------------------------------
# Read by _emit_join's shuffle static provider and _aux_artifact during a
# bind this module drives; a contextvar so concurrent sessions on other
# threads bind unaffected.

_shuffle_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "mesh_shuffle", default=False)
# set to the mesh size while THIS module drives a bind (both
# strategies): _emit_join's mode_provider divides the join-expansion
# bucket by it — each shard expands only its probe slice, so expansion
# memory/work shrinks with the mesh instead of replicating the global
# output axis on every device
_bind_devices: contextvars.ContextVar = contextvars.ContextVar(
    "mesh_bind_devices", default=0)

_cache_lock = locks.named_lock("engine.mesh_exec")


def shuffle_active() -> bool:
    return bool(_shuffle_ctx.get())


def bind_devices() -> int:
    """Mesh size of the bind in flight on this thread (0 = not a mesh
    lane bind)."""
    return int(_bind_devices.get())


def _reg():
    from snappydata_tpu.observability.metrics import global_registry

    return global_registry()


# -- strategy selection ----------------------------------------------------

def choose_join_strategy(compiled, build_bytes: int,
                         probe_data) -> Tuple[str, Optional[str]]:
    """('broadcast'|'shuffle', decline_reason_or_None).

    The decline reason says why AUTO (or a forced 'shuffle') could not
    shuffle and fell back to broadcast — counted
    mesh_join_shuffle_fallback_<reason> by the caller, mirroring the
    join engine's itemized host-fallback reasons."""
    from snappydata_tpu import config

    props = config.global_properties()
    knob = str(props.get("mesh_join_strategy", "auto") or "auto").lower()
    if not compiled.join_meta:
        return "broadcast", None
    if knob == "broadcast":
        return "broadcast", None
    reason = _shuffle_ineligible(compiled, probe_data)
    if knob == "shuffle":
        return ("broadcast", reason) if reason else ("shuffle", None)
    # auto: broadcast small builds (replication is one placement and the
    # probe-side trace keeps the cached-artifact fast path); shuffle
    # once the replicated build would dominate per-device HBM
    limit = int(props.get("mesh_broadcast_build_bytes", 64 << 20) or 0)
    if limit and build_bytes > limit:
        return ("broadcast", reason) if reason else ("shuffle", None)
    return "broadcast", None


def _shuffle_ineligible(compiled, probe_data) -> Optional[str]:
    if len(compiled.join_meta) != 1:
        return "multi_join"
    meta = compiled.join_meta[0]
    if not meta["artifact_mode"] or meta["shuf_si"] is None:
        return "derived_build"
    if meta["probe_rel"] is None or meta["probe_ords"] is None:
        return "derived_probe"
    if meta["probe_rel"].info.data is not probe_data:
        return "probe_mismatch"
    if meta["how"] not in ("inner", "left", "semi", "anti"):
        return "outer_extension"
    return None


# -- bind-side helpers -----------------------------------------------------

def _array_layout(compiled) -> List[Tuple[object, int, int]]:
    """[(relation, first_index, valid_index)] into the flat `arrays`
    list a _bind returns — the one layout contract this module and
    make_ctx both derive from compiled.relations."""
    out = []
    pos = 0
    for r in compiled.relations:
        out.append((r, pos, pos + len(r.used)))
        pos += len(r.used) + 1
    return out


def _encoded_keys(meta, side: str, arrays, layout) -> Tuple:
    """(flat int64 encoded keys ON DEVICE, flat valid) for one join
    side of the CURRENT bind — the exact key domain the trace compares
    in (string codes translated to the build's code space, f64 pairs
    cast), so host-side bucket placement and in-trace matching agree
    bit-for-bit."""
    from snappydata_tpu.ops import join as _dj

    rel = meta["probe_rel"] if side == "probe" else meta["build_rel"]
    ords = meta["probe_ords"] if side == "probe" else meta["build_ords"]
    entry = next(e for e in layout if e[0] is rel)
    _r, base, vpos = entry
    pairs = []
    anynull = None
    for pi, (ci, spec) in enumerate(zip(ords, meta["enc_spec"])):
        apos = base + rel.used.index(ci)
        v, nl = arrays[apos]
        if isinstance(v, tuple):
            raise _Ineligible("complex_plate")
        v = v.reshape(-1)
        nl = nl.reshape(-1) if nl is not None else None
        if side == "probe":
            getter = meta["trans_getters"].get(pi)
            if getter is not None:
                trans = jnp.asarray(getter())
                v = trans[jnp.clip(v, 0, trans.shape[0] - 1)]
        if spec == "f64":
            v = v.astype(jnp.float64)
        pairs.append((v, nl))
        if nl is not None:
            anynull = nl if anynull is None else (anynull | nl)
    valid_flat = arrays[vpos].reshape(-1)
    if side == "probe":
        keys = _dj.encode_probe_keys(pairs, anynull)
    else:
        keys = _dj.encode_build_keys(pairs, valid_flat, anynull)
    return keys, valid_flat


class _Ineligible(Exception):
    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


def _bucket_layout(keys: np.ndarray, valid: np.ndarray, cap: int,
                   nd: int, old_batches: int):
    """Bucket-wise exchange plan for one side: rows hash into `nd`
    buckets over the encoded key domain (Spark-compatible murmur3 —
    parallel/hashing), bucket d's rows pack into device d's batch
    slice.  Returns (perm [B_new*cap] source flat indices, live mask,
    B_new, moved_rows)."""
    from snappydata_tpu.parallel.hashing import bucket_of_np
    from snappydata_tpu.parallel.mesh import _ladder

    live_idx = np.flatnonzero(valid)
    buckets = bucket_of_np(keys[live_idx].astype(np.int64), nd)
    per_dev = [live_idx[buckets == d] for d in range(nd)]
    max_rows = max((len(p) for p in per_dev), default=0)
    s_batches = _ladder(max(1, -(-max_rows // cap)))
    b_new = nd * s_batches
    perm = np.zeros(b_new * cap, dtype=np.int64)
    live = np.zeros(b_new * cap, dtype=bool)
    moved = 0
    for d, rows in enumerate(per_dev):
        base = d * s_batches * cap
        perm[base:base + len(rows)] = rows
        live[base:base + len(rows)] = True
        # a row "moves" when its source device block differs from its
        # bucket's owner — the exchange-bytes evidence
        src_dev = (rows // cap) * nd // max(1, old_batches)
        moved += int(np.count_nonzero(src_dev != d))
    return perm, live, b_new, moved


def _exchange_relation(arrays, layout, rel, perm, live, b_new, cap, ctx):
    """Re-lay one relation's bound arrays bucket-wise: a single gather
    per leaf dispatched with SHARDED output (device d receives exactly
    its bucket's rows — the all-to-all, done by XLA's resharding of the
    gather result).  Returns ({array_index: new_leaf}, exchanged_bytes)."""
    entry = next(e for e in layout if e[0] is rel)
    _r, base, vpos = entry
    perm_d = jnp.asarray(perm)
    live_d = jnp.asarray(live.reshape(b_new, cap))

    def shard2d(x):
        return jax.device_put(x, ctx.sharding_for(x))

    gather = jax.jit(
        lambda flat: flat.reshape(-1)[perm_d].reshape(b_new, cap),
        out_shardings=ctx.batch_sharding)
    replaced: Dict[int, object] = {}
    nbytes = 0
    for i in range(base, vpos):
        v, nl = arrays[i]
        if isinstance(v, tuple):
            raise _Ineligible("complex_plate")
        v2 = gather(v)
        nl2 = gather(nl) if nl is not None else None
        nbytes += int(v2.nbytes) + (int(nl2.nbytes) if nl2 is not None
                                    else 0)
        replaced[i] = (v2, nl2)
    valid2 = gather(arrays[vpos]) & shard2d(live_d)
    nbytes += int(valid2.nbytes)
    replaced[vpos] = valid2
    return replaced, nbytes


def _replicate_relation(arrays, layout, rel, ctx):
    """Explicitly place one build relation's bound arrays REPLICATED
    (the broadcast): one device_put per leaf, so repeated executions
    pay no per-dispatch all-gather.  Returns ({index: leaf}, bytes)."""
    entry = next(e for e in layout if e[0] is rel)
    _r, base, vpos = entry

    def rep(x):
        return jax.device_put(x, ctx.replicated)

    replaced: Dict[int, object] = {}
    nbytes = 0
    for i in range(base, vpos + 1):
        a = arrays[i]
        if i == vpos:
            replaced[i] = rep(a)
            nbytes += int(a.nbytes)
            continue
        v, nl = a
        if isinstance(v, tuple):
            parts = tuple(rep(x) for x in v)
            v2 = type(v)(*parts) if hasattr(v, "_fields") else parts
            nbytes += sum(int(x.nbytes) for x in v)
        else:
            v2 = rep(v)
            nbytes += int(v.nbytes)
        nl2 = rep(nl) if nl is not None else None
        nbytes += int(nl.nbytes) if nl is not None else 0
        replaced[i] = (v2, nl2)
    return replaced, nbytes


# -- the lane --------------------------------------------------------------

def run_partial(compiled, params: Tuple, probe_data, ctx,
                build_bytes: int = 0):
    """Bind + shard_map-execute a partial-raw compiled plan over the
    active mesh; returns HOST outs (mask, pairs, overflow) ready for
    compiled._assemble, or None when this lane must decline (caller
    falls back to GSPMD, counted by reason there)."""
    from snappydata_tpu.engine.exprs import CompileError
    from snappydata_tpu.observability import tracing

    reg = _reg()
    strategy, decline = ("scan", None) if not compiled.join_meta else \
        choose_join_strategy(compiled, build_bytes, probe_data)
    if decline:
        reg.inc("mesh_join_shuffle_fallback_" + decline)

    def _bind_with(strat):
        tok = _shuffle_ctx.set(strat == "shuffle")
        tok_d = _bind_devices.set(ctx.num_devices)
        try:
            return compiled._bind(params)
        finally:
            _shuffle_ctx.reset(tok)
            _bind_devices.reset(tok_d)

    tables, arrays, aux, static, pvals = _bind_with(strategy)
    layout = _array_layout(compiled)
    sharded_rels = {id(e[0]) for e in layout
                    if e[0].info.data is probe_data}
    if strategy == "shuffle":
        try:
            meta = compiled.join_meta[0]
            arrays, _xbytes = _apply_shuffle(
                compiled, meta, arrays, layout, tables, static, params,
                ctx, reg)
            sharded_rels.add(id(meta["build_rel"]))
            reg.inc("mesh_join_shuffle")
        except _Ineligible as e:
            # an exchange-time ineligibility (e.g. a complex plate on a
            # join side) DECLINES TO BROADCAST like the plan-time checks
            # — it must not abandon the shard_map lane entirely.  The
            # bind re-runs with the shuffle specialization off (the
            # shuf_si static and artifact aux differ).
            reg.inc("mesh_join_shuffle_fallback_" + e.reason)
            strategy = "broadcast"
            tables, arrays, aux, static, pvals = _bind_with(strategy)
            layout = _array_layout(compiled)
            sharded_rels = {id(e[0]) for e in layout
                            if e[0].info.data is probe_data}
    if strategy == "broadcast":
        arrays = _apply_broadcast(
            compiled, arrays, layout, sharded_rels, tables, static,
            params, ctx, reg)
        reg.inc("mesh_join_broadcast")

    tags = compiled.tile_merge["tags"]
    # keyed on the DEVICE TUPLE, not the context token: two contexts
    # over the same devices lower identically, and a shard_map jit is
    # expensive enough that rotating it per context would make every
    # fresh MeshContext recompile the world
    key = (static, tuple(ctx.mesh.devices.ravel().tolist()), strategy)
    fn = compiled._jitted_mesh.get(key)
    first = fn is None
    if first:
        fn = _build_mesh_fn(compiled, static, tags, ctx, layout,
                            sharded_rels, arrays, aux, pvals)
        compiled._jitted_mesh[key] = fn
    n_merges = sum(1 for t in tags if t[0] != "key")
    from snappydata_tpu.parallel.mesh import dispatch_lock
    from snappydata_tpu.reliability import failpoints as rfail

    # mesh_dispatch entry seam — before the leaf lock (fenced region
    # must acquire nothing), so an injected raise fails the statement
    # before any collective rendezvous starts
    rfail.hit("mesh.dispatch")
    with tracing.span("jit_compile" if first else "device_execute",
                      phase="mesh", devices=ctx.num_devices), \
            dispatch_lock:
        outs = compiled._noted_call(
            static, "mesh", fn, (tuple(arrays), tuple(aux), pvals))
        # locklint: blocking-under-lock the dispatch lock exists exactly
        # to fence concurrent device collectives (see parallel/mesh.py);
        # it is a leaf — nothing is acquired under it
        jax.block_until_ready(outs)
    reg.inc("mesh_shard_execs")
    reg.inc("mesh_psum_merges", n_merges)
    note = compiled.agg_notes.get(static) if compiled.agg_notes else None
    if note is not None:
        reg.inc("agg_reduce_passes", note["passes"])
        for s in note["strategies"]:
            reg.inc("agg_strategy_" + s)
    host = jax.device_get(outs)
    if bool(np.asarray(host[2])):
        raise CompileError(
            "mesh partial overflow (group cardinality or join expansion "
            "past its bound): host path")
    return host, tables


def _build_mesh_fn(compiled, static, tags, ctx, layout, sharded_rels,
                   arrays, aux, pvals):
    """jit(shard_map(traced + collective merges)) for one (static,
    mesh, strategy) specialization.  in_specs: probe-side (and
    shuffled-build) relation leaves split on the batch axis, everything
    else replicated; out_specs replicated — after the psum/pmin/pmax
    tree every shard holds the full merged partials."""

    def leaf_spec(leaf, shard: bool):
        if leaf is None:
            return None
        return P("data", *([None] * (np.ndim(leaf) - 1))) if shard \
            else P()

    arr_specs: List = []
    for r, base, vpos in layout:
        shard = id(r) in sharded_rels
        for i in range(base, vpos):
            v, nl = arrays[i]
            if isinstance(v, tuple):
                parts = tuple(leaf_spec(x, shard) for x in v)
                vs = type(v)(*parts) if hasattr(v, "_fields") else parts
            else:
                vs = leaf_spec(v, shard)
            arr_specs.append((vs, leaf_spec(nl, shard)))
        arr_specs.append(leaf_spec(arrays[vpos], shard))

    def merged_fn(arrays_l, aux_l, pvals_l):
        mask, pairs, overflow = compiled.traced(
            static, arrays_l, aux_l, pvals_l)
        out_pairs = []
        for (va, na), tag in zip(pairs, tags):
            if tag[0] == "key":
                # key columns decode from the shared [G] index space —
                # identical on every shard, no merge needed
                out_pairs.append((va, na))
            elif tag[1] == "min":
                out_pairs.append((jax.lax.pmin(va, "data"), None))
            elif tag[1] == "max":
                out_pairs.append((jax.lax.pmax(va, "data"), None))
            else:  # sum family (covers counts and sumsq)
                out_pairs.append((jax.lax.psum(va, "data"), None))
        mask = jax.lax.psum(mask.astype(jnp.int32), "data") > 0
        overflow = jax.lax.psum(overflow.astype(jnp.int32), "data") > 0
        return mask, tuple(out_pairs), overflow

    aux_specs = jax.tree.map(lambda _: P(), tuple(aux))
    p_specs = jax.tree.map(lambda _: P(), tuple(pvals))
    return jax.jit(shard_map(
        merged_fn, mesh=ctx.mesh,
        in_specs=(tuple(arr_specs), aux_specs, p_specs),
        out_specs=P()))


# -- shuffle/broadcast bind caches ----------------------------------------
# Keyed on (mesh token, static, bind identity, params): an unchanged
# table version re-uses the exchanged layout; a mutation rotates the
# bind identity (the per-version `valid` array) and the entry ages out.

# per-plan layout caches register in a WeakKeyDictionary so the byte
# gauge WALKS live entries instead of keeping a counter ledger — a
# counter drifted on concurrent same-key recomputes and leaked forever
# when plan-cache eviction dropped a CompiledPlan (review finding)
_LAYOUT_CACHES = weakref.WeakKeyDictionary()


def _layout_cache(compiled) -> "collections.OrderedDict":
    with _cache_lock:
        cache = _LAYOUT_CACHES.get(compiled)
        if cache is None:
            cache = collections.OrderedDict()
            _LAYOUT_CACHES[compiled] = cache
    return cache


def mesh_layout_cache_nbytes() -> int:
    with _cache_lock:
        return sum(entry[1] for cache in _LAYOUT_CACHES.values()
                   for entry in cache.values())


def trim_layout_caches(target_bytes: int) -> int:
    """Tier ladder's HBM rung for the exchange caches: drop
    least-recently-used exchanged layouts until the total fits
    `target_bytes`.  Returns bytes freed; dropped layouts rebuild from
    the next bind (one re-exchange), exactly like an evicted plate."""
    freed = 0
    with _cache_lock:
        total = sum(entry[1] for cache in _LAYOUT_CACHES.values()
                    for entry in cache.values())
        for cache in list(_LAYOUT_CACHES.values()):
            while cache and total > max(0, int(target_bytes)):
                _k, entry = cache.popitem(last=False)
                total -= entry[1]
                freed += entry[1]
            if total <= max(0, int(target_bytes)):
                break
    return freed


def _cache_key(tables, static, params, ctx, kind: str):
    try:
        hash(params)
    except TypeError:
        return None
    return (kind, ctx.token, static,
            tuple(id(dt.valid) for dt in tables), params)


def _cache_get_put(compiled, key, tables, compute):
    import weakref

    from snappydata_tpu import config

    if key is None:
        value, nbytes = compute()
        return value, nbytes, False
    cache = _layout_cache(compiled)
    with _cache_lock:
        hit = cache.get(key)
        # the key carries id(valid) per bound table — verify the weakrefs
        # still point at those exact arrays (ids get reused after GC; a
        # stale hit would serve another version's exchanged layout)
        if hit is not None and all(
                r() is dt.valid for r, dt in zip(hit[2], tables)):
            cache.move_to_end(key)
            return hit[0], hit[1], True
    value, nbytes = compute()
    cap = int(config.global_properties().get(
        "mesh_shuffle_cache_entries", 4) or 0)
    refs = tuple(weakref.ref(dt.valid) for dt in tables)
    with _cache_lock:
        cache[key] = (value, nbytes, refs)
        while cap and len(cache) > cap:
            cache.popitem(last=False)
    return value, nbytes, False


def _apply_shuffle(compiled, meta, arrays, layout, tables, static,
                   params, ctx, reg):
    """Bucketed exchange of BOTH join sides (cached per bind identity);
    returns (new arrays list, exchanged bytes)."""
    key = _cache_key(tables, static, params, ctx, "shuf")

    def compute():
        # the exchange runs MULTI-DEVICE programs end to end — the key
        # encodes/device_gets read sharded arrays eagerly and the
        # bucketed gathers dispatch with sharded out_shardings — so the
        # whole computation holds the collective-rendezvous fence like
        # every other sharded dispatch (review finding: a concurrent
        # sharded query could interleave participants and deadlock)
        from snappydata_tpu.parallel.mesh import dispatch_lock

        with dispatch_lock:
            # locklint: blocking-under-lock the dispatch lock exists
            # exactly to fence device collectives; it is a leaf
            cap = int(jnp.shape(arrays[layout[0][2]])[1])
            replaced: Dict[int, object] = {}
            nbytes = 0
            moved_rows = 0
            for side, rel in (("probe", meta["probe_rel"]),
                              ("build", meta["build_rel"])):
                keys_d, valid_d = _encoded_keys(meta, side, arrays,
                                                layout)
                # locklint: blocking-under-lock the dispatch fence must
                # cover the eager sharded reads — that IS its purpose
                keys = np.asarray(jax.device_get(keys_d))
                # locklint: blocking-under-lock same fence invariant
                valid = np.asarray(jax.device_get(valid_d))
                old_b = valid.size // cap
                perm, live, b_new, moved = _bucket_layout(
                    keys, valid, cap, ctx.num_devices, old_b)
                rep, nb = _exchange_relation(
                    arrays, layout, rel, perm, live, b_new, cap, ctx)
                # locklint: blocking-under-lock the exchange completes
                # INSIDE the fence (leaf lock; nothing acquired under it)
                jax.block_until_ready(list(rep.values()))
                replaced.update(rep)
                nbytes += nb
                moved_rows += moved
        reg.inc("mesh_exchange_bytes", nbytes)
        reg.inc("mesh_exchange_rows", moved_rows)
        return replaced, nbytes

    replaced, _nb, hit = _cache_get_put(compiled, key, tables, compute)
    if hit:
        reg.inc("mesh_exchange_cache_hits")
    out = list(arrays)
    for i, v in replaced.items():
        out[i] = v
    return out, _nb


def _apply_broadcast(compiled, arrays, layout, sharded_rels, tables,
                     static, params, ctx, reg):
    """Replicate every non-probe relation's bound arrays (cached per
    bind identity); returns the new arrays list."""
    build_rels = [e[0] for e in layout if id(e[0]) not in sharded_rels]
    if not build_rels:
        return arrays
    key = _cache_key(tables, static, params, ctx, "bcast")

    def compute():
        replaced: Dict[int, object] = {}
        nbytes = 0
        for rel in build_rels:
            rep, nb = _replicate_relation(arrays, layout, rel, ctx)
            replaced.update(rep)
            nbytes += nb
        # broadcast volume stays under its OWN metric — the
        # mesh_exchange_* family is the shuffle exchange's evidence
        # (review finding: a pure-broadcast workload read as shuffling)
        reg.inc("mesh_broadcast_bytes", nbytes * ctx.num_devices)
        return replaced, nbytes

    replaced, _nb, hit = _cache_get_put(compiled, key, tables, compute)
    if hit:
        reg.inc("mesh_broadcast_cache_hits")
    out = list(arrays)
    for i, v in replaced.items():
        out[i] = v
    return out
