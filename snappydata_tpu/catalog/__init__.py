from snappydata_tpu.catalog.catalog import Catalog, TableInfo  # noqa: F401
