"""Shared plumbing for the locklint passes: findings, waiver comments,
file walking, dotted-name rendering."""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, NamedTuple, Optional


class Finding(NamedTuple):
    rule: str
    file: str
    line: int
    message: str

    def render(self) -> str:
        return "%s:%d: [%s] %s" % (self.file, self.line, self.rule,
                                   self.message)


# `# locklint: rule1,rule2 <invariant text>` on the offending line or the
# line above waives those rules at that site; the free text is the
# reviewed invariant that makes the shape safe. `# locklint: lock=NAME`
# additionally resolves an acquisition the analyzer cannot type.
_WAIVE_RE = re.compile(r"#\s*locklint:\s*([A-Za-z0-9_,.\-]+)(?:\s+(.*))?")
_LOCK_HINT_RE = re.compile(r"#\s*locklint:\s*lock=([A-Za-z0-9_.\-]+)")


class SourceFile:
    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.split("\n")
        self.tree = ast.parse(text, filename=path)

    def _annotation_lines(self, line: int):
        """The finding line itself, then upward through the contiguous
        pure-comment block above it (multi-line invariant comments)."""
        if 1 <= line <= len(self.lines):
            yield self.lines[line - 1]
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].strip().startswith("#"):
            yield self.lines[ln - 1]
            ln -= 1

    def waived(self, line: int, rule: str) -> bool:
        for text in self._annotation_lines(line):
            m = _WAIVE_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                if rule in rules or "all" in rules:
                    return True
        return False

    def lock_hint(self, line: int) -> Optional[str]:
        for text in self._annotation_lines(line):
            m = _LOCK_HINT_RE.search(text)
            if m:
                return m.group(1)
        return None


def iter_py_files(paths: List[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def load_sources(paths: List[str]) -> Dict[str, SourceFile]:
    out: Dict[str, SourceFile] = {}
    for path in iter_py_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            out[path] = SourceFile(path, fh.read())
    return out


def module_name(path: str) -> str:
    """Dotted module name from the path, rooted at the scanned tree."""
    parts = os.path.normpath(path).split(os.sep)
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    # keep at most the package-relative tail; strip leading ./ roots
    parts = [p for p in parts if p not in (".", "", "..")]
    return ".".join(parts)


def dotted(expr: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c' (None for anything
    fancier)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted(expr.value)
        if base is None:
            return None
        return base + "." + expr.attr
    if isinstance(expr, ast.Call):
        return None
    return None


def terminal_name(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
