"""Backend-aware fused segmented reductions for grouped aggregation.

One grouped query used to issue one masked full-table reduction PER GROUP
PER SLOT (`_seg_reduce` unroll) — a strategy tuned for TPU scatter costs
that is pessimal on CPU: TPC-H Q1 (G=9, ~9 slots) paid ~72 full passes
over a 24M-row table (r05: 2.3M rows/s vs Q6's 100M on the same data).
The executor now packs all compatible aggregate slots into one [N, S]
value matrix per accumulator-dtype family and reduces EVERY slot of the
family in a single fused dispatch.  This module owns the per-family
strategy table and the fused kernels:

  unroll   G masked reductions over the packed [N, S] block — the
           measured-good TPU regime for G <= 64 (dispatch-floor masked
           sums; r01: Q1 at 827M rows/s on one v5e)
  scatter  jax.ops.segment_{sum,min,max} along axis 0 — one pass, the
           safe default for large G on any backend
  matmul   one-hot [S,N]@[N,G] in the accumulator dtype — on CPU the
           one-hot feeds a multithreaded BLAS gemm (measured on the dev
           container, 24M rows, G=9: gemm with a prebuilt one-hot 0.7s
           vs 3.0s scatter vs 4.2s packed unroll), and the one-hot is
           exactly what the executor's group-index cache can reuse
           across repeated dashboard queries

`agg_reduce_strategy` (config.py) picks one explicitly; `auto` keys on
backend + G + S + N (see `resolve_strategy`).  Counts ride the float
family as 0.0/1.0 columns — exact below 2**53 rows, which also fixes
the old int32 count accumulator (`jnp.sum` of int32 ones kept int32 and
could wrap beyond 2**31 rows); the unroll/scatter count path widens by
an explicit row-count bound instead (`count_pack_dtype`).

Exactness contract per family:
  float sums  f64 accumulation everywhere (reordered summation only —
              measured max rel err vs math.fsum at Q1 scale: ~8e-14)
  int sums    int64 scatter/unroll only, NEVER matmul (f64 dot loses
              bits above 2**53)
  counts      exact on every strategy (f64 0/1 columns < 2**53, or
              bound-checked int accumulators)
  min/max     order-independent; empty groups keep the same +/-inf and
              integer-extreme fillers the unrolled path produced
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

STRATEGIES = ("auto", "unroll", "scatter", "matmul")

# unroll's G-masked-reductions shape only ever wins in the small-G
# dictionary regime; past this it degrades to scatter even if requested
UNROLL_MAX_SEGMENTS = 64

# On CPU, vectorized masked reductions are ~5x faster per pass than a
# scatter (measured: one masked [N] f64 sum 0.37s vs 2.0s segment_sum at
# 24M rows), so for a handful of segments — global aggregates and tiny
# groupings, TPC-H Q6's shape — unroll wins outright; beyond this the
# G-pass cost loses to one matmul/scatter pass.  (First bench run
# mis-routed Q6's 2-segment global sum to matmul: 0.24s -> 2.16s.)
CPU_UNROLL_MAX_SEGMENTS = 4

# matmul materializes (or caches) a [N, G] one-hot in the accumulator
# dtype: bound it so a large-G or huge-N aggregate falls back to scatter
# instead of exploding memory (TPC-H Q1 at SF4 with pow2 batch padding
# is 33.5M rows x 9 segments x 8B = 2.4GB — deliberately inside this
# bound)
MATMUL_ONEHOT_MAX_BYTES = 4 << 30

# int32 count accumulators are exact only while a group can hold fewer
# than 2**31 rows; above that the packed count dtype widens to int64
COUNT_I32_MAX_ROWS = (1 << 31) - 1


def count_pack_dtype(n_rows: int):
    """Accumulator dtype for packed int counts: int32 while no group can
    reach 2**31 rows (N is a static shape, so this is a trace-time
    decision), int64 beyond — the explicit widening for the old
    `jnp.sum(int32 ones)` overflow."""
    return jnp.int32 if n_rows <= COUNT_I32_MAX_ROWS else jnp.int64


def onehot_bytes(n_rows: int, num_segments: int, acc_dtype) -> int:
    return int(n_rows) * int(num_segments) * jnp.dtype(acc_dtype).itemsize


def resolve_strategy(requested: str, backend: str, num_segments: int,
                     n_rows: int, family: str, acc_dtype) -> str:
    """Pick the fused strategy for one accumulator family.

    family: "fsum" (float sums + counts-as-f64), "isum" (exact int64
    sums), "minmax".  Invalid requests degrade rather than fail:
    matmul is refused for int sums (inexact) and min/max (not a dot),
    and for one-hots past MATMUL_ONEHOT_MAX_BYTES; unroll degrades to
    scatter past UNROLL_MAX_SEGMENTS.
    """
    if requested not in STRATEGIES:
        requested = "auto"
    if requested == "matmul" and (
            family != "fsum"
            or onehot_bytes(n_rows, num_segments, acc_dtype)
            > MATMUL_ONEHOT_MAX_BYTES):
        requested = "auto"
    if requested == "unroll" and num_segments > UNROLL_MAX_SEGMENTS:
        requested = "scatter"
    if requested != "auto":
        return requested
    small = num_segments <= (UNROLL_MAX_SEGMENTS if backend == "tpu"
                             else CPU_UNROLL_MAX_SEGMENTS)
    if small:
        # TPU: unrolled masked reductions are at the dispatch floor for
        # dictionary-card G (measured r01 — XLA lowers scatter serially
        # there); CPU: they beat one-hot materialization while the pass
        # count stays tiny (global aggregates, Q6)
        return "unroll"
    if family == "fsum" and backend != "tpu" and onehot_bytes(
            n_rows, num_segments, acc_dtype) <= MATMUL_ONEHOT_MAX_BYTES:
        # CPU dictionary regime: the one-hot gemm is the measured winner
        # (24M rows, G=9: gemm with a prebuilt one-hot 0.7s vs 3.0s
        # scatter vs 4.2s packed unroll), and the one-hot is exactly
        # what the group-index cache amortizes across repeated queries
        return "matmul"
    return "scatter"


def make_onehot(gidx, num_segments: int, acc_dtype):
    """[N, G] one-hot of the (already validity-masked) group index in
    the accumulator dtype.  Callers pass the REAL group count: rows
    whose gidx points at the excluded overflow segment match no column
    and become all-zero rows, contributing nothing to any group — so
    invalid rows need no per-slot masking on the matmul path."""
    return (gidx[:, None]
            == jnp.arange(num_segments)[None, :]).astype(acc_dtype)


def _pack(cols):
    """[N, S] matrix from a family's columns.  Only the scatter/matmul
    strategies pay this materialization; unroll reduces straight from
    the source columns so XLA fuses each mask+reduce chain with the
    expressions that produced the column (measured: packing Q6's single
    global sum cost ~0.4s of pure stack traffic at 24M rows)."""
    if len(cols) == 1:
        return cols[0][:, None]
    return jnp.stack(cols, axis=1)


def packed_sum(cols, gidx, num_segments: int, strategy: str,
               onehot=None):
    """Fused segmented SUM of a family's columns (list of [N] arrays)
    -> [num_segments, S].  Rows must already be masked into the
    additive identity (0).

    matmul caveat: NaN/Inf values leak across groups through the dot
    (NaN * one-hot-zero is NaN), so the matmul branch carries a
    runtime finite-check and falls back to the group-isolating scatter
    via lax.cond when any packed value is non-finite."""
    if strategy == "unroll" and num_segments <= UNROLL_MAX_SEGMENTS:
        outs = []
        for k in range(num_segments):
            m = gidx == k
            outs.append(jnp.stack([
                jnp.sum(jnp.where(m, c, jnp.zeros((), c.dtype)))
                for c in cols]))
        return jnp.stack(outs)
    packed = _pack(cols)
    if strategy == "matmul":
        oh = make_onehot(gidx, num_segments, packed.dtype) \
            if onehot is None else onehot
        if jnp.issubdtype(packed.dtype, jnp.floating):
            return jax.lax.cond(
                jnp.all(jnp.isfinite(packed)),
                lambda p, o: (p.T @ o).T,
                lambda p, _o: jax.ops.segment_sum(
                    p, gidx, num_segments=num_segments),
                packed, oh)
        return (packed.T @ oh).T
    return jax.ops.segment_sum(packed, gidx, num_segments=num_segments)


def packed_minmax(kind: str, cols, gidx, num_segments: int,
                  strategy: str):
    """Fused segmented MIN/MAX of a family's columns (list of [N]
    arrays).  Rows must already be masked to the identity filler
    (+/-inf or integer extremes); empty segments yield that filler,
    matching what the old per-slot unroll produced (scatter's
    segment_min/max use the same identity)."""
    if strategy == "unroll" and num_segments <= UNROLL_MAX_SEGMENTS:
        op = jnp.min if kind == "min" else jnp.max
        fill = _extreme_of(cols[0].dtype, kind == "min")
        outs = []
        for k in range(num_segments):
            m = gidx == k
            outs.append(jnp.stack([op(jnp.where(m, c, fill))
                                   for c in cols]))
        return jnp.stack(outs)
    packed = _pack(cols)
    seg = jax.ops.segment_min if kind == "min" else jax.ops.segment_max
    return seg(packed, gidx, num_segments=num_segments)


def _extreme_of(dtype, positive: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if positive else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if positive else info.min, dtype)
