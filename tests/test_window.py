"""Window function tests vs pandas oracle."""

import numpy as np
import pandas as pd
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture(scope="module")
def s():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE sal (dept STRING, emp INT, pay DOUBLE) "
             "USING column")
    rng = np.random.default_rng(5)
    n = 500
    sess.insert_arrays("sal", [
        np.array(["a", "b", "c"], dtype=object)[rng.integers(0, 3, n)],
        np.arange(n, dtype=np.int32),
        np.round(rng.uniform(1000, 9000, n), 2)])
    yield sess
    sess.stop()


@pytest.fixture(scope="module")
def df(s):
    r = s.sql("SELECT * FROM sal")
    return pd.DataFrame({n: c for n, c in zip(r.names, r.columns)})


def test_row_number(s, df):
    r = s.sql("SELECT emp, row_number() OVER "
              "(PARTITION BY dept ORDER BY pay DESC) AS rn FROM sal")
    got = {row[0]: row[1] for row in r.rows()}
    exp = df.sort_values("pay", ascending=False).groupby("dept").cumcount() + 1
    for emp, rn in zip(df.emp, exp.reindex(df.index)):
        assert got[emp] == rn


def test_rank_and_dense_rank(s):
    s.sql("CREATE TABLE t (g STRING, v INT) USING column")
    s.sql("INSERT INTO t VALUES ('x', 10), ('x', 10), ('x', 20), "
          "('y', 5), ('y', 7), ('y', 7)")
    r = s.sql("SELECT g, v, rank() OVER (PARTITION BY g ORDER BY v) AS r, "
              "dense_rank() OVER (PARTITION BY g ORDER BY v) AS dr "
              "FROM t ORDER BY g, v")
    rows = r.rows()
    assert [(x[2], x[3]) for x in rows] == \
        [(1, 1), (1, 1), (3, 2), (1, 1), (2, 2), (2, 2)]


def test_partition_aggregate_whole_frame(s, df):
    r = s.sql("SELECT emp, pay, sum(pay) OVER (PARTITION BY dept) AS total, "
              "avg(pay) OVER (PARTITION BY dept) AS ap FROM sal")
    totals = df.groupby("dept").pay.sum()
    means = df.groupby("dept").pay.mean()
    dept_of = dict(zip(df.emp, df.dept))
    for emp, pay, total, ap in r.rows():
        assert total == pytest.approx(totals[dept_of[emp]])
        assert ap == pytest.approx(means[dept_of[emp]])


def test_running_sum(s):
    s.sql("CREATE TABLE rs (g STRING, ord INT, v INT) USING column")
    s.sql("INSERT INTO rs VALUES ('a', 1, 10), ('a', 2, 20), ('a', 3, 30), "
          "('b', 1, 5), ('b', 2, 5)")
    r = s.sql("SELECT g, ord, sum(v) OVER (PARTITION BY g ORDER BY ord) "
              "AS running FROM rs ORDER BY g, ord")
    assert [x[2] for x in r.rows()] == [10, 30, 60, 5, 10]


def test_lag_lead(s):
    s.sql("CREATE TABLE ll (ord INT, v INT) USING column")
    s.sql("INSERT INTO ll VALUES (1, 100), (2, 200), (3, 300)")
    r = s.sql("SELECT ord, lag(v) OVER (ORDER BY ord) AS prev, "
              "lead(v) OVER (ORDER BY ord) AS nxt FROM ll ORDER BY ord")
    assert r.rows() == [(1, None, 200), (2, 100, 300), (3, 200, None)]


def test_window_in_expression(s):
    s.sql("CREATE TABLE we (g STRING, v DOUBLE) USING column")
    s.sql("INSERT INTO we VALUES ('a', 10.0), ('a', 30.0), ('b', 50.0)")
    r = s.sql("SELECT g, v, v / sum(v) OVER (PARTITION BY g) AS share "
              "FROM we ORDER BY g, v")
    assert [x[2] for x in r.rows()] == [pytest.approx(0.25),
                                        pytest.approx(0.75),
                                        pytest.approx(1.0)]


def test_window_with_prepared_params(s):
    s.sql("CREATE TABLE wp (id INT, age INT) USING column")
    s.sql("INSERT INTO wp VALUES (1, 30), (2, 60), (3, 40)")
    r = s.sql("SELECT id, row_number() OVER (ORDER BY id) FROM wp "
              "WHERE age > ? AND id < ?", params=(35, 3))
    assert r.rows() == [(2, 1)]


def test_window_aggregates_skip_nulls(s):
    s.sql("CREATE TABLE wn (b INT) USING column")
    s.sql("INSERT INTO wn VALUES (NULL), (2), (4)")
    r = s.sql("SELECT count(b) OVER () AS c, avg(b) OVER () AS a, "
              "min(b) OVER () AS m FROM wn LIMIT 1")
    assert r.rows() == [(2, 3.0, 2)]


def test_running_frame_range_semantics_on_ties(s):
    s.sql("CREATE TABLE wt (k INT, v INT) USING column")
    s.sql("INSERT INTO wt VALUES (1, 10), (1, 20), (2, 5)")
    r = s.sql("SELECT k, sum(v) OVER (ORDER BY k) AS rs FROM wt "
              "ORDER BY k, v")
    assert [x[1] for x in r.rows()] == [30, 30, 35]  # peers share the frame


def test_null_join_keys_never_match(s):
    s.sql("CREATE TABLE njc (ck INT) USING column")
    s.sql("CREATE TABLE njo (ok INT) USING column")
    s.sql("INSERT INTO njc VALUES (1), (NULL)")
    s.sql("INSERT INTO njo VALUES (NULL), (2)")
    r = s.sql("SELECT count(*) FROM njc WHERE NOT EXISTS "
              "(SELECT 1 FROM njo WHERE ok = ck)")
    assert r.rows()[0][0] == 2
    r = s.sql("SELECT count(*) FROM njc JOIN njo ON ck = ok")
    assert r.rows()[0][0] == 0


def test_mixed_dtype_join_keys(s):
    s.sql("CREATE TABLE mji (k INT) USING column")
    s.sql("CREATE TABLE mjd (k2 DOUBLE) USING column")
    s.sql("INSERT INTO mji VALUES (3), (4)")
    s.sql("INSERT INTO mjd VALUES (3.0), (5.0)")
    assert s.sql("SELECT count(*) FROM mji JOIN mjd ON k = k2"
                 ).rows()[0][0] == 1


def test_distinct_in_window_rejected(s):
    with pytest.raises(Exception, match="DISTINCT"):
        s.sql("SELECT count(DISTINCT dept) OVER () FROM sal")


def test_count_star_window(s):
    s.sql("CREATE TABLE cw (g STRING) USING column")
    s.sql("INSERT INTO cw VALUES ('a'), ('a'), ('b')")
    r = s.sql("SELECT g, count(*) OVER (PARTITION BY g) AS c FROM cw "
              "ORDER BY g")
    assert [x[1] for x in r.rows()] == [2, 2, 1]


def test_device_window_no_host_fallback():
    """Supported OVER() shapes must run in the compiled device path (ref:
    PushDownWindowLogicalPlan; round-1 gap: ALL windows were host pandas)."""
    import pandas as pd
    from snappydata_tpu.observability.metrics import global_registry

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE dw (g BIGINT, t BIGINT, v DOUBLE) USING column")
    rng = np.random.default_rng(9)
    n = 5000
    g = rng.integers(0, 40, n).astype(np.int64)
    t = rng.permutation(n).astype(np.int64)
    v = np.round(rng.random(n) * 10, 3)
    s.insert_arrays("dw", [g, t, v])
    df = pd.DataFrame({"g": g, "t": t, "v": v})

    before = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    r = s.sql(
        "SELECT g, t, row_number() OVER (PARTITION BY g ORDER BY t) AS rn,"
        " dense_rank() OVER (PARTITION BY g ORDER BY t DESC) AS dr,"
        " count(*) OVER (PARTITION BY g) AS c,"
        " min(v) OVER (PARTITION BY g ORDER BY t) AS mn,"
        " max(v) OVER (PARTITION BY g) AS mx,"
        " lead(t) OVER (PARTITION BY g ORDER BY t) AS ld "
        "FROM dw")
    after = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    assert after == before, "supported windows fell back to host"

    got = pd.DataFrame(r.rows(), columns=r.names) \
        .sort_values(["g", "t"]).reset_index(drop=True)
    ex = df.sort_values(["g", "t"]).reset_index(drop=True)
    ex["rn"] = ex.groupby("g").cumcount() + 1
    ex["dr"] = ex.groupby("g").t.rank(method="dense", ascending=False) \
        .astype(int)
    ex["c"] = ex.groupby("g").t.transform("size")
    ex["mn"] = ex.groupby("g").v.cummin()
    ex["mx"] = ex.groupby("g").v.transform("max")
    ex["ld"] = ex.groupby("g").t.shift(-1)
    assert (got.rn.to_numpy() == ex.rn.to_numpy()).all()
    assert (got.dr.to_numpy() == ex.dr.to_numpy()).all()
    assert (got.c.to_numpy() == ex.c.to_numpy()).all()
    assert np.allclose(got.mn.to_numpy(), ex.mn.to_numpy())
    assert np.allclose(got.mx.to_numpy(), ex.mx.to_numpy())
    gn, en = got.ld.isna().to_numpy(), ex.ld.isna().to_numpy()
    assert (gn == en).all()
    assert (got.ld.to_numpy()[~gn].astype(np.int64)
            == ex.ld.to_numpy()[~en].astype(np.int64)).all()


def test_device_window_null_handling():
    """NULL aggregate inputs are skipped; NULL order keys sort last."""
    import pandas as pd
    from snappydata_tpu.observability.metrics import global_registry

    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE dwn (g BIGINT, t BIGINT, v DOUBLE) USING column")
    s.sql("INSERT INTO dwn VALUES (1, 1, 10.0), (1, 2, NULL), "
          "(1, 3, 30.0), (2, 1, NULL), (2, 2, NULL)")
    before = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    r = s.sql("SELECT g, t, sum(v) OVER (PARTITION BY g ORDER BY t) AS rs,"
              " count(v) OVER (PARTITION BY g ORDER BY t) AS cv "
              "FROM dwn ORDER BY g, t")
    after = global_registry().snapshot()["counters"].get(
        "host_fallbacks", 0)
    assert after == before
    rows = r.rows()
    assert [x[2] for x in rows] == [10.0, 10.0, 40.0, None, None]
    assert [x[3] for x in rows] == [1, 1, 2, 0, 0]


def test_window_order_null_placement_spark_defaults():
    """ASC → NULLS FIRST (Spark default): a NULL order key ranks FIRST;
    explicit NULLS LAST overrides — honored on device AND host paths."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE wnp (g VARCHAR, v DOUBLE) USING column")
    s.sql("INSERT INTO wnp VALUES ('a', 2.0), ('a', NULL), ('a', 1.0)")
    got = s.sql("SELECT v, row_number() OVER (PARTITION BY g ORDER BY v) "
                "FROM wnp ORDER BY 2").rows()
    assert got == [(None, 1), (1.0, 2), (2.0, 3)], got
    got = s.sql("SELECT v, row_number() OVER "
                "(PARTITION BY g ORDER BY v NULLS LAST) "
                "FROM wnp ORDER BY 2").rows()
    assert got == [(1.0, 1), (2.0, 2), (None, 3)], got
    got = s.sql("SELECT v, row_number() OVER "
                "(PARTITION BY g ORDER BY v DESC) "
                "FROM wnp ORDER BY 2").rows()
    assert got == [(2.0, 1), (1.0, 2), (None, 3)], got
    got = s.sql("SELECT v, row_number() OVER "
                "(PARTITION BY g ORDER BY v DESC NULLS FIRST) "
                "FROM wnp ORDER BY 2").rows()
    assert got == [(None, 1), (2.0, 2), (1.0, 3)], got
    s.stop()


def test_top_level_order_by_nulls_first_last():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE onp (v DOUBLE) USING column")
    s.sql("INSERT INTO onp VALUES (2.0), (NULL), (1.0)")
    assert s.sql("SELECT v FROM onp ORDER BY v").rows() == \
        [(None,), (1.0,), (2.0,)]
    assert s.sql("SELECT v FROM onp ORDER BY v NULLS LAST").rows() == \
        [(1.0,), (2.0,), (None,)]
    assert s.sql("SELECT v FROM onp ORDER BY v DESC").rows() == \
        [(2.0,), (1.0,), (None,)]
    assert s.sql("SELECT v FROM onp ORDER BY v DESC NULLS FIRST").rows() \
        == [(None,), (2.0,), (1.0,)]
    s.stop()
