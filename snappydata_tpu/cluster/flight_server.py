"""Arrow Flight front door: SQL + bulk ingest per node.

The reference's network server is a thrift/DRDA listener on every data
server with failover-aware drivers (cluster/README-thrift.md:20-35), with
an ExecutionEngineArbiter that answers simple/point queries locally and
routes analytics to the lead (docs/architecture/cluster_architecture.md:
31-33). TPU-first choice per SURVEY.md §7.7: Arrow Flight — columnar
result paging for free, off-the-shelf clients:

- do_get(Ticket{sql, params})   → query as one Arrow table (record-batch
                                  paged by Flight itself)
- do_put(descriptor=table name) → bulk columnar ingest straight into the
                                  column store (the 1M events/s path —
                                  no per-row protocol overhead)
- do_action(sql|checkpoint|stats|ping) → DDL/DML + ops
"""

from __future__ import annotations

import json
import threading
from snappydata_tpu.utils import locks
from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight

from snappydata_tpu import types as T


def result_to_arrow(result, sel: Optional[np.ndarray] = None) -> pa.Table:
    """Result → Arrow table; `sel` optionally selects a row subset (used by
    the repartition exchange to ship one peer's shard)."""
    arrays = []
    names = []
    for name, col, nmask, dtype in zip(result.names, result.columns,
                                       result.nulls, result.dtypes):
        names.append(name)
        if sel is not None:
            col = np.asarray(col)[sel]
            nmask = np.asarray(nmask)[sel] if nmask is not None else None
        if dtype.name == "decimal":
            # real decimal128(p, s) on the wire — the BI/JDBC contract
            # (ref readDecimal, ColumnEncoding.scala:137-140); values may
            # be Decimal objects (finalized), scaled int64 (engine
            # domain) or plain floats (host fallback)
            import decimal as _d

            pt = pa.decimal128(max(1, dtype.precision), dtype.scale)
            fconv = T.decimal_float_converter(dtype)

            def cell(i, v):
                if (nmask is not None and nmask[i]) or v is None:
                    return None
                if isinstance(v, _d.Decimal):
                    return v
                if isinstance(v, (int, np.integer)) \
                        and getattr(dtype, "is_exact", False):
                    return T.unscaled_to_python(dtype, v)
                return fconv(v)

            cells = [cell(i, v) for i, v in enumerate(col)]
            try:
                arrays.append(pa.array(cells, type=pt))
            except (pa.ArrowInvalid, pa.ArrowTypeError):
                # the engine's int64-overflow fallback returns an
                # APPROXIMATE float total that can be wider than the
                # declared precision (decimal_sum_type caps at p=18) —
                # widen the wire type rather than failing the export
                # the local session happily answers (advisor round 5)
                try:
                    arrays.append(pa.array(
                        cells, type=pa.decimal128(38, dtype.scale)))
                except (pa.ArrowInvalid, pa.ArrowTypeError):
                    arrays.append(pa.array(
                        [None if c is None else float(c) for c in cells],
                        type=pa.float64()))
        elif dtype.name == "string" or col.dtype == object:
            arrays.append(pa.array(
                [None if (nmask is not None and nmask[i]) or v is None
                 else str(v) for i, v in enumerate(col)], type=pa.string()))
        else:
            arrays.append(pa.array(col, mask=np.asarray(nmask)
                          if nmask is not None else None))
    return pa.table(dict(zip(names, arrays)))


def try_stream_scan(sess, sql_text: str, params=(),
                    page_rows: int = 65536):
    """Scan-shaped queries ([LIMIT] [Project] [Filter] Relation over a
    column table — no aggregate/sort/join/window) stream per scan unit
    through the `sql` ticket instead of materializing the whole result
    first: a `SELECT *` over a table far larger than host memory
    completes with peak host rows bounded by one column batch
    (ref: CachedDataFrame.executeTake:766 incremental decode +
    SparkSQLExecuteImpl.packRows:109 paging; round-4 verdict Weak #7).

    Row-level security stays intact — policy predicates inject during
    `analyze_plan` (sql/analyzer.py relation resolution), which runs
    here exactly as in the materialized path. Returns (pa.schema,
    generator-of-record-batches) or None when the shape doesn't
    qualify (the caller falls back to the materialized path)."""
    from snappydata_tpu.engine import hosteval
    from snappydata_tpu.engine.result import Result
    from snappydata_tpu.sql import ast as _ast
    from snappydata_tpu.sql.analyzer import _expr_name, expr_type
    from snappydata_tpu.sql.parser import parse as _parse
    from snappydata_tpu.storage.table_store import RowTableData

    try:
        stmt = _parse(sql_text)
    except Exception:
        return None
    if not isinstance(stmt, _ast.Query):
        return None
    if getattr(stmt, "with_error", None) is not None:
        # AQP WITH ERROR routes through the error-estimation path —
        # streaming plain rows would silently drop the clause
        return None

    def plain(e) -> bool:
        if isinstance(e, (_ast.WindowFunc, _ast.ScalarSubquery,
                          _ast.InSubquery, _ast.ExistsSubquery)):
            return False
        if isinstance(e, _ast.Func) and e.name in _ast.AGG_FUNCS:
            return False
        return all(plain(c) for c in e.children())

    def peel(plan):
        """([limit], [proj], [filt], relation-ish) or None — shared by
        the RAW pre-analysis gate (so non-scan queries skip the second
        analyze; review finding) and the resolved-plan match."""
        node = plan
        lim = None
        if isinstance(node, _ast.Limit):
            lim = int(node.n)
            node = node.child
        pr = None
        if isinstance(node, _ast.Project):
            pr = node
            node = node.child
        fl = None
        if isinstance(node, _ast.Filter):
            fl = node
            node = node.child
        while isinstance(node, _ast.SubqueryAlias):
            node = node.child
        if not isinstance(node, (_ast.Relation,
                                 _ast.UnresolvedRelation)):
            return None
        for e in (list(pr.exprs) if pr is not None else []) \
                + ([fl.condition] if fl is not None else []):
            if not plain(e):
                return None
        return lim, pr, fl, node

    if peel(stmt.plan) is None:   # cheap raw-shape gate: no analyze
        return None
    try:
        resolved, _scope = sess.analyzer.analyze_plan(stmt.plan)
        # user '?' placeholders: positions are normally assigned inside
        # _run_query_inner — this path bypasses it, and an unassigned
        # Param(pos=-1) would read params[-1] (review finding; the
        # round-4 UPDATE/DELETE bug class)
        from snappydata_tpu.sql.analyzer import assign_param_positions

        resolved = assign_param_positions(resolved, 0)
    except Exception:
        return None
    shaped = peel(resolved)
    if shaped is None:
        return None
    limit, proj, filt, node = shaped
    if not isinstance(node, _ast.Relation):
        return None
    info = sess.catalog.lookup_table(node.name)
    if info is None or isinstance(info.data, RowTableData):
        return None  # row tables are small: materialized path is fine

    exprs = list(proj.exprs) if proj is not None else None

    sess._require(node.name, "select")
    if exprs is not None:
        out_names = [_expr_name(e) for e in exprs]
        out_types = [expr_type(e) for e in exprs]
    else:
        fields = [f for f in info.schema.fields]
        out_names = [f.name for f in fields]
        out_types = [f.dtype for f in fields]
    schema = pa.schema([pa.field(n, _arrow_type(t))
                        for n, t in zip(out_names, out_types)])

    def gen():
        from snappydata_tpu.observability.metrics import global_registry

        reg = global_registry()
        have = 0
        for chunk in iter_table_chunks(sess, node.name):
            cols = list(chunk.columns)
            nulls = list(chunk.nulls)
            n = chunk.num_rows
            if filt is not None:
                v, nl = hosteval.eval_expr(filt.condition, cols, nulls,
                                           params, n)
                keep = np.broadcast_to(v, (n,)).astype(bool)
                if nl is not None:
                    keep = keep & ~np.broadcast_to(nl, (n,))
                idx = np.flatnonzero(keep)
                if idx.size == 0:
                    continue
                cols = [c[idx] for c in cols]
                nulls = [nm[idx] if nm is not None else None
                         for nm in nulls]
                n = idx.size
            if exprs is not None:
                out_c, out_n = [], []
                for e in exprs:
                    v, nl = hosteval.eval_expr(e, cols, nulls, params, n)
                    out_c.append(np.broadcast_to(v, (n,)))
                    out_n.append(np.broadcast_to(nl, (n,))
                                 if nl is not None else None)
            else:
                out_c, out_n = cols, nulls
            if limit is not None and have + n > limit:
                take = limit - have
                out_c = [c[:take] for c in out_c]
                out_n = [nm[:take] if nm is not None else None
                         for nm in out_n]
                n = take
            res = Result(out_names, out_c, out_n, out_types)
            tbl = result_to_arrow(res)
            if tbl.schema != schema:
                tbl = tbl.cast(schema)
            reg.inc("stream_scan_chunks")
            reg.inc("stream_scan_rows", n)
            yield from tbl.to_batches(max_chunksize=max(1, page_rows))
            have += n
            if limit is not None and have >= limit:
                reg.inc("stream_scan_early_stops")
                return  # LIMIT early-exit: remaining units never decode

    return schema, gen


def iter_table_chunks(sess, table: str):
    """Stream a table's content as per-scan-unit Results — one column
    batch (or row-buffer chunk) decoded at a time, so exporting a table
    never materializes more than `column_batch_rows` rows on the host
    (ref: batch-at-a-time ColumnFormatIterator; the round-2/3 exchanges
    built the whole table first — this is the streamed replacement).
    Yields `snappydata_tpu.engine.result.Result` objects."""
    from snappydata_tpu.engine.result import Result
    from snappydata_tpu.storage.table_store import RowTableData

    info = sess.catalog.describe(table)
    schema = info.schema
    names = [f.name for f in schema.fields]
    dtypes = [f.dtype for f in schema.fields]
    if isinstance(info.data, RowTableData):
        # row tables are bounded by design (PK'd operational rows)
        res = sess.sql(f"SELECT * FROM {table}")
        if res.num_rows:
            yield res
        return
    data = info.data
    from snappydata_tpu.storage import mvcc

    # one manifest for the whole stream (per-unit consistency) — the
    # ambient pinned epoch when a snapshot-pinned statement streams
    manifest = mvcc.snapshot_of(data)
    for view in manifest.views:
        live = view.live_mask()
        n = int(live.sum())
        if n == 0:
            continue
        cols, nulls = [], []
        for ci, f in enumerate(schema.fields):
            if f.dtype.name == "string":
                codes = view.decoded_column(ci)[live]
                lut = data.dictionary(ci)
                vals = lut[codes] if lut is not None and len(lut) \
                    else np.array([None] * n, dtype=object)
            else:
                vals = view.decoded_column(ci)[live]
            nm = view.null_mask(ci)
            nulls.append(nm[live] if nm is not None else None)
            cols.append(vals)
        yield Result(list(names), cols, nulls, list(dtypes))
    # row-buffer snapshot rows
    if manifest.row_count:
        cols, nulls = [], []
        for ci, f in enumerate(schema.fields):
            src = manifest.row_arrays[ci][:manifest.row_count]
            nm = manifest.row_nulls[ci][:manifest.row_count] \
                if manifest.row_nulls and manifest.row_nulls[ci] is not None \
                else None
            cols.append(np.asarray(src))
            nulls.append(nm)
        yield Result(list(names), cols, nulls, list(dtypes))


def arrow_to_arrays(table: pa.Table):
    """Arrow table → (arrays, null_masks) in storage domain."""
    arrays = []
    nulls = []
    for col in table.columns:
        combined = col.combine_chunks()
        if pa.types.is_decimal(combined.type):
            # storage host domain for decimals is plain float64 (the
            # scaled-int64 form is device-bind-time only); f64 holds
            # partial aggregates exactly through 15 significant digits
            vals = combined.to_pylist()
            arrays.append(np.array(
                [0.0 if v is None else float(v) for v in vals],
                dtype=np.float64))
            nulls.append(np.array([v is None for v in vals])
                         if combined.null_count else None)
        elif pa.types.is_string(combined.type) or \
                pa.types.is_large_string(combined.type):
            arrays.append(np.array(combined.to_pylist(), dtype=object))
            nulls.append(np.array([v is None for v in combined.to_pylist()])
                         if combined.null_count else None)
        else:
            np_arr = combined.to_numpy(zero_copy_only=False)
            if combined.null_count:
                mask = np.array([not v for v in
                                 combined.is_valid().to_pylist()])
                np_arr = np.where(mask, 0, np_arr)
                nulls.append(mask)
            else:
                nulls.append(None)
            arrays.append(np_arr)
    return arrays, nulls


class _HeaderAuthMiddleware(flight.ServerMiddleware):
    def __init__(self, header: Optional[str]):
        self.header = header


class _HeaderAuthMiddlewareFactory(flight.ServerMiddlewareFactory):
    """Captures the `authorization` header so FlightSQL requests (which
    authenticate per the spec via Basic/Bearer headers, not a body
    token) can resolve their principal."""

    def start_call(self, info, headers):
        vals = headers.get("authorization") or \
            headers.get(b"authorization") or []
        v = vals[0] if vals else None
        if isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        return _HeaderAuthMiddleware(v)


class SnappyFlightServer(flight.FlightServerBase):
    # login-issued tokens expire after this long; the client re-logs-in
    # transparently (SnappyClient retries once on Unauthenticated)
    TOKEN_TTL_S = 8 * 3600.0

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0,
                 auth_tokens: Optional[dict] = None, auth_provider=None,
                 internal_token: Optional[str] = None):
        """`auth_tokens`: pre-shared token → user map. `auth_provider`: a
        `security.AuthProvider` (BUILTIN/LDAP) validating user+password —
        clients `login` once for an ephemeral token (ref: SecurityUtils
        credential check per connection). When either is configured, EVERY
        request must carry a valid credential and runs as that principal
        (so GRANT/REVOKE applies); when neither is, requests run as an
        UNAUTHENTICATED remote session — EXEC PYTHON is refused either way
        unless the principal is an authenticated admin (advisor finding:
        the network surface used to run as the admin superuser).
        `internal_token`: cluster-shared secret (conf `auth_cluster_token`)
        for server↔server traffic — login tokens are per-server, so peer
        calls (repartition/replicate do_put) authenticate with this
        instead of forwarding a caller's token."""
        location = f"grpc://{host}:{port}"
        super().__init__(
            location,
            middleware={"snappy-auth": _HeaderAuthMiddlewareFactory()})
        self.session = session
        from snappydata_tpu.cluster.flightsql import FlightSqlHandler

        self.flightsql = FlightSqlHandler(self)
        self.auth_tokens = auth_tokens or {}
        self.auth_provider = auth_provider
        self.internal_token = internal_token
        self._issued_tokens: dict = {}   # token -> (user, expiry)
        self._token_lock = locks.named_lock("flight.tokens")
        self.host = host
        self._location = location

    @property
    def actual_port(self) -> int:
        return self.port

    def _origin(self) -> str:
        """This member's REAL bound address for trace origins — the
        init-time `_location` may say port 0 (bind-assigned)."""
        try:
            return f"grpc://{self.host}:{self.port}"
        except Exception:
            return self._location

    def wait_ready(self, timeout: float = 10.0) -> None:
        """Block until the gRPC loop actually accepts connections. The port
        is bound at __init__, so a nonzero port does NOT mean serve() is
        running yet — probing with a real connection is the only reliable
        readiness signal."""
        client = flight.connect(f"grpc://{self.host}:{self.port}")
        try:
            client.wait_for_available(timeout=int(max(1, timeout)))
        finally:
            client.close()

    def _auth_enabled(self) -> bool:
        return bool(self.auth_tokens) or self.auth_provider is not None

    def _session_for(self, body: Optional[dict]):
        """Per-request principal session (ref: SnappySessionPerConnection,
        SparkSQLExecuteImpl.scala:99)."""
        if not self._auth_enabled():
            return self.session.for_user(self.session.user,
                                         authenticated=False)
        body = body or {}
        token = body.get("token")
        if token is not None and not isinstance(token, str):
            raise flight.FlightUnauthenticatedError("malformed token")
        user = None
        if token:
            import hmac as _hmac

            if self.internal_token is not None and _hmac.compare_digest(
                    token.encode("utf-8"),
                    self.internal_token.encode("utf-8")):
                # peer server: runs as this node's own (admin) principal
                user = self.session.user
            else:
                user = self.auth_tokens.get(token)
            if user is None:
                import time as _t

                with self._token_lock:
                    entry = self._issued_tokens.get(token)
                    if entry is not None:
                        if entry[1] > _t.time():
                            user = entry[0]
                        else:
                            self._issued_tokens.pop(token, None)
        if user is None and self.auth_provider is not None:
            # inline credentials (clients normally `login` once instead —
            # this path hits the provider, e.g. an LDAP bind, per request)
            u, p = body.get("user"), body.get("password")
            if u and p and self.auth_provider.authenticate(u, p):
                user = u
        if user is None:
            raise flight.FlightUnauthenticatedError(
                "missing or invalid token/credentials")
        return self.session.for_user(user, authenticated=True)

    def _session_from_context(self, context):
        """FlightSQL principal resolution: the `authorization` header
        (Basic user:password or Bearer <token>) captured by middleware
        feeds the same credential paths as the JSON body protocol."""
        body: dict = {}
        try:
            mw = context.get_middleware("snappy-auth")
        except Exception:
            mw = None
        header = getattr(mw, "header", None)
        if header:
            if header.lower().startswith("basic "):
                import base64

                try:
                    raw = base64.b64decode(header[6:]).decode("utf-8")
                    u, _, p = raw.partition(":")
                    body = {"user": u, "password": p}
                except Exception:
                    pass
            elif header.lower().startswith("bearer "):
                body = {"token": header[7:]}
        return self._session_for(body)

    # -- queries ----------------------------------------------------------

    @staticmethod
    def _deadline_ctx(body: Optional[dict], sess, sql_text: str):
        """Deadline propagation (reliability layer): a request body
        carrying `timeout_s` — the CALLER's remaining budget — becomes a
        QueryContext deadline, so the engine's cooperative checks stop
        server-side work within one tile of the caller giving up instead
        of computing a result nobody will read. Returns None when the
        request carries no budget."""
        budget = (body or {}).get("timeout_s")
        try:
            budget = float(budget) if budget is not None else 0.0
        except (TypeError, ValueError):
            budget = 0.0
        if budget <= 0:
            return None
        from snappydata_tpu import resource

        ctx = resource.new_query(sql_text, user=sess.user)
        ctx.set_deadline_in(budget)
        return ctx

    def do_get(self, context, ticket: flight.Ticket):
        from snappydata_tpu.cluster.flightsql import unpack_any
        from snappydata_tpu.fault import failpoints

        # server-side failpoint: an injected raise here reaches clients
        # as a Flight error from a member that is otherwise ALIVE — the
        # probe-then-raise (no-failover) path in DistributedSession
        failpoints.hit("flight.serve")
        fsql = unpack_any(ticket.ticket)
        if fsql is not None:
            return self.flightsql.do_get(context, fsql[0], fsql[1])
        req = json.loads(ticket.ticket.decode("utf-8"))
        if "plan" in req:
            # plan-fragment shipping: execute a serialized UNRESOLVED
            # logical plan through the normal session pipeline — shapes
            # the single-block SQL renderer can't express run distributed
            # this way (ref: SparkSQLExecuteImpl.scala:75-109)
            from snappydata_tpu import resource
            from snappydata_tpu.sql import ast as _ast
            from snappydata_tpu.sql.plan_json import from_json

            sess = self._session_for(req)
            plan = from_json(req["plan"])
            ctx = self._deadline_ctx(req, sess, "<shipped plan>")
            from snappydata_tpu.observability import tracing

            # trace propagation: a traced caller's trace_id rides the
            # ticket like its deadline — this fragment's spans record
            # under the SAME id, joinable across the member rings
            with tracing.request_scope("<shipped plan>", user=sess.user,
                                       kind="server",
                                       trace_id=req.get("trace_id"),
                                       origin=self._origin()):
                if ctx is not None:
                    # propagated deadline: the caller's remaining budget
                    # — cooperative checks stop this fragment when the
                    # caller has already given up (its client-side
                    # cutoff fired)
                    ctx.start()
                    with resource.query_scope(ctx):
                        result = sess.execute_statement(
                            _ast.Query(plan),
                            tuple(req.get("params", ())))
                else:
                    result = sess.execute_statement(
                        _ast.Query(plan), tuple(req.get("params", ())))
            table = result_to_arrow(result)
            chunk = int(req.get("page_rows", 65536))
            batches = table.to_batches(max_chunksize=max(1, chunk))
            return flight.GeneratorStream(table.schema, iter(batches))
        if "scan_table" in req:
            # full-table export ticket: stream scan units without ever
            # materializing the table (peak memory = one column batch)
            sess = self._session_for(req)
            name = req["scan_table"]
            sess._require(name, "select")
            info = self.session.catalog.describe(name)
            fields = [pa.field(f.name, _arrow_type(f.dtype), f.nullable)
                      for f in info.schema.fields]
            schema = pa.schema(fields)

            def gen():
                for result in iter_table_chunks(sess, name):
                    tbl = result_to_arrow(result)
                    if tbl.schema != schema:
                        tbl = tbl.cast(schema)
                    yield from tbl.to_batches(max_chunksize=65536)

            return flight.GeneratorStream(schema, gen())
        sess = self._session_for(req)
        # scan-shaped queries (project/filter, no aggregate/sort)
        # stream per scan unit — peak host rows bounded by one column
        # batch even for a SELECT * over an oversized table.  This wins
        # over the `prepared` flag too: a full-table export must NEVER
        # materialize server-side just because the client asked for
        # serving-path routing (the serving registry targets small/point
        # results, not bulk scans)
        streamed = try_stream_scan(sess, req["sql"],
                                   tuple(req.get("params", ())),
                                   page_rows=int(req.get("page_rows",
                                                         65536)))
        if streamed is not None:
            schema, gen = streamed
            return flight.GeneratorStream(schema, gen())
        ctx = self._deadline_ctx(req, sess, req.get("sql", ""))
        from snappydata_tpu.observability import tracing

        # the server opens its own trace under the caller's trace_id
        # (or mints one for an untraced caller) BEFORE entering the
        # session, so session.sql's scope joins it instead of minting
        with tracing.request_scope(req.get("sql", ""), user=sess.user,
                                   kind="server",
                                   trace_id=req.get("trace_id"),
                                   origin=self._origin()):
            if req.get("prepared"):
                # serving front door: {"sql", "params", "prepared":
                # true} routes through the prepared-plan registry —
                # repeated tickets skip parse/plan, concurrent ones fuse
                # into one vmapped dispatch, the governor admits per
                # principal
                result = sess.serving_sql(req["sql"],
                                          tuple(req.get("params", ())),
                                          query_ctx=ctx)
            else:
                result = sess.sql(req["sql"],
                                  params=tuple(req.get("params", ())),
                                  query_ctx=ctx)
        table = result_to_arrow(result)
        # page as record batches (ref: CachedDataFrame paged collect /
        # GfxdHeapDataOutputStream result pages) — clients start consuming
        # before the last page is serialized
        chunk = int(req.get("page_rows", 65536))
        batches = table.to_batches(max_chunksize=max(1, chunk))
        return flight.GeneratorStream(table.schema, iter(batches))

    def get_flight_info(self, context, descriptor):
        from snappydata_tpu.cluster.flightsql import unpack_any

        fsql = unpack_any(descriptor.command) \
            if descriptor.command else None
        if fsql is not None:
            return self.flightsql.flight_info(context, descriptor,
                                              fsql[0], fsql[1])
        req = json.loads(descriptor.command.decode("utf-8"))
        # schema WITHOUT executing (ref: prepared-statement metadata phase,
        # SparkSQLPrepareImpl) — clients can plan on dtypes cheaply
        sess = self._session_for(req)
        schema = sess.query_schema(req["sql"])
        fields = [pa.field(f.name, _arrow_type(f.dtype), f.nullable)
                  for f in schema.fields]
        endpoint = flight.FlightEndpoint(
            descriptor.command, [flight.Location(self._location)])
        return flight.FlightInfo(pa.schema(fields), descriptor, [endpoint],
                                 -1, -1)

    # -- bulk ingest ------------------------------------------------------

    def do_put(self, context, descriptor, reader, writer):
        if descriptor.path:
            target, body = descriptor.path[0].decode("utf-8"), None
        else:
            from snappydata_tpu.cluster.flightsql import unpack_any

            fsql = unpack_any(descriptor.command)
            if fsql is not None:
                self.flightsql.do_put(context, fsql[0], fsql[1],
                                      reader, writer)
                return
            body = json.loads(descriptor.command.decode("utf-8"))
            target = body["table"]
        sess = self._session_for(body)   # raises if auth on and no token
        sess._require(target, "insert")
        from snappydata_tpu import reliability
        from snappydata_tpu.observability.metrics import global_registry

        stmt_id = (body or {}).get("stmt_id")
        dedup = reliability.dedup_for(self.session.catalog) \
            if stmt_id else None
        if dedup is not None and dedup.begin(stmt_id) is not None:
            # lost-ack retry: the first send applied (and fsynced — acks
            # gate on the covering WAL sync) but its response was lost.
            # Drain the stream and ack WITHOUT re-applying.
            reader.read_all()
            global_registry().inc("mutation_dedup_hits")
            return
        try:
            table = reader.read_all()
            arrays, nulls = arrow_to_arrays(table)
            info = self.session.catalog.describe(target)
            # same gate as every session write lane: acked rows put into
            # a view's backing table would vanish at the view's next sync
            self.session._reject_matview_write(info)
            from snappydata_tpu.storage.table_store import RowTableData

            # WAL-then-apply under the store's mutation lock (same
            # invariant as session mutations: journal first so a
            # concurrent checkpoint can't fold un-journaled rows, and
            # carry null masks so recovery doesn't turn bulk-ingested
            # NULLs into zeros). stmt_scope threads the client's
            # statement id into the WAL header — recovery replay re-seeds
            # the dedup window from it, so a retry racing a server
            # RESTART still dedups.
            # sync_force: the put RESPONSE is a durability ack the lead's
            # fan-out (and its replica bookkeeping) relies on — the
            # covering WAL fsync is forced even when this server runs
            # wal_fsync_mode=interval. Relaxed acks are a local-session
            # policy, never a network one. Scoped to THIS put's record so
            # one client's ack never waits on other sessions' records.
            from snappydata_tpu.observability import tracing

            with tracing.request_scope(
                    f"<put {target}>", user=sess.user, kind="server",
                    trace_id=(body or {}).get("trace_id"),
                    origin=self._origin()), \
                    reliability.stmt_scope(stmt_id):
                if isinstance(info.data, RowTableData):
                    from snappydata_tpu.session import _restore_none_arrays

                    raw = _restore_none_arrays(arrays, nulls)
                    n = self.session._journal_then(
                        info, "insert", raw, None,
                        lambda: self.session._fold_views(
                            info, raw, None, info.data.insert_arrays(raw)),
                        sync_force=True)
                else:
                    nmask = nulls if any(m is not None for m in nulls) \
                        else None
                    n = self.session._journal_then(
                        info, "insert", arrays, nmask,
                        lambda: self.session._fold_views(
                            info, arrays, nmask,
                            info.data.insert_arrays(arrays, nulls=nmask)),
                        sync_force=True)
        except BaseException:
            if dedup is not None:
                dedup.abort(stmt_id)   # nothing applied: a retry may run
            raise
        if dedup is not None:
            dedup.commit(stmt_id, {"rows": [[int(n or 0)]]})

    # -- ops --------------------------------------------------------------

    def do_action(self, context, action: flight.Action):
        name = action.type
        if name != "ping":
            # ping stays exempt: liveness probes must answer truthfully
            # or an injected app-level fault would masquerade as member
            # death and trigger a spurious failover
            from snappydata_tpu.fault import failpoints

            failpoints.hit("flight.serve")
        if name in ("CreatePreparedStatement", "ClosePreparedStatement"):
            from snappydata_tpu.cluster.flightsql import unpack_any

            fsql = unpack_any(action.body.to_pybytes()) \
                if action.body else None
            if fsql is not None:
                for out in self.flightsql.do_action(context, fsql[0],
                                                    fsql[1]):
                    yield flight.Result(out)
                return
        body = json.loads(action.body.to_pybytes().decode("utf-8")) \
            if action.body else {}
        if name == "sql":
            from snappydata_tpu import reliability
            from snappydata_tpu.observability.metrics import \
                global_registry

            sess = self._session_for(body)
            stmt_id = body.get("stmt_id")
            dedup = reliability.dedup_for(self.session.catalog) \
                if stmt_id else None
            if dedup is not None:
                prior = dedup.begin(stmt_id)
                if prior is not None:
                    # lost-ack retry of an applied mutation: return the
                    # recorded result, apply nothing
                    global_registry().inc("mutation_dedup_hits")
                    yield flight.Result(json.dumps(
                        dict(prior, deduped=True)).encode("utf-8"))
                    return
            try:
                ctx = self._deadline_ctx(body, sess, body["sql"])
                from snappydata_tpu.observability import tracing

                with tracing.request_scope(
                        body["sql"], user=sess.user, kind="server",
                        trace_id=body.get("trace_id"),
                        origin=self._origin()), \
                        reliability.stmt_scope(stmt_id):
                    result = sess.sql(
                        body["sql"], params=tuple(body.get("params", ())),
                        query_ctx=ctx)
                payload = {"names": result.names,
                           "rows": [[_json_val(v) for v in r]
                                    for r in result.rows()[:1000]]}
            except BaseException:
                if dedup is not None:
                    dedup.abort(stmt_id)
                raise
            if dedup is not None:
                dedup.commit(stmt_id, payload)
            yield flight.Result(json.dumps(payload).encode("utf-8"))
        elif name == "login":
            # credential → ephemeral session token (ref: per-connection
            # authentication in SecurityUtils; the token plays the role of
            # the authenticated connection)
            if self.auth_provider is None:
                raise flight.FlightUnauthenticatedError(
                    "no auth provider configured (login unavailable)")
            u, p = body.get("user"), body.get("password")
            if not u or not p or not self.auth_provider.authenticate(u, p):
                raise flight.FlightUnauthenticatedError(
                    "invalid credentials")
            import secrets
            import time as _t

            now = _t.time()
            tok = secrets.token_hex(16)
            with self._token_lock:
                # prune expired tokens so the table can't grow unbounded
                for stale in [t for t, (_, exp)
                              in self._issued_tokens.items() if exp <= now]:
                    self._issued_tokens.pop(stale, None)
                self._issued_tokens[tok] = (u, now + self.TOKEN_TTL_S)
            yield flight.Result(json.dumps(
                {"token": tok, "user": u}).encode("utf-8"))
        elif name == "checkpoint":
            sess = self._session_for(body)
            if self._auth_enabled() and sess.user != "admin":
                raise flight.FlightServerError("checkpoint requires admin")
            self.session.checkpoint()
            yield flight.Result(b"{}")
        elif name == "wal_sync":
            # cluster-wide durability barrier (DistributedSession
            # .flush_wals / REST POST /wal/flush): drain+fsync this
            # member's commit buffer past any relaxed interval-mode ack
            self._session_for(body)   # credential gate when auth on
            ds = self.session.disk_store
            if ds is not None:
                ds.wal_sync(force=True)
            yield flight.Result(json.dumps(
                {"durable": ds is not None}).encode("utf-8"))
        elif name == "catalog":
            # thin-client catalog protocol (ref: StoreHiveCatalog serving
            # getCatalogMetadata to connectors; SmartConnectorExternalCatalog
            # caches per catalog version and invalidates all entries on any
            # DDL): one round trip returns the FULL table/view inventory
            # plus the catalog generation the client caches against.
            self._session_for(body)   # catalog metadata: credential gate
            yield flight.Result(json.dumps(
                self._catalog_payload()).encode("utf-8"))
        elif name == "stats":
            self._session_for(body)  # catalog metadata: token when auth on
            from snappydata_tpu.observability import TableStatsService

            stats = TableStatsService(self.session.catalog).collect_once()
            yield flight.Result(json.dumps(stats).encode("utf-8"))
        elif name == "repartition":
            # Peer-to-peer hash-repartition (shuffle) exchange: THIS server
            # re-buckets its local shard of `table` by `key` and streams
            # each peer's sub-shard straight to that peer's `dest` table
            # over do_put — no lead-side materialization (ref: Spark
            # exchange fallback, SnappyStrategies.scala:80-128, re-shaped
            # as server-to-server Arrow Flight streams).
            sess = self._session_for(body)
            sess._require(body["table"], "select")
            n = self._repartition_shard(
                sess, body["table"], body["key"], body["dest"],
                body["servers"], int(body["num_buckets"]),
                self.internal_token or body.get("token"),
                body.get("bucket_owners"))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "promote":
            # failover re-hosting: replica-shadow rows of the given
            # buckets become primary rows on THIS server (ref: bucket
            # redundancy re-hosting on member departure)
            sess = self._session_for(body)
            moved = self._promote_replica(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]))
            yield flight.Result(json.dumps({"rows": moved}).encode("utf-8"))
        elif name == "replicate":
            # redundancy restoration: push THIS server's rows of the
            # given buckets into a peer's replica shadow (ref: bucket
            # redundancy recovery after re-hosting)
            sess = self._session_for(body)
            sess._require(body["table"], "select")
            n = self._replicate_buckets(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]),
                body["target"], self.internal_token or body.get("token"))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "purge_replica":
            # drop the given buckets' rows from the local shadow (makes
            # re-replication idempotent after a failed/rolled-back copy)
            sess = self._session_for(body)
            n = self._purge_replica(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "purge_buckets":
            # rejoin resync: journaled delete of the given buckets' rows
            # from the local PRIMARY copy (a restarted member's stale
            # rows of re-homed buckets must go before re-admission —
            # they would double-count under scatter otherwise)
            sess = self._session_for(body)
            n = self._purge_primary(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "demote":
            # rejoin zero-copy redundancy restore: the inverse of
            # promote — this server's PRIMARY rows of the given buckets
            # move into its local replica shadow, because the restarted
            # member's recovered copy (provably current by WAL-seq
            # watermark) is taking the primary role back
            sess = self._session_for(body)
            n = self._demote_to_replica(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "move_buckets":
            # rebalance data plane: copy this server's PRIMARY rows of
            # the given buckets to `target` and delete them locally (ref:
            # SYS.REBALANCE_ALL_BUCKETS, docs/reference/
            # inbuilt_system_procedures/rebalance-all-buckets.md)
            sess = self._session_for(body)
            sess._require(body["table"], "select")
            n = self._move_buckets(
                sess, body["table"], body["key"],
                frozenset(body["buckets"]), int(body["num_buckets"]),
                body["target"], self.internal_token or body.get("token"))
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "export":
            # streamed table export for broadcast exchanges: THIS server
            # pushes its local shard of `table` into `dest` on every
            # target, one scan unit at a time — the lead coordinates but
            # never holds data (replaces the round-3 gather-to-lead
            # broadcast; ref CachedDataFrame.scala:766 paged results)
            sess = self._session_for(body)
            sess._require(body["table"], "select")
            from snappydata_tpu.cluster.client import SnappyClient

            tok = self.internal_token or body.get("token")
            clients = [SnappyClient(address=a, token=tok)
                       for a in body["targets"]]
            n = 0
            try:
                for result in iter_table_chunks(sess, body["table"]):
                    piece = result_to_arrow(result)
                    for c in clients:
                        c.insert(body["dest"], piece)
                    n += result.num_rows
            finally:
                for c in clients:
                    c.close()
            yield flight.Result(json.dumps({"rows": n}).encode("utf-8"))
        elif name == "ping":
            yield flight.Result(b'{"ok": true}')
        else:
            raise flight.FlightServerError(f"unknown action {name}")

    def _catalog_payload(self) -> dict:
        """Serialize the catalog: table schemas + placement metadata +
        the generation DDL bumps (the connector's invalidation key)."""
        catalog = self.session.catalog
        tables = {}
        for info in catalog.list_tables():
            snap_rows = None
            try:
                snap_rows = int(info.data.snapshot().total_rows())
            except Exception:
                pass
            tables[info.name] = {
                "provider": info.provider,
                "columns": [{"name": f.name, "type": str(f.dtype),
                             "nullable": bool(f.nullable)}
                            for f in info.schema.fields],
                "key_columns": list(info.key_columns),
                "partition_by": list(info.partition_by),
                "buckets": info.buckets,
                "colocate_with": info.colocate_with,
                "redundancy": info.redundancy,
                "base_table": info.base_table,
                "row_count": snap_rows,
            }
        return {"generation": catalog.generation,
                "tables": tables,
                "views": sorted(catalog._views.keys())}

    def _repartition_shard(self, sess, table: str, key: str, dest: str,
                           servers, num_buckets: int,
                           token: Optional[str],
                           bucket_owners=None) -> int:
        """Stream the local shard one scan unit at a time, bucket each
        chunk by murmur3(key) (the SAME placement the lead's insert
        routing uses — an explicit bucket→server map when given, so
        re-bucketed rows land exactly where a direct insert would even
        after failovers), and push each peer its sub-shard per chunk —
        peak host memory is ONE column batch, not the whole shard (ref:
        SparkSQLExecuteImpl.packRows:109 paged streaming; round-3 verdict
        Weak #5)."""
        from snappydata_tpu.cluster.client import SnappyClient
        from snappydata_tpu.parallel.hashing import bucket_of_np

        clients: dict = {}
        sent = 0
        try:
            for result in iter_table_chunks(sess, table):
                ki = [c.lower() for c in result.names].index(key.lower())
                buckets = bucket_of_np(np.asarray(result.columns[ki]),
                                       num_buckets)
                if bucket_owners is not None:
                    owner = np.asarray(bucket_owners,
                                       dtype=np.int64)[buckets]
                else:
                    owner = buckets % len(servers)
                for si, addr in enumerate(servers):
                    mask = owner == si
                    if not mask.any():
                        continue
                    piece = result_to_arrow(result, sel=mask)
                    if si not in clients:
                        clients[si] = SnappyClient(address=addr,
                                                   token=token)
                    clients[si].insert(dest, piece)
                    sent += int(mask.sum())
        finally:
            for c in clients.values():
                c.close()
        return sent

    @staticmethod
    def _bucket_rows(sess, table: str, key: str, buckets: frozenset,
                     num_buckets: int):
        """Scan `table` and select the rows belonging to `buckets`.
        Returns (result, bool row mask) — mask is None when empty."""
        from snappydata_tpu.parallel.hashing import bucket_of_np

        result = sess.sql(f"SELECT * FROM {table}")
        n = int(result.columns[0].shape[0]) if result.columns else 0
        if n == 0:
            return result, None
        ki = [c.lower() for c in result.names].index(key.lower())
        rb = bucket_of_np(np.asarray(result.columns[ki]), num_buckets)
        mask = np.isin(rb, np.fromiter(buckets, dtype=np.int64))
        return result, (mask if mask.any() else None)

    def _promote_replica(self, sess, table: str, key: str,
                         buckets: frozenset, num_buckets: int) -> int:
        """Move rows of `buckets` from <table>__replica into <table> and
        drop them from the shadow (their old primary died)."""
        replica = f"{table}__replica"
        result, mask = self._bucket_rows(sess, replica, key, buckets,
                                         num_buckets)
        if mask is None:
            return 0
        moved = int(mask.sum())
        from snappydata_tpu.storage.table_store import RowTableData

        info = self.session.catalog.describe(table)
        self.session._reject_matview_write(info)  # views have no replicas
        arrays = [np.asarray(c)[mask] for c in result.columns]
        nulls = [np.asarray(nm)[mask] if nm is not None else None
                 for nm in result.nulls]
        nmask = nulls if any(m is not None for m in nulls) else None
        # sync_force: the promotion is a network-level ack to the lead's
        # failover bookkeeping AND the shadow rows are deleted right
        # below — the covering fsync must land BEFORE the only other
        # copy goes away, even under wal_fsync_mode=interval
        if isinstance(info.data, RowTableData):
            from snappydata_tpu.session import _restore_none_arrays

            raw = _restore_none_arrays(arrays, nulls)
            self.session._journal_then(
                info, "insert", raw, None,
                lambda: self.session._fold_views(
                    info, raw, None, info.data.insert_arrays(raw)),
                sync_force=True)
        else:
            self.session._journal_then(
                info, "insert", arrays, nmask,
                lambda: self.session._fold_views(
                    info, arrays, nmask,
                    info.data.insert_arrays(arrays, nulls=nmask)),
                sync_force=True)
        # remove promoted rows from the shadow so a LATER promotion of
        # other buckets can't double-promote these
        from snappydata_tpu.parallel.hashing import bucket_of_np

        rinfo = self.session.catalog.describe(replica)

        def pred(cols, _k=key.lower(), _bk=buckets, _nb=num_buckets):
            vals = np.asarray(cols[_k])
            return np.isin(bucket_of_np(vals, _nb),
                           np.fromiter(_bk, dtype=np.int64))

        rinfo.data.delete(pred)
        return moved

    def _replicate_buckets(self, sess, table: str, key: str,
                           buckets: frozenset, num_buckets: int,
                           target: str, token: Optional[str]) -> int:
        """Copy this server's current rows of `buckets` into `target`'s
        <table>__replica shadow. The target PURGES those buckets from its
        shadow first, so a retried/rolled-back restoration never leaves
        duplicate shadow rows."""
        from snappydata_tpu.cluster.client import SnappyClient

        result, mask = self._bucket_rows(sess, table, key, buckets,
                                         num_buckets)
        if mask is None:
            return 0
        piece = result_to_arrow(result, sel=mask)
        client = SnappyClient(address=target, token=token)
        try:
            client.purge_replica({"table": table, "key": key,
                                  "buckets": sorted(buckets),
                                  "num_buckets": num_buckets})
            client.insert(f"{table}__replica", piece)
        finally:
            client.close()
        return int(mask.sum())


    def _move_buckets(self, sess, table: str, key: str,
                      buckets: frozenset, num_buckets: int,
                      target: str, token: Optional[str]) -> int:
        """Copy the local PRIMARY rows of `buckets` to `target`'s primary
        and delete them here (journaled). Copy-then-delete: a crash
        between the two leaves the bucket duplicated, which a re-run of
        the rebalance repairs (the reference's rebalance is likewise
        restartable) — delete-then-copy would instead LOSE rows."""
        from snappydata_tpu.cluster.client import SnappyClient

        result, mask = self._bucket_rows(sess, table, key, buckets,
                                         num_buckets)
        if mask is None:
            return 0
        piece = result_to_arrow(result, sel=mask)
        client = SnappyClient(address=target, token=token)
        try:
            client.insert(table, piece)
        finally:
            client.close()
        # journaled local delete: rows with the moved partition-key
        # values ARE exactly the moved buckets' rows (equal values share
        # a bucket), and delete_keys WALs the operation for recovery
        ki = [c.lower() for c in result.names].index(key.lower())
        moved_vals = np.asarray(result.columns[ki])[mask]
        self.session.delete_keys(table, [key.lower()],
                                 [np.unique(moved_vals)])
        return int(mask.sum())

    def _purge_primary(self, sess, table: str, key: str,
                       buckets: frozenset, num_buckets: int) -> int:
        """Journaled delete of `buckets` rows from the local primary copy
        (delete_keys WALs the operation — recovery must never resurrect
        rows the rejoin resync removed)."""
        result, mask = self._bucket_rows(sess, table, key, buckets,
                                         num_buckets)
        if mask is None:
            return 0
        ki = [c.lower() for c in result.names].index(key.lower())
        vals = np.asarray(result.columns[ki])[mask]
        self.session.delete_keys(table, [key.lower()], [np.unique(vals)])
        return int(mask.sum())

    def _demote_to_replica(self, sess, table: str, key: str,
                           buckets: frozenset, num_buckets: int) -> int:
        """Move local PRIMARY rows of `buckets` into the local replica
        shadow: purge the shadow's slice of those buckets first (a
        crashed earlier demote may have left its copy — re-running must
        not duplicate it), then copy-into-shadow (journaled,
        fsync-forced — the shadow row must be durable before the
        primary copy goes away), then a journaled delete of the primary
        rows. A crash mid-sequence leaves the bucket in BOTH places
        (the shadow is invisible to queries and the next run's purge
        repairs it) — never in neither."""
        result, mask = self._bucket_rows(sess, table, key, buckets,
                                         num_buckets)
        if mask is None:
            return 0
        self._purge_replica(sess, table, key, buckets, num_buckets)
        replica = f"{table}__replica"
        rinfo = self.session.catalog.describe(replica)
        arrays = [np.asarray(c)[mask] for c in result.columns]
        nulls = [np.asarray(nm)[mask] if nm is not None else None
                 for nm in result.nulls]
        nmask = nulls if any(m is not None for m in nulls) else None
        self.session._journal_then(
            rinfo, "insert", arrays, nmask,
            lambda: rinfo.data.insert_arrays(arrays, nulls=nmask),
            sync_force=True)
        ki = [c.lower() for c in result.names].index(key.lower())
        vals = np.asarray(result.columns[ki])[mask]
        self.session.delete_keys(table, [key.lower()], [np.unique(vals)])
        return int(mask.sum())

    def _purge_replica(self, sess, table: str, key: str,
                       buckets: frozenset, num_buckets: int) -> int:
        from snappydata_tpu.parallel.hashing import bucket_of_np

        rinfo = self.session.catalog.lookup_table(f"{table}__replica")
        if rinfo is None:
            return 0

        def pred(cols, _k=key.lower(), _bk=buckets, _nb=num_buckets):
            vals = np.asarray(cols[_k])
            return np.isin(bucket_of_np(vals, _nb),
                           np.fromiter(_bk, dtype=np.int64))

        return rinfo.data.delete(pred)

    def list_actions(self, context):
        return [("sql", "execute a statement"),
                ("checkpoint", "persist all tables"),
                ("stats", "table stats"), ("ping", "liveness")]


def _json_val(v):
    if v is None or isinstance(v, (int, float, str, bool)):
        return v
    return str(v)


def _arrow_type(dt) -> pa.DataType:
    if dt.name == "string":
        return pa.string()
    if dt.name == "decimal":
        # the BI/JDBC contract: real decimal128 on the wire, matching
        # result_to_arrow's arrays (a float64 mapping here made
        # schema-casts silently downcast streamed decimal columns)
        return pa.decimal128(max(1, dt.precision), dt.scale)
    if dt.name in ("array", "map", "struct"):
        return pa.string()  # complex values ride JSON-encoded
    try:
        return pa.from_numpy_dtype(np.dtype(dt.np_dtype))
    except (pa.ArrowNotImplementedError, TypeError):
        return pa.string()
