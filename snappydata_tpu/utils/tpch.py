"""TPC-H-shaped data generator (statistical, not spec-dbgen) + query text.

Used by the correctness tests and bench.py, mirroring the reference's
in-tree TPC-H harness (cluster/src/test/scala/io/snappydata/benchmark/
TPCH_Queries.scala, TPCHColumnPartitionedTable.scala): lineitem/orders/
customer with the columns, domains and correlations the headline queries
(Q1/Q3/Q6) touch.
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np

_EPOCH = datetime.date(1970, 1, 1)


def _days(iso: str) -> int:
    return (datetime.date.fromisoformat(iso) - _EPOCH).days


LINEITEM_ROWS_PER_SF = 6_000_000
ORDERS_ROWS_PER_SF = 1_500_000
CUSTOMER_ROWS_PER_SF = 150_000

RETURNFLAGS = np.array(["A", "N", "R"], dtype=object)
LINESTATUS = np.array(["F", "O"], dtype=object)
SEGMENTS = np.array(["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
                     "MACHINERY"], dtype=object)
SHIPMODES = np.array(["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP",
                      "TRUCK"], dtype=object)


def gen_lineitem(num_rows: int, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    orderkey = rng.integers(1, max(2, num_rows // 4), num_rows,
                            dtype=np.int64)
    ship = rng.integers(_days("1992-01-02"), _days("1998-12-01"), num_rows,
                        dtype=np.int32)
    qty = rng.integers(1, 51, num_rows).astype(np.float64)
    price = np.round(rng.uniform(900.0, 105_000.0, num_rows), 2)
    disc = np.round(rng.integers(0, 11, num_rows) * 0.01, 2)
    tax = np.round(rng.integers(0, 9, num_rows) * 0.01, 2)
    # linestatus correlates with shipdate in real dbgen (O after 1995-06)
    status = np.where(ship > _days("1995-06-17"), "O", "F").astype(object)
    flag = RETURNFLAGS[rng.integers(0, 3, num_rows)]
    flag[status == "O"] = "N"
    return {
        "l_orderkey": orderkey,
        "l_partkey": rng.integers(1, 200_000, num_rows, dtype=np.int64),
        "l_suppkey": rng.integers(1, 10_000, num_rows, dtype=np.int64),
        "l_linenumber": rng.integers(1, 8, num_rows).astype(np.int32),
        "l_quantity": qty,
        "l_extendedprice": price,
        "l_discount": disc,
        "l_tax": tax,
        "l_returnflag": flag,
        "l_linestatus": status,
        "l_shipdate": ship,
        "l_commitdate": ship + rng.integers(-30, 30, num_rows,
                                            dtype=np.int32),
        "l_receiptdate": ship + rng.integers(1, 30, num_rows,
                                             dtype=np.int32),
        "l_shipmode": SHIPMODES[rng.integers(0, len(SHIPMODES), num_rows)],
    }


def gen_orders(num_rows: int, num_customers: int, seed: int = 1
               ) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "o_orderkey": np.arange(1, num_rows + 1, dtype=np.int64),
        "o_custkey": rng.integers(1, max(2, num_customers + 1), num_rows,
                                  dtype=np.int64),
        "o_orderstatus": np.array(["F", "O", "P"], dtype=object)[
            rng.integers(0, 3, num_rows)],
        "o_totalprice": np.round(rng.uniform(850.0, 560_000.0, num_rows), 2),
        "o_orderdate": rng.integers(_days("1992-01-01"), _days("1998-08-02"),
                                    num_rows, dtype=np.int32),
        "o_orderpriority": np.array(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"],
            dtype=object)[rng.integers(0, 5, num_rows)],
        "o_shippriority": np.zeros(num_rows, dtype=np.int32),
    }


def gen_customer(num_rows: int, seed: int = 2) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    return {
        "c_custkey": np.arange(1, num_rows + 1, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in
                            range(1, num_rows + 1)], dtype=object),
        "c_nationkey": rng.integers(0, 25, num_rows, dtype=np.int32),
        "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, num_rows), 2),
        "c_mktsegment": SEGMENTS[rng.integers(0, len(SEGMENTS), num_rows)],
    }


LINEITEM_DDL = """CREATE TABLE lineitem (
    l_orderkey BIGINT, l_partkey BIGINT, l_suppkey BIGINT,
    l_linenumber INT, l_quantity DOUBLE, l_extendedprice DOUBLE,
    l_discount DOUBLE, l_tax DOUBLE, l_returnflag STRING,
    l_linestatus STRING, l_shipdate DATE, l_commitdate DATE,
    l_receiptdate DATE, l_shipmode STRING
) USING column OPTIONS (partition_by 'l_orderkey')"""

ORDERS_DDL = """CREATE TABLE orders (
    o_orderkey BIGINT, o_custkey BIGINT, o_orderstatus STRING,
    o_totalprice DOUBLE, o_orderdate DATE, o_orderpriority STRING,
    o_shippriority INT
) USING column OPTIONS (partition_by 'o_orderkey', colocate_with 'lineitem')"""

CUSTOMER_DDL = """CREATE TABLE customer (
    c_custkey BIGINT, c_name STRING, c_nationkey INT, c_acctbal DOUBLE,
    c_mktsegment STRING
) USING column OPTIONS (partition_by 'c_custkey')"""

Q1 = """SELECT l_returnflag, l_linestatus,
    sum(l_quantity) AS sum_qty,
    sum(l_extendedprice) AS sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
    avg(l_quantity) AS avg_qty,
    avg(l_extendedprice) AS avg_price,
    avg(l_discount) AS avg_disc,
    count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""

Q6 = """SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24"""

Q3 = """SELECT l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) AS revenue,
    o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10"""


def load_tpch(session, sf: float = 0.001, seed: int = 0) -> None:
    """Create + populate the three tables at the given scale factor."""
    n_l = max(1000, int(LINEITEM_ROWS_PER_SF * sf))
    n_o = max(250, int(ORDERS_ROWS_PER_SF * sf))
    n_c = max(25, int(CUSTOMER_ROWS_PER_SF * sf))
    session.sql(LINEITEM_DDL)
    session.sql(ORDERS_DDL)
    session.sql(CUSTOMER_DDL)
    li = gen_lineitem(n_l, seed)
    li["l_orderkey"] = np.minimum(li["l_orderkey"], n_o)  # FK into orders
    session.insert_arrays("lineitem", list(li.values()))
    session.insert_arrays("orders",
                          list(gen_orders(n_o, n_c, seed + 1).values()))
    session.insert_arrays("customer", list(gen_customer(n_c, seed + 2).values()))
