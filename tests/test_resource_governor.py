"""Resource governor (resource/): unified ledger, admission control
(admit/queue/reject with LowMemoryException SQLSTATE XCL54), graceful
degradation, and cooperative cancellation (CANCEL / statement timeout /
REST, SQLSTATE XCL52) stopping a tiled scan at a tile boundary.

Ref: SnappyUnifiedMemoryManager admission + critical-heap-percentage
fail-fast (SnappyUnifiedMemoryManager.scala:379-401) and the
CancelException checks in the reference's generated scan loops.
"""

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config, resource
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry


@pytest.fixture()
def props():
    """Governor knobs live on the GLOBAL properties (the broker is
    process-wide, like the reference's per-JVM memory manager) — restore
    everything this file touches."""
    p = config.global_properties()
    saved = (p.memory_limit_bytes, p.admission_queue_depth,
             p.admission_wait_s, p.admission_slots_per_user,
             p.query_timeout_s, p.scan_tile_bytes)
    yield p
    (p.memory_limit_bytes, p.admission_queue_depth, p.admission_wait_s,
     p.admission_slots_per_user, p.query_timeout_s,
     p.scan_tile_bytes) = saved


@pytest.fixture()
def session(props):
    s = SnappySession(catalog=Catalog())
    yield s
    s.stop()


def _tiled_table(session, name="rg_t", batches=8, cap=64):
    """A column table cut into `batches` batches plus a tiny tile budget
    so aggregates stream tile by tile (each tile = one cancel point)."""
    session.sql(f"CREATE TABLE {name} (v DOUBLE) USING column OPTIONS "
                f"(column_batch_rows '{cap}', "
                f"column_max_delta_rows '{cap}')")
    n = batches * cap
    session.insert_arrays(name, [np.arange(n, dtype=np.float64)])
    # one unit per tile: unit_bytes = cap (mask) + cap*(8+1) (v column)
    session.conf.scan_tile_bytes = cap * 10 + 1
    return float(np.arange(n, dtype=np.float64).sum())


@contextlib.contextmanager
def _slow_tiles(monkeypatch, delay_s=0.05):
    """Make every scan tile take >= delay_s so signals land mid-scan."""
    import snappydata_tpu.storage.device as device_mod

    orig = device_mod.scan_window

    @contextlib.contextmanager
    def slow_window(data, lo, hi, manifest=None, **kw):
        time.sleep(delay_s)
        with orig(data, lo, hi, manifest, **kw):
            yield

    monkeypatch.setattr(device_mod, "scan_window", slow_window)
    yield


# ---------------------------------------------------------------------
# admission: admit / reject / queue / fair slots
# ---------------------------------------------------------------------

def test_estimate_scales_with_rows(session):
    from snappydata_tpu.sql.parser import parse

    session.sql("CREATE TABLE est_t (a BIGINT, s STRING) USING column")
    stmt = parse("SELECT count(*) FROM est_t")
    assert resource.estimate_statement_bytes(session.catalog, stmt) == 0
    session.insert_arrays("est_t", [
        np.arange(100, dtype=np.int64),
        np.array(["x"] * 100, dtype=object)])
    e100 = resource.estimate_statement_bytes(session.catalog, stmt)
    # 100 rows x (8 int64 + 4 code + 2 validity) = 1400
    assert e100 == 100 * 14
    session.insert_arrays("est_t", [
        np.arange(100, dtype=np.int64),
        np.array(["x"] * 100, dtype=object)])
    assert resource.estimate_statement_bytes(session.catalog, stmt) \
        == 2 * e100


def test_oversize_query_rejected_with_sqlstate(session, props):
    session.sql("CREATE TABLE rej_t (v DOUBLE) USING column")
    session.insert_arrays("rej_t", [np.ones(1000)])
    props.memory_limit_bytes = 64          # deliberately tiny
    before = global_registry().counter("governor_rejected")
    with pytest.raises(resource.LowMemoryException) as ei:
        session.sql("SELECT sum(v) FROM rej_t")
    assert "XCL54" in str(ei.value)
    assert global_registry().counter("governor_rejected") == before + 1
    # reads that fit still run: the governor rejects work, not the node
    props.memory_limit_bytes = 10 ** 9
    assert session.sql("SELECT sum(v) FROM rej_t").rows()[0][0] == 1000.0


def test_queue_full_rejects(props):
    props.memory_limit_bytes = 1000
    props.admission_queue_depth = 0
    broker = resource.global_broker()
    blocker = resource.new_query("blocker", "admin")
    broker.admit(blocker, estimate_bytes=900)
    try:
        with pytest.raises(resource.LowMemoryException) as ei:
            broker.admit(resource.new_query("q2", "admin"),
                         estimate_bytes=500)
        assert "queue full" in str(ei.value)
    finally:
        broker.release(blocker)


def test_queued_query_runs_after_blocker_finishes(props):
    props.memory_limit_bytes = 1000
    props.admission_queue_depth = 4
    props.admission_wait_s = 10.0
    broker = resource.global_broker()
    blocker = resource.new_query("blocker", "admin")
    broker.admit(blocker, estimate_bytes=800)
    queued_before = global_registry().counter("governor_queued")
    done = []

    def second():
        ctx = resource.new_query("q2", "admin")
        broker.admit(ctx, estimate_bytes=500)
        done.append(ctx)
        broker.release(ctx)

    t = threading.Thread(target=second, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while global_registry().counter("governor_queued") == queued_before \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert global_registry().counter("governor_queued") == queued_before + 1
    assert not done                      # still blocked
    assert any(q["state"] == "queued" for q in broker.queries())
    broker.release(blocker)              # blocker finishes ...
    t.join(5)
    assert done and done[0].state == "finished"   # ... queued query ran


def test_admission_wait_timeout_rejects(props):
    props.memory_limit_bytes = 1000
    props.admission_queue_depth = 4
    props.admission_wait_s = 0.2
    broker = resource.global_broker()
    blocker = resource.new_query("blocker", "admin")
    broker.admit(blocker, estimate_bytes=1000)
    try:
        with pytest.raises(resource.LowMemoryException) as ei:
            broker.admit(resource.new_query("q2", "admin"),
                         estimate_bytes=500)
        assert "XCL54" in str(ei.value)
    finally:
        broker.release(blocker)


def test_statement_timeout_covers_queue_time(props):
    """query_timeout_s starts at SUBMISSION: a query that times out
    while queued surfaces as CancelException XCL52 (a timeout), not a
    LowMemoryException memory rejection — and the deadline is not
    re-armed at admission."""
    props.memory_limit_bytes = 1000
    props.admission_queue_depth = 4
    props.admission_wait_s = 30.0
    broker = resource.global_broker()
    blocker = resource.new_query("blocker", "admin")
    broker.admit(blocker, estimate_bytes=1000)
    timeouts_before = global_registry().counter("governor_timeouts")
    try:
        with pytest.raises(resource.CancelException) as ei:
            broker.admit(resource.new_query("q2", "admin"),
                         estimate_bytes=500, timeout_s=0.15)
        assert "XCL52" in str(ei.value)
        assert global_registry().counter("governor_timeouts") \
            == timeouts_before + 1
        # and when admission DOES succeed, the deadline still counts
        # from submission (not re-armed by start())
        q3 = resource.new_query("q3", "admin")
        broker.release(blocker)
        t0 = time.monotonic()
        broker.admit(q3, estimate_bytes=100, timeout_s=5.0)
        try:
            assert q3.deadline is not None
            # generous slack: a scheduling hiccup between t0 and the
            # deadline arming flaked the 0.1s bound on a loaded box; a
            # re-armed deadline would still blow well past this
            assert q3.deadline - t0 <= 5.0 + 1.0
        finally:
            broker.release(q3)
    finally:
        broker.release(blocker)


def test_fair_slot_head_does_not_starve_other_users(props):
    """A queue head blocked purely by its principal's fair slot must not
    block another user's admissible query (head-of-line)."""
    props.memory_limit_bytes = 10 ** 9
    props.admission_slots_per_user = 1
    props.admission_wait_s = 10.0
    broker = resource.global_broker()
    a1 = resource.new_query("a1", "alice")
    broker.admit(a1, estimate_bytes=10)
    blocked = []

    def alices_second():
        a2 = resource.new_query("a2", "alice")
        broker.admit(a2, estimate_bytes=10)    # slot-blocked: queues
        blocked.append(a2)
        broker.release(a2)

    t = threading.Thread(target=alices_second, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while not any(q["state"] == "queued" for q in broker.queries()) \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    # bob sails past alice's slot-blocked queue head
    b1 = resource.new_query("b1", "bob")
    broker.admit(b1, estimate_bytes=10, timeout_s=2.0)
    assert b1.state == "running"
    broker.release(b1)
    assert not blocked                          # alice's a2 still waits
    broker.release(a1)
    t.join(5)
    assert blocked


def test_watched_job_cancellable_before_admission(props):
    """A jobserver-submitted context is visible and cancellable from the
    moment of submission; a cancel landing before admit() makes admit
    raise CancelException instead of 404-ing."""
    broker = resource.global_broker()
    ctx = broker.watch(resource.new_query("pending job", "admin"))
    try:
        assert any(q["id"] == ctx.query_id for q in broker.queries())
        assert broker.cancel(ctx.query_id, "cancelled pre-admission")
        with pytest.raises(resource.CancelException):
            broker.admit(ctx, estimate_bytes=0)
    finally:
        broker.release(ctx)
    assert all(q["id"] != ctx.query_id for q in broker.queries())


def test_row_tables_visible_to_ledger_and_estimate(session):
    from snappydata_tpu.sql.parser import parse

    session.sql("CREATE TABLE rg_row (k BIGINT PRIMARY KEY, v DOUBLE) "
                "USING row")
    session.insert_arrays("rg_row", [np.arange(500, dtype=np.int64),
                                     np.ones(500)])
    stmt = parse("SELECT sum(v) FROM rg_row")
    # 500 rows x (8+1 + 8+1) decoded width
    assert resource.estimate_statement_bytes(session.catalog, stmt) \
        == 500 * 18
    led = resource.global_broker().ledger()
    assert led["host"].get("rg_row", 0) == 500 * 18


def test_per_principal_fair_slots(props):
    props.memory_limit_bytes = 10 ** 9
    props.admission_slots_per_user = 1
    props.admission_wait_s = 10.0
    broker = resource.global_broker()
    q1 = resource.new_query("q1", "alice")
    broker.admit(q1, estimate_bytes=10)
    got = []

    def second():
        q2 = resource.new_query("q2", "alice")
        broker.admit(q2, estimate_bytes=10)
        got.append(q2)
        broker.release(q2)

    t = threading.Thread(target=second, daemon=True)
    t.start()
    time.sleep(0.15)
    assert not got                       # alice's second query waits
    # a DIFFERENT principal is not starved by alice's slot
    q3 = resource.new_query("q3", "bob")
    # (bob joins the FIFO behind alice's q2 — admit him after q1 frees)
    broker.release(q1)
    t.join(5)
    assert got
    broker.admit(q3, estimate_bytes=10)
    broker.release(q3)


# ---------------------------------------------------------------------
# cooperative cancellation: CANCEL / timeout, mid-scan
# ---------------------------------------------------------------------

def test_cancel_stops_scan_at_tile_boundary(session, props, monkeypatch):
    total_tiles = 8
    _tiled_table(session, "rg_c", batches=total_tiles)
    broker = resource.global_broker()
    errs = []
    t0 = global_registry().counter("scan_tiles")

    def run():
        try:
            session.sql("SELECT sum(v) FROM rg_c")
            errs.append(None)
        except Exception as e:          # noqa: BLE001
            errs.append(e)

    with _slow_tiles(monkeypatch, 0.05):
        th = threading.Thread(target=run, daemon=True)
        th.start()
        qid = None
        deadline = time.monotonic() + 5
        while qid is None and time.monotonic() < deadline:
            live = [q for q in broker.queries() if "rg_c" in q["sql"]]
            if live:
                qid = live[0]["id"]
            else:
                time.sleep(0.005)
        assert qid is not None
        cancelled_before = global_registry().counter("governor_cancelled")
        assert broker.cancel(qid, "cancelled by test")
        th.join(10)
    assert isinstance(errs[0], resource.CancelException)
    assert "XCL52" in str(errs[0])
    # stopped at a tile boundary, not after scanning everything
    assert global_registry().counter("scan_tiles") - t0 < total_tiles
    assert global_registry().counter("governor_cancelled") \
        == cancelled_before + 1
    assert all(q["id"] != qid for q in broker.queries())  # deregistered


def test_statement_timeout_cancels_mid_scan(session, props, monkeypatch):
    total_tiles = 8
    _tiled_table(session, "rg_to", batches=total_tiles)
    session.conf.query_timeout_s = 0.12   # ~2 tiles at 0.05s/tile
    t0 = global_registry().counter("scan_tiles")
    timeouts_before = global_registry().counter("governor_timeouts")
    with _slow_tiles(monkeypatch, 0.05):
        with pytest.raises(resource.CancelException) as ei:
            session.sql("SELECT sum(v) FROM rg_to")
    assert "XCL52" in str(ei.value)
    assert global_registry().counter("scan_tiles") - t0 < total_tiles
    assert global_registry().counter("governor_timeouts") \
        == timeouts_before + 1
    # and with the timeout off the same query completes
    session.conf.query_timeout_s = 0.0
    assert session.sql("SELECT count(*) FROM rg_to").rows()[0][0] == 8 * 64


def test_set_knobs_via_sql(session, props):
    session.sql("SET snappydata.query_timeout_s = 2.5")
    assert session.conf.query_timeout_s == 2.5
    session.sql("SET snappydata.memory_limit_bytes = 1048576")
    assert props.memory_limit_bytes == 1048576
    props.memory_limit_bytes = 0
    session.conf.query_timeout_s = 0.0


# ---------------------------------------------------------------------
# ledger + degradation
# ---------------------------------------------------------------------

def test_ledger_unifies_host_and_device_bytes(session):
    session.sql("CREATE TABLE rg_l (a BIGINT, v DOUBLE) USING column "
                "OPTIONS (column_batch_rows '128', "
                "column_max_delta_rows '128')")
    session.insert_arrays("rg_l", [np.arange(512, dtype=np.int64),
                                   np.ones(512)])
    session.sql("SELECT sum(v) FROM rg_l")   # populates device cache
    led = resource.global_broker().ledger()
    assert led["host"].get("rg_l", 0) > 0          # encoded batches
    assert led["device"].get("rg_l", 0) > 0        # cached plates
    assert led["host_total"] >= led["host"]["rg_l"]
    assert led["device_total"] >= led["device"]["rg_l"]
    snap = global_registry().snapshot()
    assert snap["gauges"]["governor_host_bytes"] >= led["host"]["rg_l"]


def test_tiled_aggregate_admitted_under_small_limit(session, props):
    """A table whose decoded size exceeds memory_limit_bytes must still
    be queryable when scan_tile_bytes streams it tile-by-tile: the
    admission estimate is the PEAK (one tile), not the full table —
    otherwise the governor forbids exactly the out-of-core workloads
    the tile pass exists for."""
    exact = _tiled_table(session, "rg_ooc", batches=8, cap=64)
    # full decoded estimate: 512 rows x 9B = 4608 > limit; tile: 641
    props.memory_limit_bytes = 2000
    got = session.sql("SELECT sum(v) FROM rg_ooc").rows()[0][0]
    assert got == exact
    # a NON-tilable query over the same table still rejects
    with pytest.raises(resource.LowMemoryException):
        session.sql("SELECT v FROM rg_ooc ORDER BY v")


def test_dropped_table_leaves_ledger(session):
    session.sql("CREATE TABLE rg_drop (v DOUBLE) USING column")
    session.insert_arrays("rg_drop", [np.ones(100)])
    broker = resource.global_broker()
    assert broker.ledger()["host"].get("rg_drop", 0) > 0
    # a plan-cache entry holds the data object alive past the DROP
    session.sql("SELECT sum(v) FROM rg_drop")
    session.sql("DROP TABLE rg_drop")
    assert "rg_drop" not in broker.ledger()["host"]


def test_row_table_updates_do_not_double_ledger_charge(session):
    session.sql("CREATE TABLE rg_upd (k BIGINT PRIMARY KEY, v DOUBLE) "
                "USING row")
    session.insert_arrays("rg_upd", [np.arange(100, dtype=np.int64),
                                     np.ones(100)])
    before = resource.global_broker().ledger()["host"]["rg_upd"]
    session.sql("UPDATE rg_upd SET v = 2.0")   # tombstones 100 old slots
    after = resource.global_broker().ledger()["host"]["rg_upd"]
    assert after == before                      # live rows, not slots


def test_degradation_order_evict_spill_cancel(session):
    broker = resource.global_broker()
    session.sql("CREATE TABLE rg_d (v DOUBLE) USING column OPTIONS "
                "(column_batch_rows '64', column_max_delta_rows '64')")
    session.insert_arrays("rg_d", [np.ones(256)])
    session.sql("SELECT sum(v) FROM rg_d")       # warm the plan cache
    assert session.executor._plan_cache
    victim = resource.new_query("hungry", "admin")
    broker.admit(victim, estimate_bytes=10 ** 6)
    spilled_before = (global_registry().counter("host_batches_spilled")
                      + global_registry().counter("tier_demotions_host"))
    try:
        # impossible target: -1, since the tier ladder can now demote
        # EVERY resident byte to disk-backed forms and actually reach 0
        broker._degrade(-1)
        # 1) plan caches dropped
        assert not session.executor._plan_cache
        # 2) cold batches spilled to disk — the tier ladder's host→disk
        # rung (CRC-framed tier files) runs before the hoststore spill
        # and usually leaves it nothing resident to take
        assert (global_registry().counter("host_batches_spilled")
                + global_registry().counter("tier_demotions_host")) \
            > spilled_before
        # 3) hungriest admitted query cancelled
        assert victim.cancelled
        assert "low memory" in victim.cancel_reason
    finally:
        broker.release(victim)
    # the spilled table still answers queries (memmap reload)
    assert session.sql("SELECT count(*) FROM rg_d").rows()[0][0] == 256


# ---------------------------------------------------------------------
# REST surface + jobserver registry
# ---------------------------------------------------------------------

@pytest.fixture()
def rest(session):
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    svc = RestService(session,
                      TableStatsService(session.catalog)).start()
    yield svc
    svc.stop()


def _get(svc, path):
    with urllib.request.urlopen(
            f"http://{svc.host}:{svc.port}{path}") as r:
        return json.loads(r.read())


def _post(svc, path, body=b"{}"):
    req = urllib.request.Request(
        f"http://{svc.host}:{svc.port}{path}", data=body, method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_queries_and_cancel(session, props, rest, monkeypatch):
    _tiled_table(session, "rg_r", batches=8)
    with _slow_tiles(monkeypatch, 0.05):
        code, sub = _post(
            rest, "/jobs",
            json.dumps({"sql": "SELECT sum(v) FROM rg_r"}).encode())
        assert code == 200
        job = _get(rest, f"/jobs/{sub['jobId']}")
        qid = job["queryId"]            # visible from submission on
        # the governed query shows up on GET /queries while running
        deadline = time.monotonic() + 5
        seen = False
        while time.monotonic() < deadline and not seen:
            seen = any(q["id"] == qid for q in _get(rest, "/queries"))
            if not seen:
                time.sleep(0.01)
        assert seen
        code, body = _post(rest, f"/queries/{qid}/cancel")
        assert code == 200 and body["cancelled"] is True
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            job = _get(rest, f"/jobs/{sub['jobId']}")
            if job["status"] != "RUNNING":
                break
            time.sleep(0.02)
    assert job["status"] == "ERROR"
    assert "XCL52" in job["error"]
    # cancelling an unknown query 404s
    code, body = _post(rest, "/queries/nosuchquery/cancel")
    assert code == 404 and body["cancelled"] is False
    # the unified ledger is served too
    led = _get(rest, "/queries/ledger")
    assert "host" in led and "device" in led


def test_non_query_statements_governed_with_explicit_ctx(session):
    """Jobserver DML (INSERT/UPDATE/DDL) runs under its pre-created
    context too: cancellation applies, and a cancel landing before the
    worker thread starts stops the statement entirely."""
    broker = resource.global_broker()
    session.sql("CREATE TABLE rg_nq (v DOUBLE) USING column")
    session.insert_arrays("rg_nq", [np.ones(10)])
    ctx = broker.watch(resource.new_query("ins", "admin"))
    session.sql("INSERT INTO rg_nq SELECT v FROM rg_nq", query_ctx=ctx)
    assert ctx.state == "finished"
    ctx2 = broker.watch(resource.new_query("ins2", "admin"))
    ctx2.cancel("cancelled pre-admission")
    with pytest.raises(resource.CancelException):
        session.sql("INSERT INTO rg_nq SELECT v FROM rg_nq",
                    query_ctx=ctx2)
    broker.release(ctx2)
    assert session.sql("SELECT count(*) FROM rg_nq").rows()[0][0] == 20


def test_metrics_registry_has_governor_counters(session):
    session.sql("CREATE TABLE rg_m (v DOUBLE) USING column")
    session.insert_arrays("rg_m", [np.ones(10)])
    before = global_registry().counter("governor_admitted")
    session.sql("SELECT sum(v) FROM rg_m")
    snap = global_registry().snapshot()
    assert snap["counters"]["governor_admitted"] == before + 1
    for g in ("governor_inflight_bytes", "governor_active_queries",
              "governor_queued_queries"):
        assert g in snap["gauges"]
    # prometheus exposition carries them as well
    assert "snappy_tpu_governor_admitted_total" in \
        global_registry().to_prometheus()


@pytest.mark.slow
def test_endurance_admission_churn(session, props):
    """Endurance-style: sustained admit/queue/release churn from many
    threads leaks no inflight bytes and deadlocks nobody."""
    props.memory_limit_bytes = 10_000
    props.admission_queue_depth = 64
    props.admission_wait_s = 30.0
    broker = resource.global_broker()
    errors = []

    def worker(i):
        try:
            for _ in range(50):
                ctx = resource.new_query(f"w{i}", f"user{i % 3}")
                broker.admit(ctx, estimate_bytes=3000)
                broker.release(ctx)
        except Exception as e:          # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors
    with broker._cond:
        assert broker._inflight_bytes == 0
        assert not broker._queue
