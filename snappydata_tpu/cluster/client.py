"""Client: failover-aware Flight connection (the snappydata JDBC-driver
analogue — jdbc:snappydata://host:port with locator-based failover,
jdbc/.../Constant.scala:29-33)."""

from __future__ import annotations

import json
import random
import time
from typing import Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight

from snappydata_tpu import config
from snappydata_tpu.cluster.retry import CircuitBreaker, ExponentialBackoff
from snappydata_tpu.fault import failpoints


class SnappyClient:
    def __init__(self, address: Optional[str] = None,
                 locator: Optional[str] = None,
                 token: Optional[str] = None,
                 user: Optional[str] = None,
                 password: Optional[str] = None):
        """Connect directly (`address`='host:port') or discover query
        servers through a locator ('host:port' of the locator service).
        `token` authenticates every request when the server has
        auth_tokens configured; `user`+`password` instead log in against
        the server's auth provider (BUILTIN/LDAP) for an ephemeral token —
        re-acquired automatically after a failover, since tokens are
        per-server (ref: JDBC user/password connection properties)."""
        self._token = token
        self._user = user
        self._password = password
        self._catalog_cache: Optional[dict] = None
        self._catalog_fetched_at = 0.0
        self._addresses: List[str] = []
        if address:
            self._addresses.append(address)
        self._locator = locator
        self._conn: Optional[flight.FlightClient] = None
        props = config.global_properties()
        # per-address circuit breakers: a member that failed establishment
        # breaker_failures times in a row is SKIPPED during failover while
        # its breaker is open (no connect-timeout tax per request), probed
        # again half-open after breaker_reset_s — and always retried as a
        # last resort when no other member connects
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._backoff = ExponentialBackoff(
            props.retry_backoff_base_s, props.retry_backoff_max_s,
            jitter=props.retry_jitter,
            rng=random.Random(props.fault_seed))
        if locator and not address:
            self._refresh_from_locator()

    def _refresh_from_locator(self) -> None:
        from snappydata_tpu.cluster.locator import LocatorClient

        lc = LocatorClient(self._locator, member_id="client", role="client")
        try:
            members = lc.members()
        finally:
            lc.close()
        self._addresses = [f"{m.host}:{m.port}" for m in members
                           if m.port and m.role in ("server", "lead")]

    def _login(self, conn: flight.FlightClient) -> None:
        """Exchange user/password for a per-server ephemeral token."""
        if self._user is None or self._password is None:
            return
        body = json.dumps({"user": self._user,
                           "password": self._password}).encode("utf-8")
        results = list(conn.do_action(flight.Action("login", body)))
        self._token = json.loads(
            results[0].body.to_pybytes().decode("utf-8"))["token"]

    def _establish(self, addr: str) -> flight.FlightClient:
        conn = flight.connect(f"grpc://{addr}")
        list(conn.do_action(flight.Action("ping", b"")))
        self._login(conn)
        return conn

    def _breaker(self, addr: str) -> CircuitBreaker:
        br = self._breakers.get(addr)
        if br is None:
            props = config.global_properties()
            br = self._breakers[addr] = CircuitBreaker(
                props.breaker_failures, props.breaker_reset_s)
        return br

    def _try_establish(self, addr: str) -> Optional[flight.FlightClient]:
        """Attempt one address, recording the outcome in its breaker.
        Returns None on (non-auth) failure; re-raises auth errors."""
        br = self._breaker(addr)
        try:
            conn = self._establish(addr)
        except flight.FlightUnauthenticatedError:
            raise   # bad credentials — failover can't fix that
        except Exception as e:  # failover to the next member
            br.record_failure()
            self._last_establish_err = e
            return None
        br.record_success()
        return conn

    def _client(self) -> flight.FlightClient:
        if self._conn is not None:
            return self._conn
        self._last_establish_err: Optional[Exception] = None
        skipped: List[str] = []
        for addr in list(self._addresses):
            if not self._breaker(addr).allow():
                skipped.append(addr)   # breaker open: known-dead, skip
                continue
            conn = self._try_establish(addr)
            if conn is not None:
                self._conn = conn
                return conn
        if self._locator:
            self._refresh_from_locator()
            for addr in self._addresses:
                if addr in skipped:
                    continue
                conn = self._try_establish(addr)
                if conn is not None:
                    self._conn = conn
                    return conn
        # last resort: open breakers never REDUCE availability — when no
        # healthy member connected, try the skipped ones anyway
        for addr in skipped:
            conn = self._try_establish(addr)
            if conn is not None:
                self._conn = conn
                return conn
        raise ConnectionError(
            f"no reachable member: {self._last_establish_err}")

    def _invalidate(self) -> None:
        self._conn = None

    def _request(self, once, retry: bool):
        """Run `once` (which must connect via _client() before building
        its payload — the token may only exist after login, and a
        failover re-login mints a fresh per-server token). Retries once
        on connection loss when `retry` (only for idempotent requests —
        a blind retry of e.g. repartition would duplicate rows), and once
        on an expired login token (re-login via reconnect)."""
        def guarded():
            # flight.rpc failpoint: `before` simulates a request that
            # never reached the server; `after` simulates a response
            # lost AFTER the server applied (the case _NON_IDEMPOTENT
            # exists for — a blind retry would double-apply)
            failpoints.hit("flight.rpc")
            out = once()
            failpoints.hit("flight.rpc", phase="after")
            return out

        try:
            return guarded()
        except flight.FlightUnauthenticatedError:
            if self._user is None or self._token is None:
                raise
            self._invalidate()   # reconnect → fresh login
            return guarded()
        except (flight.FlightUnavailableError, ConnectionError):
            # ALWAYS drop the dead connection so the next call fails over;
            # only re-issuing this request is gated on idempotency
            self._invalidate()
            if not retry:
                raise
            from snappydata_tpu.observability.metrics import global_registry

            global_registry().inc("failover_retries")
            time.sleep(self._backoff.delay(0))
            return guarded()

    def _action(self, name: str, body: dict, retry: bool = True) -> dict:
        def once():
            conn = self._client()
            raw = json.dumps(self._with_token(dict(body))).encode("utf-8")
            results = list(conn.do_action(flight.Action(name, raw)))
            return json.loads(results[0].body.to_pybytes().decode("utf-8"))

        return self._request(once, retry)

    def sql(self, sql: str, params: Sequence = (),
            prepared: bool = False) -> pa.Table:
        """Query → Arrow table (record-batch paged by Flight).
        `prepared` routes through the server's serving executor —
        repeated statements skip parse/plan on the server and concurrent
        requests of one shape fuse into a single device dispatch."""
        def once():
            conn = self._client()
            body = {"sql": sql, "params": list(params)}
            if prepared:
                body["prepared"] = True
            ticket = flight.Ticket(json.dumps(
                self._with_token(body)).encode("utf-8"))
            return conn.do_get(ticket).read_all()

        return self._request(once, retry=True)

    # leading keywords whose statements are NOT safe to blind-retry after
    # a connection drop (the server may have applied them before the
    # response was lost — a re-send would double-apply)
    _NON_IDEMPOTENT = ("insert", "put", "update", "delete", "exec")

    def execute(self, sql: str, params: Sequence = ()) -> dict:
        """DDL/DML via action (no result paging needed). Queries and DDL
        retry across failover; DML does not (re-sending an INSERT whose
        response was lost would duplicate rows)."""
        head = sql.lstrip().split(None, 1)[0].lower() if sql.strip() else ""
        return self._action("sql", {"sql": sql, "params": list(params)},
                            retry=head not in self._NON_IDEMPOTENT)

    def insert(self, table: str, columns: dict) -> None:
        """Bulk columnar ingest via do_put. `columns` is a name → array
        dict or a ready pyarrow Table."""
        arrow = columns if isinstance(columns, pa.Table) else \
            pa.table(columns)

        def once():
            conn = self._client()   # may log in and mint self._token
            if self._token is not None:
                descriptor = flight.FlightDescriptor.for_command(
                    json.dumps({"table": table,
                                "token": self._token}).encode("utf-8"))
            else:
                descriptor = flight.FlightDescriptor.for_path(table)
            writer, _ = conn.do_put(descriptor, arrow.schema)
            writer.write_table(arrow)
            writer.close()

        # retry=False: an insert whose response was lost may have been
        # applied — only expired-token re-login is safe to retry
        self._request(once, retry=False)

    def repartition(self, body: dict) -> dict:
        """Ask this server to hash-repartition its shard of body['table']
        by body['key'] into body['dest'] across body['servers'] (the
        shuffle-exchange fan-out)."""
        return self._action("repartition", body, retry=False)

    def plan(self, plan_payload, params: Sequence = ()):
        """Execute a serialized logical plan fragment on this server and
        return the Arrow result (the plan-shipping twin of sql() —
        idempotent read, so failover/re-login retry applies the same)."""
        def once():
            conn = self._client()
            body = self._with_token({"plan": plan_payload,
                                     "params": list(params)})
            return conn.do_get(flight.Ticket(
                json.dumps(body).encode("utf-8"))).read_all()

        return self._request(once, retry=True)

    def move_buckets(self, body: dict) -> dict:
        """Rebalance: this server copies its primary rows of
        body['buckets'] (table body['table']) to body['target'] and
        deletes them locally."""
        return self._action("move_buckets", body, retry=False)

    def export(self, body: dict) -> dict:
        """Ask this server to STREAM its local shard of body['table']
        into body['dest'] on every body['targets'] address, one scan
        unit at a time (the broadcast exchange data plane)."""
        return self._action("export", body, retry=False)

    def scan_table(self, name: str):
        """Stream a table's full content as record batches (server-side
        memory bounded by one column batch)."""
        conn = self._client()
        body = self._with_token({"scan_table": name})
        import json as _json

        return conn.do_get(flight.Ticket(
            _json.dumps(body).encode("utf-8"))).to_reader()

    def ping(self) -> None:
        """Liveness probe (raises if the member is unreachable)."""
        list(self._client().do_action(flight.Action("ping", b"")))

    def promote(self, body: dict) -> dict:
        """Failover re-hosting: move this server's replica-shadow rows of
        body['buckets'] into its primary table (body['table'])."""
        return self._action("promote", body, retry=False)

    def replicate(self, body: dict) -> dict:
        """Redundancy restoration: this server copies its CURRENT rows of
        body['buckets'] (table body['table']) into body['target']'s
        replica shadow."""
        return self._action("replicate", body, retry=False)

    def purge_replica(self, body: dict) -> dict:
        """Drop body['buckets'] rows from this server's replica shadow of
        body['table'] (pre-copy cleanup for idempotent re-replication)."""
        return self._action("purge_replica", body)

    def _with_token(self, body: dict) -> dict:
        if self._token is not None:
            body["token"] = self._token
        return body

    def stats(self) -> dict:
        return self._action("stats", {})

    # -- thin-client catalog (ref: ConnectorExternalCatalog's cached
    # catalog tables keyed on catalog version, invalidated wholesale on
    # any DDL — SmartConnectorExternalCatalog.invalidate) ---------------

    # catalog snapshots are trusted this long before refetching — remote
    # DDL (a bumped server generation) is observed within the TTL, like
    # SmartConnectorExternalCatalog's version check per access
    CATALOG_TTL_S = 5.0

    def catalog(self, refresh: bool = False) -> dict:
        """Full catalog metadata in ONE round trip: {generation, tables:
        {name: {columns, provider, partition_by, buckets, ...}}, views}.
        Served from cache within CATALOG_TTL_S; `refresh=True` or
        `invalidate_catalog()` forces a refetch."""
        import time

        now = time.monotonic()
        if self._catalog_cache is None or refresh or \
                now - self._catalog_fetched_at > self.CATALOG_TTL_S:
            self._catalog_cache = self._action("catalog", {})
            self._catalog_fetched_at = now
        return self._catalog_cache

    def invalidate_catalog(self) -> None:
        self._catalog_cache = None

    def tables(self, refresh: bool = False) -> dict:
        """table name → metadata (schema columns, provider, placement)."""
        return self.catalog(refresh=refresh)["tables"]

    def describe(self, table: str, refresh: bool = False) -> dict:
        """One table's metadata; a miss refetches once before raising —
        the cached snapshot may simply predate the table's DDL."""
        name = table.lower().removeprefix("app.")
        tables = self.tables(refresh=refresh)
        if name not in tables and not refresh:
            tables = self.tables(refresh=True)
        if name not in tables:
            raise KeyError(f"no such table: {table}")
        return tables[name]

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
