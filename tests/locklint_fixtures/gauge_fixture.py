"""Fixture: the PR 10 gauge-under-registry-lock shape.

`snapshot()` iterates the gauge callables and invokes them while STILL
holding the registry lock — a gauge that touches the registry (e.g. a
ledger refresh calling `inc()`) self-deadlocks on the non-reentrant
lock. tools/locklint must flag the `fn()` call as callback-under-lock.
Also carries a swallowed-exception loop and a metric-name collision
pair for the sibling lints. Never imported by the engine."""

import time

from snappydata_tpu.utils import locks


class Registry:
    def __init__(self):
        self._lock = locks.named_lock("fixture.registry")
        self._gauges = {}
        self._counters = {}

    def gauge(self, name, fn):
        with self._lock:
            self._gauges[name] = fn

    def inc(self, name):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + 1

    def snapshot(self):
        out = {}
        with self._lock:
            for name, fn in self._gauges.items():
                out[name] = fn()      # BUG: callback under the lock
        return out


def poller(registry, stop):
    while not stop.is_set():
        try:
            registry.snapshot()
        except Exception:
            pass                      # BUG: swallowed in a loop
        time.sleep(0.05)


def collide(reg):
    # BUG: distinct raw names, one sanitized form ("a.b" vs "a_b")
    reg.inc("fixture.rows_seen")
    reg.inc("fixture_rows_seen")
