"""Adaptive micro-batcher: coalesce concurrent executes of one prepared
plan into a single vmapped device dispatch.

Inference-server shape ("Global Hash Tables Strike Back!" frames why
concurrent small aggregates should share one device pass instead of
contending): the first request on an idle plan becomes the LEADER; if it
is alone it waits up to `serving_batch_wait_us` for batchmates, then
dispatches.  While a dispatch is in flight, new arrivals queue with NO
added wait — they fuse into the next leader's batch, so under load the
batcher adds zero artificial latency and occupancy rises naturally.

Correctness inside a fused batch:
- every request keeps its own governor context — a request cancelled (or
  timed out) before dispatch is dropped from the batch and raises its
  own CancelException; its batchmates are untouched;
- requests with incompatible bind signatures (different param dtypes)
  never fuse;
- a batch is padded to a {2^k, 1.5*2^k} bucket (bounded recompiles) by
  repeating the last request's binds; padded lanes are discarded;
- any fused-dispatch failure (ragged aux shapes, vmap limitation,
  per-lane overflow) falls back to per-request engine execution — the
  batch path can only ever be an optimization, never an answer change.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
import time
from typing import List, Optional, Sequence

import numpy as np

from snappydata_tpu import config
from snappydata_tpu.observability.metrics import global_registry


# how recently another request must have overlapped this plan's queue
# for a LONE leader to open the coalescing window.  Wide enough that a
# steady minority stream (e.g. the 30%-aggregate share of a mixed
# serving load) keeps coalescing between bursts; a truly single-stream
# caller still never waits (first request sees a cold signal).
_CONCURRENCY_HORIZON_S = 0.05


def bucket_ladder(bmax: int) -> List[int]:
    """{2^k, 1.5*2^k} padded batch sizes up to bmax (same ladder as
    storage.device.batch_bucket)."""
    out = [1]
    k = 1
    while out[-1] < bmax:
        for cand in (1 << k, (1 << k) + (1 << (k - 1))):
            if cand <= bmax and cand > out[-1]:
                out.append(cand)
        k += 1
    if out[-1] != bmax:
        out.append(bmax)
    return out


def _pad_bucket(n: int, bmax: int) -> int:
    for b in bucket_ladder(bmax):
        if b >= n:
            return b
    return bmax


def _bind_signature(params) -> tuple:
    from snappydata_tpu.engine.executor import _param_scalar

    return tuple(_param_scalar(v).dtype.str for v in params)


class _Request:
    __slots__ = ("params", "ctx", "session", "sig", "done", "result",
                 "error")

    def __init__(self, session, params, ctx):
        self.session = session
        self.params = params
        self.ctx = ctx
        self.sig = _bind_signature(params)
        self.done = False
        self.result = None
        self.error: Optional[BaseException] = None


class BatchQueue:
    """Per-PreparedPlan queue + leader election state."""

    def __init__(self):
        self.cond = locks.named_condition("serving.batcher_cond")
        self.waiting: List[_Request] = []
        self.leader: Optional[_Request] = None
        # adaptive coalescing signal: last time a request arrived while
        # another was queued/dispatching — a lone leader only opens the
        # serving_batch_wait_us window when concurrency was seen within
        # _CONCURRENCY_HORIZON_S, so a single-stream caller pays ZERO
        # added latency
        self.saw_concurrency = float("-inf")


class MicroBatcher:
    def submit(self, entry, session, params, ctx):
        """Execute `entry` with `params`, fusing with concurrent
        submissions when possible.  Blocks until this request's result
        (or error) is ready."""
        q = entry.batch_queue
        if q is None:
            # locklint: lock=serving.plan (entry is a PreparedPlan)
            with entry._lock:
                if entry.batch_queue is None:
                    entry.batch_queue = BatchQueue()
                q = entry.batch_queue
        req = _Request(session, params, ctx)
        with q.cond:
            if q.waiting or q.leader is not None:
                q.saw_concurrency = time.monotonic()
            q.waiting.append(req)
            q.cond.notify_all()
            while True:
                if req.done:
                    break
                if q.leader is None:
                    q.leader = req
                    break
                q.cond.wait()
        if not req.done:      # we are the leader
            try:
                self._lead(entry, q, req)
            finally:
                with q.cond:
                    q.leader = None
                    q.cond.notify_all()
        if req.error is not None:
            raise req.error
        return req.result

    # -- leader ---------------------------------------------------------

    def _lead(self, entry, q: BatchQueue, leader: _Request) -> None:
        props = config.global_properties()
        bmax = max(1, int(props.serving_batch_max or 1))
        wait_s = max(0.0, float(props.serving_batch_wait_us or 0.0)) / 1e6
        with q.cond:
            mine = [r for r in q.waiting if r.sig == leader.sig]
            if len(mine) < bmax and wait_s > 0 and bmax > 1 and \
                    time.monotonic() - q.saw_concurrency \
                    < _CONCURRENCY_HORIZON_S:
                # partial batch and concurrency was seen in the last few
                # ms: open the coalescing window to top up toward
                # serving_batch_max; batchmates arriving mid-window
                # notify and fuse.  (A single-stream caller never enters
                # here — straight through, no added wait.)
                deadline = time.monotonic() + wait_s
                while True:
                    mine = [r for r in q.waiting if r.sig == leader.sig]
                    if len(mine) >= bmax:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    q.cond.wait(remaining)
            # the leader MUST ride its own batch: with more than bmax
            # compatible waiters, a plain prefix could omit it — its
            # submit() would then return with neither result nor error
            mine.remove(leader)
            batch = [leader] + mine[:bmax - 1]
            for r in batch:
                q.waiting.remove(r)
        try:
            self._dispatch(entry, batch)
        finally:
            with q.cond:
                for r in batch:
                    r.done = True
                q.cond.notify_all()

    def _dispatch(self, entry, batch: List[_Request]) -> None:
        reg = global_registry()
        # per-request cancellation/timeout gate: a dead request must not
        # ride (or poison) the fused dispatch
        live: List[_Request] = []
        for r in batch:
            try:
                if r.ctx is not None:
                    r.ctx.check()
                live.append(r)
            except BaseException as e:     # noqa: BLE001 — delivered as-is
                r.error = e
        if not live:
            return
        if len(live) == 1:
            reg.inc("serving_straight_through")
            self._solo(entry, live[0])
            return
        session = live[0].session
        bmax = max(len(live),
                   int(config.global_properties().serving_batch_max or 1))
        bucket = _pad_bucket(len(live), bmax)
        padded = [r.params for r in live] + \
            [live[-1].params] * (bucket - len(live))
        try:
            tables, outs = entry.compiled_for(session) \
                .execute_batched(padded)
            results = [entry.assemble_batched(r.session, outs, tables, i,
                                              r.params)
                       for i, r in enumerate(live)]
        except BaseException:              # noqa: BLE001
            # ragged aux, vmap limitation, bind-check failure, OOM —
            # anything: the batch path must never change answers, so
            # every request re-executes through the normal engine path
            reg.inc("serving_batch_fallbacks")
            for r in live:
                self._solo(entry, r)
            return
        reg.inc("serving_batched_dispatches")
        reg.inc("serving_batch_requests", len(live))
        for r, res in zip(live, results):
            if res is None:     # this lane overflowed its static bounds
                self._solo(entry, r)  # executor.execute counts it
            else:
                r.result = res
                # engine counters for fused lanes (solo reroutes count
                # inside executor.execute — don't double-count them)
                reg.inc("queries")
                reg.inc("rows_returned", res.num_rows)

    @staticmethod
    def _solo(entry, r: _Request) -> None:
        # runs in the LEADER's thread: scope the request's OWN governor
        # context so cooperative checks see r's cancellation/deadline,
        # not the leader's — a leader timing out mid-fallback must not
        # poison the batchmate it is re-executing (and vice versa)
        from snappydata_tpu.resource.context import query_scope

        try:
            if r.ctx is not None:
                with query_scope(r.ctx):
                    r.result = r.session.executor.execute(
                        entry.tokenized, r.params, plan_key=entry.core_key)
            else:
                r.result = r.session.executor.execute(
                    entry.tokenized, r.params, plan_key=entry.core_key)
        except BaseException as e:         # noqa: BLE001
            r.error = e


_BATCHER = MicroBatcher()


def global_batcher() -> MicroBatcher:
    return _BATCHER
