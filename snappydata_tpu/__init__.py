"""snappydata_tpu — a TPU-native distributed in-memory analytics database.

A from-scratch JAX/XLA re-design of the capabilities of SnappyData
(reference: SnappyDataInc/snappydata @ /root/reference): a mutable column +
row store fused with a SQL engine whose hot path (scan / filter / project /
hash-aggregate / hash-join) executes as jitted XLA programs on TPU, with
plan caching keyed on literal-tokenized SQL, partitioned/replicated/
collocated tables over a `jax.sharding.Mesh`, snapshot-isolation mutation
via versioned batch manifests, exactly-once streaming ingest, and AQP
(stratified samples / TopK) as a plug-in layer.

Layer map (mirrors reference SURVEY.md §1):
  storage/   — column-batch format, encodings, deltas   (ref: encoders/)
  sql/       — lexer/parser/analyzer, logical plans     (ref: SnappyParser)
  engine/    — jitted physical operators + plan cache   (ref: codegen exec)
  parallel/  — murmur3 partitioner, bucket map, mesh    (ref: StoreHashFunction)
  catalog/   — table metadata + persistence             (ref: SnappySessionCatalog)
  cluster/   — locator/lead/server runtime              (ref: cluster/)
  streaming/ — exactly-once sink                        (ref: SnappySinkCallback)
  aqp/       — sampling, CMS/TopK                       (ref: SnappyContextFunctions)
"""

__version__ = "0.1.0"

import jax as _jax

# LONG/TIMESTAMP columns are int64; without x64, jnp.asarray silently wraps
# them to int32. Float width stays policy-controlled (config.use_float64):
# decimals are explicitly cast to float32 on TPU in types.device_dtype.
_jax.config.update("jax_enable_x64", True)

from snappydata_tpu.session import SnappySession  # noqa: E402,F401
from snappydata_tpu import config  # noqa: E402,F401
