"""REAL multi-process jax.distributed bring-up (round-4 verdict
Missing #2 / task 2): two OS processes, each with its own 4-virtual-
CPU-device jax backend, joined through `initialize_multihost` (NOT
monkeypatched) into one 8-device world — then

- a cross-process GSPMD collective (jit sum over a global mesh, Gloo
  transport) value-asserted on both ranks, and
- the composed cluster topology driven through the REAL product
  surface: `python -m snappydata_tpu server --coordinator ...` twice,
  each server picking its `local_device_indices()` submesh, with a
  DistributedSession scatter -> per-server GSPMD -> merge battery on
  top.

Ref parity: the reference's multi-host membership boots executors that
join the distributed fabric at process start
(/root/reference/cluster/src/main/scala/io/snappydata/cluster/
ExecutorInitiator.scala:45-105); here the fabric is jax.distributed's
coordination service + XLA cross-process collectives.
"""

import os
import re
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env(n_local: int):
    # CPU backend with n_local virtual devices per process; the axon
    # sitecustomize must stay OFF the path (it force-selects the TPU
    # relay and ignores JAX_PLATFORMS)
    return {**{k: v for k, v in os.environ.items()
               if k not in ("PYTHONPATH", "XLA_FLAGS", "JAX_PLATFORMS")},
            "PYTHONPATH": _REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={n_local}"}


_WORKER = '''
import sys
rank = int(sys.argv[1]); port = sys.argv[2]
from snappydata_tpu.parallel.multihost import (initialize_multihost,
                                               local_device_indices)
assert initialize_multihost(f"127.0.0.1:{port}", 2, rank)
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_enable_x64", True)
devs = jax.devices()
assert len(devs) == 8, devs
local = local_device_indices()
assert local == list(range(rank * 4, rank * 4 + 4)), (rank, local)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(devs), ("d",))
n = 800
arr = jax.make_array_from_callback(
    (n,), NamedSharding(mesh, P("d")),
    lambda idx: np.arange(n, dtype=np.float64)[idx])
total = jax.jit(lambda x: jnp.sum(x),
                out_shardings=NamedSharding(mesh, P()))(arr)
got = float(total.addressable_shards[0].data)
assert got == n * (n - 1) / 2, got
print(f"rank {rank}: OK global=8 local={local} sum={got}", flush=True)
'''


def test_two_process_jax_distributed_collective():
    """jax.distributed.initialize EXECUTES in two real processes and a
    GSPMD reduction crosses the process boundary with the right value."""
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-u", "-c", _WORKER, str(r), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(4)) for r in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
            assert p.returncode == 0, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert "rank 0: OK global=8 local=[0, 1, 2, 3]" in outs[0], outs[0]
    assert "rank 1: OK global=8 local=[4, 5, 6, 7]" in outs[1], outs[1]


def _read_until(proc, pattern: str, deadline: float) -> str:
    """Accumulate proc stdout until `pattern` matches or the deadline
    passes. Reads happen on a daemon thread: readline() blocks while a
    live child stays silent, so a plain loop would never re-check the
    deadline (review finding)."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue()

    def pump():
        for line in proc.stdout:
            q.put(line)
        q.put(None)

    threading.Thread(target=pump, daemon=True).start()
    buf = ""
    while time.time() < deadline:
        try:
            line = q.get(timeout=min(1.0, max(0.05,
                                              deadline - time.time())))
        except queue.Empty:
            continue
        if line is None:
            raise AssertionError(
                f"process died rc={proc.poll()}: {buf}")
        buf += line
        if re.search(pattern, buf):
            return buf
    raise AssertionError(f"timeout waiting for {pattern!r}; got: {buf}")


def test_cli_cluster_multihost_composed():
    """Two `python -m snappydata_tpu server --coordinator ...` processes
    form a real jax.distributed world, each owning its local submesh;
    a DistributedSession on top runs the scatter -> per-server GSPMD ->
    merge battery with exact values."""
    from snappydata_tpu.cluster.distributed import DistributedSession

    loc_port = _free_port()
    coord_port = _free_port()
    procs = []
    try:
        locator = subprocess.Popen(
            [sys.executable, "-u", "-m", "snappydata_tpu", "locator",
             "--port", str(loc_port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_env(4))
        procs.append(locator)
        _read_until(locator, r"locator running", time.time() + 60)

        servers = []
        for rank in range(2):
            sp = subprocess.Popen(
                [sys.executable, "-u", "-m", "snappydata_tpu", "server",
                 "--locator", f"127.0.0.1:{loc_port}",
                 "--coordinator", f"127.0.0.1:{coord_port}",
                 "--num-processes", "2", "--process-id", str(rank)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=_env(4))
            procs.append(sp)
            servers.append(sp)

        addrs = []
        want = [[0, 1, 2, 3], [4, 5, 6, 7]]
        for rank, sp in enumerate(servers):
            out = _read_until(sp, r"server \S+ flight at \S+",
                              time.time() + 180)
            m = re.search(r"flight at (\S+?),", out)
            addrs.append(m.group(1))
            # the server derived its submesh from local_device_indices()
            # of the REAL 8-device multi-process world
            assert f"submesh {want[rank]}" in out, out

        ds = DistributedSession(server_addresses=addrs)
        try:
            ds.sql("CREATE TABLE mh (k BIGINT, g BIGINT, v DOUBLE) "
                   "USING column OPTIONS (partition_by 'k')")
            rng = np.random.default_rng(11)
            n = 6000
            k = rng.integers(0, 500, n).astype(np.int64)
            g = (k % 4).astype(np.int64)
            v = rng.random(n)
            ds.insert_arrays("mh", [k, g, v])
            got = ds.sql("SELECT g, count(*), sum(v) FROM mh "
                         "GROUP BY g ORDER BY g").rows()
            assert len(got) == 4, got
            for gi, cnt, sv in got:
                m = g == gi
                assert cnt == int(m.sum()), (gi, cnt)
                assert abs(sv - float(v[m].sum())) <= 1e-6 * max(
                    1.0, abs(sv)), (gi, sv)
        finally:
            ds.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=30)
