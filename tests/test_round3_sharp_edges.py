"""Round-3 sharp edges: count-scalar correlated subqueries (left join +
coalesce 0), per-table eviction budgets, critical-memory fail-fast, and
string murmur3 bucketing (ref: scalar-subquery decorrelation in
Catalyst; per-table EVICTION DDL + critical-heap-percentage,
SnappyUnifiedMemoryManager.scala:379-401; StoreHashFunction UTF8)."""

import numpy as np
import pytest

from snappydata_tpu import config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.parallel.hashing import bucket_of_np, hash_bytes
from snappydata_tpu.storage.hoststore import CriticalMemoryError


def test_count_scalar_correlated_subquery(session):
    session.sql("CREATE TABLE o2 (o_id BIGINT, cust BIGINT) USING column")
    session.sql("CREATE TABLE i2 (i_oid BIGINT, qty BIGINT) USING column")
    session.insert_arrays("o2", [np.array([1, 2, 3]),
                                 np.array([10, 20, 30])])
    session.insert_arrays("i2", [np.array([1, 1, 3]),
                                 np.array([5, 6, 7])])
    r = session.sql(
        "SELECT o_id FROM o2 o WHERE (SELECT count(*) FROM i2 i "
        "WHERE i.i_oid = o.o_id) < 2 ORDER BY o_id")
    assert [x[0] for x in r.rows()] == [2, 3]
    # the empty group must compare as 0, not NULL (left join + coalesce)
    r2 = session.sql(
        "SELECT o_id FROM o2 o WHERE (SELECT count(qty) FROM i2 i "
        "WHERE i.i_oid = o.o_id) = 0")
    assert [x[0] for x in r2.rows()] == [2]
    # count on the other comparison side
    r3 = session.sql(
        "SELECT o_id FROM o2 o WHERE 1 >= (SELECT count(*) FROM i2 i "
        "WHERE i.i_oid = o.o_id) ORDER BY o_id")
    assert [x[0] for x in r3.rows()] == [2, 3]


def test_per_table_eviction_budget(session):
    from snappydata_tpu.observability.metrics import global_registry

    session.sql("CREATE TABLE ev (k BIGINT, v DOUBLE) USING column "
                "OPTIONS (eviction_bytes '4096', column_batch_rows '500', "
                "column_max_delta_rows '200')")
    before = global_registry()._counters["host_batches_spilled"]
    session.insert_arrays("ev", [np.arange(5000, dtype=np.int64),
                                 np.arange(5000, dtype=np.float64)])
    assert global_registry()._counters["host_batches_spilled"] > before
    # spilled batches stay queryable (memmaps reload transparently)
    assert session.sql("SELECT count(*), sum(k) FROM ev").rows()[0] == \
        (5000, sum(range(5000)))


def test_critical_memory_fail_fast(session):
    session.sql("CREATE TABLE cm (k BIGINT) USING column")
    session.insert_arrays("cm", [np.arange(10, dtype=np.int64)])
    props = config.global_properties()
    old = props.critical_host_bytes
    props.critical_host_bytes = 1   # any RSS exceeds this
    try:
        with pytest.raises(CriticalMemoryError):
            session.insert_arrays("cm", [np.arange(5, dtype=np.int64)])
        # reads still served at critical memory (member stays up)
        assert session.sql("SELECT count(*) FROM cm").rows()[0][0] == 10
    finally:
        props.critical_host_bytes = old
    session.insert_arrays("cm", [np.arange(5, dtype=np.int64)])
    assert session.sql("SELECT count(*) FROM cm").rows()[0][0] == 15


def test_string_murmur3_bucketing():
    vals = np.array(["east", "west", "north", None, "east"], dtype=object)
    b = bucket_of_np(vals, 16)
    assert b[0] == b[4]                      # deterministic per value
    assert 0 <= b.min() and b.max() < 16
    # word+tail path: hashes differ across lengths and match themselves
    assert hash_bytes(b"abcd") == hash_bytes(b"abcd")
    assert hash_bytes(b"abcd") != hash_bytes(b"abcde")
    assert hash_bytes(b"") == hash_bytes(b"")
    # spread: 1000 distinct strings should hit most of 32 buckets
    many = np.array([f"key-{i}" for i in range(1000)], dtype=object)
    assert len(set(bucket_of_np(many, 32).tolist())) > 24
