"""Node roles + lifecycle (the ServiceManager / LeadImpl / ServerImpl
analogue, cluster/.../ServiceManager.scala, impl/LeadImpl.scala:94,
core/.../impl/ServerImpl.scala:34).

- LocatorNode: runs the membership/locator service.
- ServerNode:  data host — Flight front door over a session (the embedded
  network-server-in-the-data-JVM design), registers + heartbeats.
- LeadNode:    acquires the primary-lead lock (standby blocks and takes
  over on primary death — LeadImpl.scala:100 election), then runs the
  stats service + REST/jobs + its own Flight endpoint.

Single-host round: nodes share the process's catalog/storage (embedded
mode); the multi-host data plane (bucket placement over DCN) layers on the
same membership surface in a later round.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from snappydata_tpu.cluster.locator import (Locator, LocatorClient,
                                            PRIMARY_LEAD_LOCK)


class LocatorNode:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.locator = Locator(host, port)

    def start(self) -> "LocatorNode":
        self.locator.start()
        return self

    def stop(self) -> None:
        self.locator.stop()

    @property
    def address(self) -> str:
        return self.locator.address


class _MemberNode:
    role = "member"

    def __init__(self, locator_address: str, session,
                 host: str = "127.0.0.1", flight_port: int = 0,
                 member_id: Optional[str] = None):
        self.session = session
        self.member_id = member_id or f"{self.role}-{uuid.uuid4().hex[:8]}"
        self.host = host
        self.locator_address = locator_address
        self._flight_port = flight_port
        self.flight = None
        self.membership: Optional[LocatorClient] = None

    @property
    def flight_address(self) -> str:
        """Every member answers queries over Flight (the lead IS an
        engine too) — failover clients pin to a tier via this."""
        return f"{self.host}:{self.flight.port}"

    def _start_flight(self) -> int:
        from snappydata_tpu.cluster.flight_server import SnappyFlightServer

        from snappydata_tpu.security import make_provider

        tokens = self.session.conf.get("auth_tokens") or None
        provider = make_provider(self.session.conf)
        cluster_token = self.session.conf.get("auth_cluster_token")
        if provider is not None and not cluster_token:
            # login tokens are per-server: without a cluster-shared secret,
            # server→server traffic (repartition/replicate do_put) would be
            # rejected by peers mid-operation — fail at boot, not mid-shuffle
            raise ValueError(
                "auth_provider is configured but auth_cluster_token is not: "
                "cluster members need a shared secret to authenticate "
                "server-to-server traffic (set auth_cluster_token to the "
                "same value on every member)")
        self.flight = SnappyFlightServer(self.session, self.host,
                                         self._flight_port,
                                         auth_tokens=tokens,
                                         auth_provider=provider,
                                         internal_token=cluster_token)
        self._flight_thread = threading.Thread(target=self.flight.serve,
                                               daemon=True)
        self._flight_thread.start()
        # the port is bound at construction; wait for the serve loop to
        # actually accept connections before registering with the locator
        self.flight.wait_ready(timeout=10)
        return self.flight.port

    def _join(self, port: int) -> None:
        self.membership = LocatorClient(self.locator_address,
                                        self.member_id, self.role,
                                        self.host, port)
        self.membership.register()
        self.membership.start_heartbeats()

    def stop(self) -> None:
        if self.membership is not None:
            self.membership.close()
        if self.flight is not None:
            self.flight.shutdown()


class ServerNode(_MemberNode):
    """Data server: storage + Flight query/ingest endpoint.

    `mesh_devices`: indices of the LOCAL accelerator devices this server
    owns — its session then runs every query GSPMD-sharded over that
    submesh, composing the cluster plane (scatter over servers) with the
    mesh plane (SPMD inside each server). Ref: one long-lived embedded
    executor per store JVM, ExecutorInitiator.scala:45-105."""

    role = "server"

    def __init__(self, locator_address: str, session,
                 host: str = "127.0.0.1", flight_port: int = 0,
                 member_id: Optional[str] = None,
                 mesh_devices: Optional[list] = None):
        super().__init__(locator_address, session, host, flight_port,
                         member_id)
        if mesh_devices:
            from snappydata_tpu.parallel.mesh import submesh

            session.default_mesh = submesh(mesh_devices)

    def start(self) -> "ServerNode":
        port = self._start_flight()
        self._join(port)
        return self


class LeadNode(_MemberNode):
    """Lead: primary/standby election, then planner-side services."""

    role = "lead"

    def __init__(self, locator_address: str, session,
                 host: str = "127.0.0.1", flight_port: int = 0,
                 rest_port: int = 0, lease_s: float = 5.0,
                 member_id: Optional[str] = None):
        super().__init__(locator_address, session, host, flight_port,
                         member_id)
        self.rest_port = rest_port
        self.lease_s = lease_s
        self.is_primary = False
        self.rest = None
        self.stats_service = None
        self._stop_event = threading.Event()
        self._election: Optional[threading.Thread] = None

    def start(self, wait_for_primary: bool = False) -> "LeadNode":
        port = self._start_flight()
        self._join(port)
        self._election = threading.Thread(target=self._election_loop,
                                          daemon=True)
        self._election.start()
        if wait_for_primary:
            deadline = time.time() + 30
            while not self.is_primary and time.time() < deadline:
                time.sleep(0.05)
        return self

    def _election_loop(self) -> None:
        """Standby blocks on the primary lock; the holder renews its lease
        (half-life cadence). Exactly the reference's dlock election."""
        while not self._stop_event.is_set():
            try:
                acquired = self.membership.try_lock(PRIMARY_LEAD_LOCK,
                                                    lease_s=self.lease_s)
            except (ConnectionError, OSError):
                acquired = False
            if acquired and not self.is_primary:
                self._become_primary()
            elif not acquired and self.is_primary:
                self._step_down()
            self._stop_event.wait(self.lease_s / 2)

    def _become_primary(self) -> None:
        from snappydata_tpu.cluster.rest import RestService
        from snappydata_tpu.observability import TableStatsService

        self.stats_service = TableStatsService(self.session.catalog).start()
        from snappydata_tpu.security import make_provider

        self.rest = RestService(self.session, self.stats_service,
                                membership=self.membership,
                                host=self.host, port=self.rest_port,
                                auth_tokens=self.session.conf.get(
                                    "auth_tokens") or None,
                                auth_provider=make_provider(
                                    self.session.conf)).start()
        # cluster view for operator actions (POST /rebalance): a
        # DistributedSession over the data servers the locator knows
        try:
            servers = sorted(f"{m.host}:{m.port}"
                             for m in self.membership.members()
                             if m.role == "server")
            if servers:
                from snappydata_tpu.cluster.distributed import \
                    DistributedSession

                self.rest.distributed = DistributedSession(
                    server_addresses=servers)
        except Exception:
            pass  # no servers yet: /rebalance reports 409 until retried
        self.is_primary = True

    def _step_down(self) -> None:
        self.is_primary = False
        if self.rest is not None:
            self.rest.stop()
            self.rest = None
        if self.stats_service is not None:
            self.stats_service.stop()
            self.stats_service = None

    def stop(self) -> None:
        self._stop_event.set()
        if self.is_primary:
            try:
                self.membership.unlock(PRIMARY_LEAD_LOCK)
            except (ConnectionError, OSError):
                pass
            self._step_down()
        super().stop()

    @property
    def rest_address(self) -> Optional[str]:
        if self.rest is None:
            return None
        return f"{self.rest.host}:{self.rest.port}"
