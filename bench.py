"""Headline benchmark: TPC-H Q1 + Q6 scan+aggregate throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline context (BASELINE.md): the reference's headline claim is the
quickstart scan+group-by over a 100M-row column table at 16-20x a Spark
2.1.1 cached DataFrame on a laptop-class JVM (docs/quickstart/
performance_apache_spark.md:2-6). No absolute rows/sec is published
in-repo; we peg the baseline at 66M rows/s (100M rows in ~1.5s, the
midpoint implied by that scenario) and report vs_baseline against it.

Scale via SNAPPY_BENCH_SF (default 16.0 → 96M lineitem rows, matching the
reference's 100M-row quickstart scenario; ~2.7GB of touched columns in
HBM, ~2min load through the native ingest path).

Round-1 result on one v5e chip: 1.02B rows/s geomean (Q1 827M, Q6 1.25B),
vs_baseline 15.4.
"""

import json
import os
import time

import numpy as np


def main() -> None:
    sf = float(os.environ.get("SNAPPY_BENCH_SF", "16.0"))
    repeats = int(os.environ.get("SNAPPY_BENCH_REPEATS", "5"))

    from snappydata_tpu import SnappySession
    from snappydata_tpu.catalog import Catalog
    from snappydata_tpu.utils import tpch

    s = SnappySession(catalog=Catalog())
    t0 = time.time()
    tpch.load_tpch(s, sf=sf, seed=17)
    load_s = time.time() - t0
    n_rows = s.catalog.lookup_table("lineitem").data.snapshot().total_rows()

    timings = {}
    for name, q in (("q1", tpch.Q1), ("q6", tpch.Q6)):
        s.sql(q)  # compile + first run
        best = float("inf")
        for _ in range(repeats):
            t0 = time.time()
            s.sql(q)
            best = min(best, time.time() - t0)
        timings[name] = best

    rows_per_s = {k: n_rows / v for k, v in timings.items()}
    geomean = float(np.sqrt(rows_per_s["q1"] * rows_per_s["q6"]))
    baseline = 66e6  # see module docstring
    print(json.dumps({
        "metric": "rows/sec scanned+aggregated (TPC-H Q1/Q6 geomean, "
                  f"{n_rows}-row column table)",
        "value": round(geomean, 1),
        "unit": "rows/s",
        "vs_baseline": round(geomean / baseline, 3),
        "detail": {
            "sf": sf,
            "rows": n_rows,
            "load_s": round(load_s, 2),
            "q1_s": round(timings["q1"], 4),
            "q6_s": round(timings["q6"], 4),
            "q1_rows_per_s": round(rows_per_s["q1"], 1),
            "q6_rows_per_s": round(rows_per_s["q6"], 1),
        },
    }))


if __name__ == "__main__":
    main()
