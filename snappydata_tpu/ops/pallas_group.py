"""Pallas kernel: fused grouped aggregation for the dictionary fast path.

The flagship scan shape (TPC-H Q1: GROUP BY two dictionary-encoded
columns, a handful of SUM/AVG/COUNT slots) otherwise runs the packed
per-family reductions (ops/reduction.py) — on TPU the auto strategy is
G unrolled masked reductions over the packed block, each widening to
emulated float64 (accurate but ~3% of HBM bandwidth, round-4 verdict).
This kernel instead does the whole slot batch in ONE streaming pass:

- the [rows, 128] f32 plates stream block-by-block through VMEM;
- each of the 8x128 vector lanes keeps an independent Kahan
  (compensated) partial PER GROUP — carry shape [G, 8, 128] — so the
  hot loop is pure native-f32 vector ops (select + 4 adds per group),
  no f64 emulation and no scatter;
- all slots of the aggregate share the single group-index load: the
  kernel takes K value columns + per-slot null masks and produces K
  sets of partials in the same pass;
- the tiny [G, 8, 128] (sum, compensation) partials combine in exact
  float64 OUTSIDE the kernel: total = sum(s) - sum(c) (the Kahan
  c-holds-the-excess convention, same as ops/pallas_reduce.py).

COUNT accumulates in f32 (each lane's partial stays far below 2^24 —
exact) and combines in int64; MIN/MAX keep plain masked partials with
the same +/-inf fillers as the packed families, so empty groups match
the unrolled path bit-for-bit.

Gated behind `properties.pallas_group_reduce` (default OFF until
measured on hardware — bench.py records the side-by-side `q1_pallas_s`
when a TPU is reachable).  Eligibility mirrors the global kernel: f32
value plates only (the TPU storage contract already stores DOUBLE as
f32 plates), dictionary/bool fast-path group indexes with
G <= MAX_GROUPS, and the documented compensated-summation caveat
(error bounded vs sum(|v|), not |sum(v)|).  CPU runs use the
interpreter for correctness tests only.

Ref parity: SnappyHashAggregateExec's dictionary-key fast path — one
generated loop updating per-dictionary-code accumulators
(/root/reference/core/src/main/scala/org/apache/spark/sql/execution/
aggregate/SnappyHashAggregateExec.scala:73-109); this is the TPU-native
equivalent, with vector-lane-parallel compensated partials instead of
JVM double accumulators.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

_LANES = 128
_SUBLANES = 8

# rows per grid step. Smaller than pallas_reduce's 2048: the per-group
# carries cost ops * [G, 8, 128] f32 VMEM (G=64, 4 sums + count ->
# 9 * 256KB = 2.3MB), plus K+1 input blocks of [1024, 128].
_BLOCK_ROWS = 1024

# G cap, counting the +1 overflow segment the executor reserves for
# invalid rows. Matches reduction.UNROLL_MAX_SEGMENTS — the same
# dictionary-card regime where unrolled masked reductions beat scatters.
MAX_GROUPS = 64

_KINDS = ("sum", "count", "min", "max")

# Conservative VMEM budget for one fused call: double-buffered input
# blocks + the [G, 8, 128] carries must fit alongside pallas overhead
# in ~16MB. Callers use op_vmem_bytes() to stop fusing (overflow slots
# take the packed-family reductions) before a wide aggregate would fail
# the Mosaic compile outright.
VMEM_BUDGET = 12 * 1024 * 1024


def base_vmem_bytes() -> int:
    """Fixed cost: the double-buffered gidx input block."""
    return _BLOCK_ROWS * _LANES * 4 * 2


def op_vmem_bytes(kind: str, num_segments: int,
                  shared_mask: bool = False,
                  shared_value: bool = False) -> int:
    """Estimated VMEM this op adds: its input blocks (value f32 + mask
    bool, double-buffered) and its [G, 8, 128] f32 carries (two for
    Kahan sums). `shared_mask`/`shared_value`: the op reuses an
    already-counted input array — grouped_reduce deduplicates inputs
    by identity, so the block costs nothing extra."""
    blk = _BLOCK_ROWS * _LANES
    mask = 0 if shared_mask else blk * 1 * 2
    val = 0 if (kind == "count" or shared_value) else blk * 4 * 2
    carry = (num_segments * _SUBLANES * _LANES * 4
             * (2 if kind == "sum" else 1))
    return mask + val + carry


def _outs_of(kind: str) -> int:
    return 2 if kind == "sum" else 1


@functools.lru_cache(maxsize=64)
def _make_kernel(spec: Tuple[Tuple[str, Optional[int], int], ...],
                 n_in: int, G: int):
    """spec: one (kind, value_input_index, mask_input_index) per op —
    indices point into the DEDUPLICATED input list, so ops sharing a
    value or mask array (all of Q1's slots share one validity mask)
    read it from HBM once per block instead of once per op."""
    steps = _BLOCK_ROWS // _SUBLANES

    def kernel(gidx_ref, *refs):
        in_refs = refs[:n_in]
        out_refs = refs[n_in:]
        pid = pl.program_id(0)
        shape = (G, _SUBLANES, _LANES)

        @pl.when(pid == 0)
        def _init():
            oi = 0
            for k, _vi, _mi in spec:
                if k == "sum":
                    out_refs[oi][...] = jnp.zeros(shape, jnp.float32)
                    out_refs[oi + 1][...] = jnp.zeros(shape, jnp.float32)
                    oi += 2
                elif k == "count":
                    out_refs[oi][...] = jnp.zeros(shape, jnp.float32)
                    oi += 1
                elif k == "min":
                    out_refs[oi][...] = jnp.full(shape, jnp.inf, jnp.float32)
                    oi += 1
                else:  # max
                    out_refs[oi][...] = jnp.full(shape, -jnp.inf, jnp.float32)
                    oi += 1

        # continue the running chains from the previous block (or the
        # identities just written): output blocks map to the same
        # buffer at every grid step, so they persist across steps
        carry0 = tuple(r[...] for r in out_refs)
        garange = jax.lax.broadcasted_iota(jnp.int32, shape, 0)

        def body(i, carry):
            sl = pl.ds(i * _SUBLANES, _SUBLANES)
            gblk = gidx_ref[sl, :]
            gm = gblk[None].astype(jnp.int32) == garange  # [G, 8, 128]
            # one VMEM load + one group-select per UNIQUE input block
            loaded = {}
            sels = {}

            def sel_of(mi):
                if mi not in sels:
                    sels[mi] = gm & in_refs[mi][sl, :][None]
                return sels[mi]

            def val_of(vi):
                if vi not in loaded:
                    loaded[vi] = in_refs[vi][sl, :]
                return loaded[vi]

            new = []
            oi = 0
            for k, vi, mi in spec:
                sel = sel_of(mi)
                if k == "count":
                    new.append(carry[oi]
                               + jnp.where(sel, 1.0, 0.0).astype(jnp.float32))
                    oi += 1
                    continue
                v = val_of(vi)
                if k == "sum":
                    s, c = carry[oi], carry[oi + 1]
                    vv = jnp.where(sel, v[None], 0.0)
                    # Kahan: masked-out lanes add 0.0, which re-folds the
                    # compensation into s (harmless: s - c is preserved)
                    y = vv - c
                    t = s + y
                    new.append(t)
                    new.append((t - s) - y)
                    oi += 2
                elif k == "min":
                    new.append(jnp.minimum(
                        carry[oi], jnp.where(sel, v[None], jnp.inf)))
                    oi += 1
                else:  # max
                    new.append(jnp.maximum(
                        carry[oi], jnp.where(sel, v[None], -jnp.inf)))
                    oi += 1
            return tuple(new)

        final = jax.lax.fori_loop(0, steps, body, carry0)
        for r, val in zip(out_refs, final):
            r[...] = val

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("spec", "G", "interpret"))
def _grouped_call(gidx2d, ins,
                  spec: Tuple[Tuple[str, Optional[int], int], ...],
                  G: int, interpret: bool):
    rows = gidx2d.shape[0]
    nblocks = rows // _BLOCK_ROWS
    blk = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    out_blk = pl.BlockSpec((G, _SUBLANES, _LANES), lambda i: (0, 0, 0))
    kinds = tuple(k for k, _, _ in spec)
    n_out = sum(_outs_of(k) for k in kinds)
    outs = pl.pallas_call(
        _make_kernel(spec, len(ins), G),
        grid=(nblocks,),
        in_specs=[blk] * (1 + len(ins)),
        out_specs=(out_blk,) * n_out,
        out_shape=tuple(
            jax.ShapeDtypeStruct((G, _SUBLANES, _LANES), jnp.float32)
            for _ in range(n_out)),
        interpret=interpret,
    )(gidx2d, *ins)

    results = []
    oi = 0
    for k in kinds:
        if k == "sum":
            s, c = outs[oi], outs[oi + 1]
            oi += 2
            results.append(jnp.sum(s.astype(jnp.float64), axis=(1, 2))
                           - jnp.sum(c.astype(jnp.float64), axis=(1, 2)))
        elif k == "count":
            # per-lane f32 partials are exact integers (< 2^24 each);
            # the cross-lane combine happens in int64
            results.append(jnp.sum(outs[oi].astype(jnp.int64), axis=(1, 2)))
            oi += 1
        elif k == "min":
            results.append(jnp.min(outs[oi], axis=(1, 2)))
            oi += 1
        else:
            results.append(jnp.max(outs[oi], axis=(1, 2)))
            oi += 1
    return tuple(results)


def grouped_reduce(ops: Sequence[Tuple[str, Optional[jnp.ndarray],
                                       jnp.ndarray]],
                   gidx: jnp.ndarray, num_segments: int,
                   interpret: Optional[bool] = None) -> List[jnp.ndarray]:
    """Fused segmented reduction of all `ops` in one streaming pass.

    ops: (kind, values, mask) per aggregate slot — kind in
    sum/count/min/max, values an f32 array (None for count), mask the
    slot's validity (row valid AND value non-null). gidx: int group
    index per element, < num_segments <= MAX_GROUPS. Returns one
    [num_segments] array per op: f64 for sums, int64 for counts, f32
    (with +/-inf empty-group fillers, matching the packed families)
    for min/max.
    """
    assert 1 <= num_segments <= MAX_GROUPS, num_segments
    kinds = tuple(k for k, _, _ in ops)
    assert all(k in _KINDS for k in kinds), kinds
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    n = gidx.reshape(-1).shape[0]
    tile = _BLOCK_ROWS * _LANES
    padded = max(tile, ((n + tile - 1) // tile) * tile)

    def prep(a, dtype):
        flat = a.reshape(-1).astype(dtype)
        if padded != n:
            flat = jnp.pad(flat, (0, padded - n))
        return flat.reshape(-1, _LANES)

    # padded rows carry mask=False, so their gidx value is irrelevant
    gidx2d = prep(gidx, jnp.int32)
    # deduplicate inputs by source-array identity: slots that share a
    # validity mask (Q1: all of them) or a value column (sum(x)+min(x))
    # cross HBM once per block, not once per op
    ins: List[jnp.ndarray] = []
    index_of: Dict[Tuple[int, str], int] = {}

    def intern(arr, role: str, dtype) -> int:
        key = (id(arr), role)
        got = index_of.get(key)
        if got is None:
            got = len(ins)
            ins.append(prep(arr, dtype))
            index_of[key] = got
        return got

    spec = []
    for k, v, m in ops:
        vi = None if k == "count" else intern(v, "v", jnp.float32)
        mi = intern(m, "m", jnp.bool_)
        spec.append((k, vi, mi))

    outs = _grouped_call(gidx2d, tuple(ins), tuple(spec), num_segments,
                         interpret)
    return list(outs)


def pallas_group_available() -> bool:
    """True when the TPU lowering path is usable on this backend."""
    return jax.default_backend() == "tpu"


# ==========================================================================
# Fused decode+filter+grouped-aggregate: the TPC-H Q1 shape over ENCODED
# batches.  Value inputs arrive as VALUE_DICT code plates plus per-batch
# dictionaries; each sum slot is a product of an optional PLAIN factor
# and any number of CODE factors, decoded INSIDE the kernel from SMEM
# dictionaries (so `sum(price * (1 - disc))` passes price plain and disc
# codes with a HOST-transformed dictionary 1-dict — dictionary-space
# preprocessing is O(D), row-space stays encoded).  Grid is
# (batch, block) so dictionaries index by batch; the per-group per-lane
# Kahan discipline matches grouped_reduce above.  All slots share one
# row mask (the Q1 shape: one filter, null-free measure columns) — the
# generic engine keeps per-slot null masks.
# ==========================================================================

_CBLOCK_ROWS = 512   # multiple of 32 (small-int tiles) and 8 (f32)


@functools.lru_cache(maxsize=32)
def _make_code_kernel(spec: Tuple, n_vmem: int, n_dict: int, G: int):
    """spec: per slot ("count",) or ("sum", plain_idx_or_None,
    ((code_vmem_idx, dict_idx), ...)) — VMEM indices point into the
    [gidx, mask, *values] block list, dict indices into the SMEM list."""
    steps = _CBLOCK_ROWS // _SUBLANES

    def kernel(*refs):
        gidx_ref = refs[0]
        mask_ref = refs[1]
        vmem = refs[:n_vmem]
        dicts = refs[n_vmem:n_vmem + n_dict]
        out_refs = refs[n_vmem + n_dict:]
        b = pl.program_id(0)
        s = pl.program_id(1)
        shape = (G, _SUBLANES, _LANES)

        @pl.when((b == 0) & (s == 0))
        def _init():
            for r in out_refs:
                r[...] = jnp.zeros(shape, jnp.float32)

        garange = jax.lax.broadcasted_iota(jnp.int32, shape, 0)

        def body(i, carry):
            sl = pl.ds(i * _SUBLANES, _SUBLANES)
            gblk = gidx_ref[0, sl, :]
            mblk = mask_ref[0, sl, :]
            sel = (gblk[None].astype(jnp.int32) == garange) & mblk[None]
            new = []
            oi = 0
            for op in spec:
                if op[0] == "count":
                    new.append(carry[oi]
                               + jnp.where(sel, 1.0, 0.0))
                    oi += 1
                    continue
                _, plain_idx, factors = op
                v = vmem[plain_idx][0, sl, :] if plain_idx is not None \
                    else jnp.ones((_SUBLANES, _LANES), jnp.float32)
                for cvi, dvi in factors:
                    codes = vmem[cvi][0, sl, :].astype(jnp.int32)
                    dref = dicts[dvi]
                    dval = jnp.zeros((_SUBLANES, _LANES), jnp.float32)

                    def dec(k, acc, _c=codes, _d=dref):
                        return jnp.where(_c == k, _d[0, k], acc)

                    dval = jax.lax.fori_loop(0, dref.shape[1], dec, dval)
                    v = v * dval
                sm, cp = carry[oi], carry[oi + 1]
                vv = jnp.where(sel, v[None], 0.0)
                y = vv - cp
                t = sm + y
                new.append(t)
                new.append((t - sm) - y)
                oi += 2
            return tuple(new)

        final = jax.lax.fori_loop(0, steps, body,
                                  tuple(r[...] for r in out_refs))
        for r, val in zip(out_refs, final):
            r[...] = val

    return kernel


@functools.partial(jax.jit, static_argnames=("spec", "G", "dshapes",
                                             "interpret"))
def _grouped_code_call(vmem_ins, dict_ins, spec, G: int, dshapes,
                       interpret: bool):
    B, capr, _ = vmem_ins[0].shape
    S = capr // _CBLOCK_ROWS
    from jax.experimental.pallas import tpu as pltpu

    blk = pl.BlockSpec((1, _CBLOCK_ROWS, _LANES), lambda b, s: (b, s, 0))
    out_blk = pl.BlockSpec((G, _SUBLANES, _LANES), lambda b, s: (0, 0, 0))
    n_out = sum(1 if op[0] == "count" else 2 for op in spec)
    outs = pl.pallas_call(
        _make_code_kernel(spec, len(vmem_ins), len(dict_ins), G),
        grid=(B, S),
        in_specs=[blk] * len(vmem_ins) + [
            pl.BlockSpec((1, d), lambda b, s: (b, 0),
                         memory_space=pltpu.SMEM) for d in dshapes],
        out_specs=(out_blk,) * n_out,
        out_shape=tuple(
            jax.ShapeDtypeStruct((G, _SUBLANES, _LANES), jnp.float32)
            for _ in range(n_out)),
        interpret=interpret,
    )(*vmem_ins, *dict_ins)
    results = []
    oi = 0
    for op in spec:
        if op[0] == "count":
            results.append(jnp.sum(outs[oi].astype(jnp.int64),
                                   axis=(1, 2)))
            oi += 1
        else:
            s, c = outs[oi], outs[oi + 1]
            oi += 2
            results.append(jnp.sum(s.astype(jnp.float64), axis=(1, 2))
                           - jnp.sum(c.astype(jnp.float64), axis=(1, 2)))
    return tuple(results)


def grouped_code_reduce(gidx, mask, slots, num_segments: int,
                        interpret: Optional[bool] = None):
    """Fused decode+filter+grouped reduction over code plates.

    gidx: [B, cap] int group index (< num_segments <= MAX_GROUPS);
    mask: [B, cap] bool shared row mask (valid & filter);
    slots: sequence of ("count",) or ("sum", plain_or_None, factors)
      with plain a [B, cap] float array and factors a sequence of
      (codes [B, cap] uint8/uint16, dicts [B, D] float) pairs — the
      slot value is plain * Π decode(codes_k).
    Returns one [num_segments] array per slot: int64 for counts,
    float64 for sums."""
    assert 1 <= num_segments <= MAX_GROUPS, num_segments
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    gidx = jnp.asarray(gidx)
    B, cap = gidx.shape
    capr = cap // _LANES
    pad_r = ((capr + _CBLOCK_ROWS - 1) // _CBLOCK_ROWS) * _CBLOCK_ROWS
    pad_cap = pad_r * _LANES

    def shape3(a, dtype):
        a = jnp.asarray(a)
        if pad_cap != cap:
            a = jnp.pad(a, ((0, 0), (0, pad_cap - cap)))
        return a.reshape(B, pad_r, _LANES).astype(dtype)

    vmem: List = [shape3(gidx, jnp.int32), shape3(mask, jnp.bool_)]
    dict_ins: List = []
    spec = []
    for slot in slots:
        if slot[0] == "count":
            spec.append(("count",))
            continue
        _, plain, factors = slot
        pi = None
        if plain is not None:
            pi = len(vmem)
            vmem.append(shape3(plain, jnp.float32))
        fs = []
        for codes, dicts in factors:
            cvi = len(vmem)
            vmem.append(shape3(codes, jnp.asarray(codes).dtype))
            dvi = len(dict_ins)
            dict_ins.append(jnp.asarray(dicts, dtype=jnp.float32))
            fs.append((cvi, dvi))
        spec.append(("sum", pi, tuple(fs)))
    dshapes = tuple(int(d.shape[1]) for d in dict_ins)
    return list(_grouped_code_call(tuple(vmem), tuple(dict_ins),
                                   tuple(spec), int(num_segments),
                                   dshapes, bool(interpret)))
