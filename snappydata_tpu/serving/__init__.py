"""Prepared-statement serving subsystem.

`prepared.py` — compile-once parameterized plans: a per-catalog registry
of analyzed+tokenized query shapes whose `?` binds are runtime arguments
of one jitted XLA program (`session.prepare(sql)`, SQL `PREPARE name AS
... / EXECUTE name (...)`).

`batcher.py` — adaptive micro-batching: concurrent executes of one
prepared plan fuse into a single `jax.vmap`-over-the-parameter-axis
device dispatch (`serving_batch_max` / `serving_batch_wait_us`), with
per-request admission, cancellation and timeouts intact.
"""

from snappydata_tpu.serving.prepared import (PreparedStatement,
                                             PreparedPlan, ServingError,
                                             ServingRegistry, registry_for,
                                             serving_registry_nbytes)
from snappydata_tpu.serving.batcher import global_batcher

__all__ = ["PreparedStatement", "PreparedPlan", "ServingError",
           "ServingRegistry", "registry_for", "serving_registry_nbytes",
           "global_batcher", "serving_snapshot"]


def serving_snapshot(catalog=None) -> dict:
    """Serving-path stats for REST `GET /status/api/v1/serving` and the
    dashboard: live knobs, registry population, and the counters that
    prove the two claims — serving_prepared_hits (executes that skipped
    parse/analyze/tokenize entirely) and serving_batched_dispatches /
    serving_batch_occupancy (how many requests shared one device
    dispatch)."""
    from snappydata_tpu import config
    from snappydata_tpu.observability.metrics import global_registry

    snap = global_registry().snapshot()
    c = snap["counters"]
    props = config.global_properties()
    dispatches = c.get("serving_batched_dispatches", 0)
    fused = c.get("serving_batch_requests", 0)
    out = {
        "serving_batch_max": props.get("serving_batch_max"),
        "serving_batch_wait_us": props.get("serving_batch_wait_us"),
        "serving_max_handles": props.get("serving_max_handles"),
        "serving_prepared_hits": c.get("serving_prepared_hits", 0),
        "serving_prepared_misses": c.get("serving_prepared_misses", 0),
        "serving_reprepares": c.get("serving_reprepares", 0),
        "serving_passthrough": c.get("serving_passthrough", 0),
        "serving_batched_dispatches": dispatches,
        "serving_batch_requests": fused,
        "serving_batch_occupancy":
            round(fused / dispatches, 2) if dispatches else None,
        "serving_straight_through": c.get("serving_straight_through", 0),
        "serving_batch_fallbacks": c.get("serving_batch_fallbacks", 0),
        "serving_vmap_compiles": c.get("serving_vmap_compiles", 0),
        "serving_bulk_transfers": c.get("serving_bulk_transfers", 0),
        "serving_handle_evictions": c.get("serving_handle_evictions", 0),
        "plan_cache_evictions": c.get("plan_cache_evictions", 0),
        "serving_registry_nbytes": serving_registry_nbytes(),
    }
    if catalog is not None:
        reg = getattr(catalog, "_serving_registry", None)
        out["handles"] = reg.describe() if reg is not None else []
    return out
