"""MVCC snapshot-isolation epochs: versioned storage that decouples
scans from ingest.

The reference runs snapshot-isolation transactions around its store
writes (JDBCSourceAsColumnarStore beginTx/commitTx); here the storage
layer is already MVCC-shaped — batches are write-once, mutations are
delta'd, and every committed write publishes a fresh immutable
``Manifest`` — so snapshot isolation is a thin layer over what exists:

- **Epoch clock**: a process-wide monotone counter.  Every manifest
  publish stamps the next epoch (and, on durable sessions, the WAL seq
  of the committing statement — the commit timestamp).  Recovery seeds
  the clock past the checkpoint/WAL fences so the vector stays
  monotone across restarts.

- **Pins**: a query pins ONE consistent cross-table cut at statement
  start (``pinned_scope``).  The cut is atomic — publishes swap their
  manifest under the same clock lock the pin capture holds — so a join
  over two tables can never see table A before a commit and table B
  after it.  Tables the statement discovers later (view expansions,
  matview backing tables re-written by sync, scratch tables) extend
  the pin at first read.  Row tables, which mutate in place, are
  captured as host-array snapshots at first read (repeatable reads
  within the statement).

- **Reads**: every scan-shaped read goes through ``snapshot_of`` /
  ``row_snapshot_of`` — the device bind (`storage/device._scan_units`),
  the host fallback, the LIMIT-n early-stop scan, join key encodes and
  the tiled-aggregate pass all resolve the pinned manifest instead of
  the live one.  The gidx/join/build caches need no changes: their
  bind-identity keys already version by the manifest's ``valid`` array,
  which differs per pinned version.

- **Retention**: a pinned manifest is kept alive by refcounts
  (``data._retained_epochs``); on top of pins a short unpinned history
  (``mvcc_retained_epochs``) is retained for observability.  Retained
  bytes ride the resource broker's ledger (``retained_epoch_bytes``)
  and the degradation ladder trims the oldest unpinned epochs (and
  their stale device-cache plates) under memory pressure.

- **Writers never wait on readers**: ingest, DML and compaction publish
  new manifests without holding ``mutation_lock`` across any scan; the
  one remaining read-under-mutation-lock (matview ``refresh_full``)
  was rebuilt on top of pins + a pending-fold journal (views/matview).

DDL that would mutate state a pinned reader is traversing IN PLACE
(``DROP COLUMN`` remaps dictionaries and shifts ordinals) raises a
typed ``SnapshotConflictError`` (SQLSTATE 40001) while pins are
active; TRUNCATE/ADD COLUMN/DROP TABLE bump the epoch cleanly —
pinned readers keep their immutable manifests.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from snappydata_tpu.utils import locks
from typing import Dict, List, Optional, Tuple

import numpy as np


class SnapshotConflictError(RuntimeError):
    """DDL raced an active pinned snapshot in a way MVCC cannot make
    safe (in-place dictionary remap / ordinal shift).  SQLSTATE 40001
    (serialization failure) — the client retries once readers drain."""

    sqlstate = "40001"

    def __init__(self, msg: str):
        super().__init__(f"{msg} [SQLSTATE {self.sqlstate}]")


# --------------------------------------------------------------------------
# epoch clock
# --------------------------------------------------------------------------

# One lock orders everything cheap: epoch bumps, manifest swaps
# (ColumnTableData._publish takes it around the reference swap), pin
# capture, and retention refcounts.  Nothing slow ever runs under it —
# that is the whole point of the subsystem.
_clock_lock = locks.named_rlock("mvcc.clock")
_epoch = [0]


def clock():
    """The shared epoch lock (context manager).  ``_publish`` swaps its
    manifest reference under it so pin captures are atomic cuts."""
    return _clock_lock


def current_epoch() -> int:
    return _epoch[0]


def _bump_epoch_locked() -> int:
    _epoch[0] += 1
    return _epoch[0]


def advance_to(seq: int) -> None:
    """Recovery: resume the clock past a checkpoint/WAL fence so
    post-recovery epochs stay monotone with pre-crash ones."""
    with _clock_lock:
        if int(seq) > _epoch[0]:
            _epoch[0] = int(seq)


# WAL seq of the committing statement, set by the session's journal
# paths (and WAL replay) around apply — ``_publish`` stamps it on the
# manifest as the commit timestamp.
_commit_seq: contextvars.ContextVar = contextvars.ContextVar(
    "mvcc_commit_seq", default=0)


@contextlib.contextmanager
def commit_scope(seq: int):
    tok = _commit_seq.set(int(seq))
    try:
        yield
    finally:
        _commit_seq.reset(tok)


def current_commit_seq() -> int:
    return _commit_seq.get()


def enabled() -> bool:
    from snappydata_tpu import config

    return bool(config.global_properties().get("snapshot_isolation", True))


def _retain_cap() -> int:
    from snappydata_tpu import config

    try:
        return max(0, int(config.global_properties().get(
            "mvcc_retained_epochs", 2)))
    except (TypeError, ValueError):
        return 2


def _reg():
    from snappydata_tpu.observability.metrics import global_registry

    return global_registry()


# --------------------------------------------------------------------------
# publish-side hooks (called by ColumnTableData._publish under clock())
# --------------------------------------------------------------------------

def retain_locked(data, old_manifest) -> None:
    """Move the just-superseded manifest into the table's retained-epoch
    list.  Pinned versions stay for as long as any pin holds them; on
    top of that the most recent ``mvcc_retained_epochs`` unpinned
    manifests are kept (observability / short pins racing the publish).
    Caller holds the clock lock."""
    retained = getattr(data, "_retained_epochs", None)
    if retained is None:
        retained = data._retained_epochs = {}
    retained[int(old_manifest.version)] = old_manifest
    _trim_retained_locked(data)


def _trim_retained_locked(data, keep_unpinned: Optional[int] = None) -> int:
    retained = getattr(data, "_retained_epochs", None)
    if not retained:
        return 0
    pins = getattr(data, "_pin_counts", {})
    cap = _retain_cap() if keep_unpinned is None else keep_unpinned
    unpinned = sorted(v for v in retained if v not in pins)
    dropped = 0
    for v in unpinned[:max(0, len(unpinned) - cap)]:
        retained.pop(v, None)
        dropped += 1
    return dropped


# --------------------------------------------------------------------------
# pin refcounts
# --------------------------------------------------------------------------

def _ref_locked(data, manifest) -> None:
    counts = getattr(data, "_pin_counts", None)
    if counts is None:
        counts = data._pin_counts = {}
    v = int(manifest.version)
    counts[v] = counts.get(v, 0) + 1
    retained = getattr(data, "_retained_epochs", None)
    if retained is None:
        retained = data._retained_epochs = {}
    retained.setdefault(v, manifest)


def _unref(data, manifest) -> None:
    with _clock_lock:
        counts = getattr(data, "_pin_counts", None)
        if not counts:
            return
        v = int(manifest.version)
        n = counts.get(v, 0) - 1
        if n > 0:
            counts[v] = n
            return
        counts.pop(v, None)
        # an unpinned retained epoch survives only inside the history cap
        _trim_retained_locked(data)


def _ref_row_locked(data, version: int) -> None:
    counts = getattr(data, "_row_pin_counts", None)
    if counts is None:
        counts = data._row_pin_counts = {}
    counts[int(version)] = counts.get(int(version), 0) + 1


def _unref_row(data, version: int) -> None:
    with _clock_lock:
        counts = getattr(data, "_row_pin_counts", None)
        if not counts:
            return
        v = int(version)
        n = counts.get(v, 0) - 1
        if n > 0:
            counts[v] = n
        else:
            counts.pop(v, None)
            # the shared host-snapshot of a now-unpinned old version is
            # dead weight (the current version re-captures on demand)
            cache = getattr(data, "_row_snapshot_cache", None)
            if cache is not None and v != int(getattr(data, "version", v)):
                cache.pop(v, None)


def _captured_row_arrays(data) -> Tuple[list, list, int, int]:
    """(arrays, null masks, n, version): the host materialization of a
    row table at its current version, shared through a per-version
    cache on the data object.  Consumers treat captured arrays as
    read-only — the same discipline sharing within one pinned statement
    already requires.  Row tables mutate IN PLACE, so without this
    every pinned statement would pay an O(table) Python-loop conversion
    per bind even when the device cache is warm."""
    cache = getattr(data, "_row_snapshot_cache", None)
    if cache is None:
        cache = data._row_snapshot_cache = {}
    ver = int(data.version)
    got = cache.get(ver)
    if got is not None:
        return got[0], got[1], got[2], ver
    arrays, masks, n = data.to_arrays_with_nulls()
    if int(data.version) != ver:
        # a mutation raced the copy: serve it privately, never cache
        return arrays, masks, n, ver
    with _clock_lock:
        cache[ver] = (arrays, masks, n)
        pinned = getattr(data, "_row_pin_counts", {})
        for v in [v for v in cache if v != ver and v not in pinned]:
            cache.pop(v, None)
    return arrays, masks, n, ver


def pinned_versions(data) -> frozenset:
    """Manifest versions some active pin holds on `data` — the device
    cache must not prune their entries mid-scan."""
    counts = getattr(data, "_pin_counts", None)
    if not counts:
        return frozenset()
    with _clock_lock:   # snapshot under the lock refs mutate beneath
        return frozenset(counts)


def pinned_versions_peek(data):
    """LOCK-FREE best-effort read of `pinned_versions` for callers that
    already hold a lock BELOW mvcc.clock (the device-cache budget's
    pin-aware eviction) — taking the clock there would add a
    device_cache -> clock edge the hierarchy forbids.  Returns None when
    the racing snapshot fails; treat None as "assume pinned" (skip the
    eviction) — a stale positive only delays one eviction."""
    counts = getattr(data, "_pin_counts", None)
    if not counts:
        return frozenset()
    try:
        return frozenset(counts)
    except RuntimeError:   # dict mutated mid-iteration
        return None


def pinned_row_versions(data) -> frozenset:
    counts = getattr(data, "_row_pin_counts", None)
    if not counts:
        return frozenset()
    with _clock_lock:
        return frozenset(counts)


def has_pins(data) -> bool:
    return bool(getattr(data, "_pin_counts", None)) \
        or bool(getattr(data, "_row_pin_counts", None))


def _check_pins_locked(data, what: str) -> None:
    if has_pins(data):
        _reg().inc("mvcc_ddl_conflicts")
        raise SnapshotConflictError(
            f"{what} conflicts with an active pinned snapshot "
            f"(a concurrent query is reading this table); retry when "
            f"readers drain")


def check_ddl(data, what: str) -> None:
    """Early (pre-WAL) gate for DDL that mutates storage state IN PLACE
    (dictionary remaps, ordinal shifts): refuse with a typed retryable
    error while any pinned snapshot could be traversing the old layout.
    DDL that publishes a fresh manifest (TRUNCATE, ADD COLUMN, DROP
    TABLE) needs no gate — pinned readers keep their immutable epoch.
    The mutation itself must run under ``ddl_scope``, which re-checks
    AND blocks new pins for its duration — a bare check alone leaves a
    check-then-mutate window where a pin admitted mid-remap would
    traverse half-shifted state."""
    with _clock_lock:
        _check_pins_locked(data, what)


def _ddl_gate_locked(data) -> None:
    """Pin-capture side of the DDL fence (caller holds the clock lock):
    refuse to pin a table whose in-place remap is mid-flight.  Typed
    and retryable, symmetric with the writer-side 40001."""
    if getattr(data, "_ddl_in_progress", 0):
        _reg().inc("mvcc_ddl_conflicts")
        raise SnapshotConflictError(
            "query admission raced in-place DDL (ALTER TABLE DROP "
            "COLUMN) on this table; retry when it completes")


@contextlib.contextmanager
def ddl_scope(data, what: str):
    """Bracket an in-place DDL mutation: refuses (40001) while pins
    exist and blocks NEW pins until the mutation finishes, closing the
    TOCTOU window between the pin check and the remap.  The clock lock
    is held only for the entry/exit bookkeeping, never across the
    remap itself."""
    with _clock_lock:
        _check_pins_locked(data, what)
        data._ddl_in_progress = getattr(data, "_ddl_in_progress", 0) + 1
    try:
        yield
    finally:
        with _clock_lock:
            data._ddl_in_progress -= 1


# --------------------------------------------------------------------------
# the pin
# --------------------------------------------------------------------------

class SnapshotPin:
    """One statement's consistent cut: {table data -> pinned Manifest}
    (+ captured host snapshots for in-place row tables).  Extended at
    first read for tables the statement discovers late; released once
    at statement end."""

    __slots__ = ("epoch", "_manifests", "_rows", "_datas", "_lock",
                 "released")

    def __init__(self):
        self.epoch = current_epoch()
        self._manifests: Dict[int, object] = {}
        self._rows: Dict[int, tuple] = {}
        self._datas: Dict[int, object] = {}
        self._lock = locks.named_lock("mvcc.pin")
        self.released = False

    # -- column tables -----------------------------------------------------

    def pin_many(self, datas) -> None:
        """Atomic cross-table capture: all manifests read under ONE
        clock-lock hold, so no commit can interleave between tables."""
        with _clock_lock:
            if self.released:
                return
            # gate-check every table BEFORE reffing any, so a raced
            # in-place DDL aborts the capture without partial refs
            # from THIS call (earlier captures release via the pin)
            for data in datas:
                _ddl_gate_locked(data)
            for data in datas:
                key = id(data)
                if key in self._manifests:
                    continue
                m = data._manifest
                self._manifests[key] = m
                self._datas[key] = data
                _ref_locked(data, m)

    def manifest_for(self, data):
        got = self._manifests.get(id(data))
        if got is not None:
            return got
        with _clock_lock:
            if self.released:
                # a straggler thread (copied context outliving the
                # statement) extending a released pin: serve the live
                # manifest and hold NOTHING — a ref taken here would
                # never be released (release already ran)
                return data._manifest
            got = self._manifests.get(id(data))
            if got is None:
                _ddl_gate_locked(data)
                got = data._manifest
                self._manifests[id(data)] = got
                self._datas[id(data)] = data
                _ref_locked(data, got)
        return got

    def repin(self, data):
        """Re-capture `data` at its CURRENT manifest.  Matview sync uses
        this (briefly under ``mutation_lock``) so the base table's
        pinned epoch lands exactly where the view's folded state is —
        base and view then agree to the row."""
        with _clock_lock:
            cur = data._manifest
            if self.released:
                return cur
            old = self._manifests.get(id(data))
            if old is cur:
                return cur
            self._manifests[id(data)] = cur
            self._datas[id(data)] = data
            _ref_locked(data, cur)
        if old is not None:
            _unref(data, old)
        _reg().inc("mvcc_repins")
        return cur

    def repin_row(self, data) -> None:
        """Drop the captured host snapshot of a ROW table so the next
        read re-captures at the CURRENT version — the row-table analogue
        of ``repin`` (matview refresh under ``mutation_lock`` uses it:
        the pin's earlier capture may predate the refresh fence)."""
        key = id(data)
        with self._lock:
            got = self._rows.pop(key, None)
        if got is not None:
            _unref_row(data, got[3])
            _reg().inc("mvcc_repins")

    # -- row tables (in-place storage: capture on first read) --------------

    def row_snapshot(self, data) -> tuple:
        key = id(data)
        got = self._rows.get(key)
        if got is not None:
            return got
        with _clock_lock:
            _ddl_gate_locked(data)
        arrays, masks, n, ver = _captured_row_arrays(data)
        with self._lock:
            if self.released:
                return (arrays, masks, n, ver)   # live read, hold nothing
            got = self._rows.get(key)
            if got is None:
                got = (arrays, masks, n, ver)
                self._rows[key] = got
                self._datas.setdefault(key, data)
                with _clock_lock:
                    _ref_row_locked(data, ver)
        return got

    # -- lifecycle ---------------------------------------------------------

    def release(self) -> None:
        # drain under BOTH locks: manifest_for/pin_many/repin mutate the
        # dicts under the clock lock, row_snapshot under self._lock —
        # holding both (same self._lock -> clock order row_snapshot
        # uses) means no extension can interleave with the drain, and
        # the released flag is visible under whichever lock a reader
        # holds
        with self._lock, _clock_lock:
            if self.released:
                return
            self.released = True
            manifests = [(self._datas[k], m)
                         for k, m in self._manifests.items()]
            rows = [(self._datas[k], v[3]) for k, v in self._rows.items()]
            self._manifests.clear()
            self._rows.clear()
            self._datas.clear()
            _ACTIVE_PINS.discard(self)
        for data, m in manifests:
            _unref(data, m)
        for data, ver in rows:
            _unref_row(data, ver)
        _reg().inc("mvcc_pin_releases")


_pin_var: contextvars.ContextVar = contextvars.ContextVar(
    "mvcc_pin", default=None)
_ACTIVE_PINS: set = set()


def current_pin() -> Optional[SnapshotPin]:
    return _pin_var.get()


def active_pin_count() -> int:
    with _clock_lock:
        return len(_ACTIVE_PINS)


@contextlib.contextmanager
def pinned_scope(catalog, table_names=()):
    """Pin one consistent snapshot for the duration of a statement.
    No-op (yields the ambient pin) when nested — tile partials, matview
    syncs, subquery rewrites and scratch merges all read the OUTER
    statement's epoch.  Matview backing tables are excluded from the
    eager cut: sync() rewrites them under this very pin, and the query
    must read the post-sync rows (they pin at first read instead)."""
    ambient = _pin_var.get()
    if ambient is not None or not enabled():
        yield ambient
        return
    pin = SnapshotPin()
    datas = []
    seen = set()
    names = list(table_names or ())
    while names:
        nm = names.pop()
        low = str(nm).lower()
        if low in seen:
            continue
        seen.add(low)
        info = catalog.lookup_table(nm) if catalog is not None else None
        if info is None:
            # plain views: expand one level so the cut covers the
            # underlying tables a late analysis would touch
            view = catalog.lookup_view(nm) if catalog is not None else None
            if view is not None:
                try:
                    from snappydata_tpu.session import _referenced_tables

                    names.extend(_referenced_tables(view))
                except Exception:
                    # best-effort widening only: the unexpanded table
                    # still pins at first read — count it so a broken
                    # view expansion is visible
                    _reg().inc("mvcc_cut_expand_errors")
            continue
        if info.options.get("materialized_view"):
            continue   # pinned at first read, AFTER sync rewrites it
        maints = getattr(catalog, "_sample_maintainers", None)
        if maints and info.name in maints:
            # SAMPLE tables are lazily rebuilt (truncate + re-insert from
            # the reservoir) inside the statement, like matview sync —
            # pin at first read, AFTER the refresh publishes
            continue
        if hasattr(info.data, "_manifest"):
            datas.append(info.data)
    try:
        pin.pin_many(datas)
    except SnapshotConflictError:
        pin.release()   # drop any refs an earlier capture took
        raise
    with _clock_lock:
        _ACTIVE_PINS.add(pin)
    _reg().inc("mvcc_pins")
    from snappydata_tpu.observability import tracing

    tracing.annotate("pinned_epoch", pin.epoch)
    tok = _pin_var.set(pin)
    try:
        yield pin
    finally:
        _pin_var.reset(tok)
        pin.release()


@contextlib.contextmanager
def unpinned_scope():
    """Suspend the ambient pin for statement-PRIVATE storage: matview
    scratch tables (``__mv_delta`` / ``__mv_partials``) are truncated,
    re-filled and re-read MANY times within one outer statement, so
    capturing them into the outer cut would serve the first rewrite's
    manifest to every later read (stale-fold corruption — the second
    fold of a pinned statement would re-aggregate the first fold's
    rows).  Reads inside resolve live manifests; the outer pin resumes
    on exit."""
    tok = _pin_var.set(None)
    try:
        yield
    finally:
        _pin_var.reset(tok)


# --------------------------------------------------------------------------
# pin-aware read helpers (THE seam every scan-shaped read goes through)
# --------------------------------------------------------------------------

def snapshot_of(data):
    """The manifest a read of `data` should traverse: the ambient pin's
    (extending the pin at first read) or, unpinned, the live one."""
    pin = _pin_var.get()
    if pin is not None and hasattr(data, "_manifest"):
        return pin.manifest_for(data)
    return data.snapshot()


def row_snapshot_of(data) -> Tuple[list, list, int, int]:
    """(arrays, null masks, n, version) of a ROW table — the ambient
    pin's captured copy (repeatable reads: the table mutates in place)
    or a fresh read."""
    pin = _pin_var.get()
    if pin is not None:
        return pin.row_snapshot(data)
    arrays, masks, n = data.to_arrays_with_nulls()
    return arrays, masks, n, int(data.version)


# --------------------------------------------------------------------------
# retained-epoch accounting (resource broker ledger + degradation)
# --------------------------------------------------------------------------

def _arr_bytes(a) -> int:
    if a is None:
        return 0
    if isinstance(a, np.ndarray) and a.dtype == object:
        return 8 * a.size          # pointer estimate, like the host ledger
    return int(getattr(a, "nbytes", 0))


def _manifest_extra_bytes(m, cur) -> int:
    """Bytes a retained manifest holds beyond what the CURRENT one
    shares: its row-buffer snapshot copies plus per-batch delete masks /
    update deltas whose view object diverged.  Batch payloads are
    write-once and shared across manifests — never double counted."""
    total = sum(_arr_bytes(a) for a in m.row_arrays)
    total += sum(_arr_bytes(a) for a in (m.row_nulls or ()))
    cur_views = {v.batch.batch_id: v for v in cur.views} \
        if cur is not None else {}
    for v in m.views:
        cv = cur_views.get(v.batch.batch_id)
        if cv is v:
            continue
        if v.delete_mask is not None and (
                cv is None or cv.delete_mask is not v.delete_mask):
            total += _arr_bytes(v.delete_mask)
        cur_deltas = set(map(id, cv.deltas)) if cv is not None else set()
        for d in v.deltas:
            if id(d) not in cur_deltas:
                total += _arr_bytes(d[1]) + _arr_bytes(d[2]) \
                    + _arr_bytes(d[3])
    return total


def retained_bytes_of(data) -> int:
    retained = getattr(data, "_retained_epochs", None)
    if not retained:
        return 0
    cur = data._manifest
    total = 0
    with _clock_lock:
        items = [(v, m) for v, m in retained.items()
                 if v != cur.version]
    for _v, m in items:
        total += _manifest_extra_bytes(m, cur)
    return total


def retained_epoch_bytes_by_table(tables) -> Dict[str, int]:
    """Per-table retained-epoch bytes for the broker ledger.  `tables`
    is an iterable of (name, data)."""
    out: Dict[str, int] = {}
    for name, data in tables:
        if not hasattr(data, "_manifest"):
            continue
        try:
            b = retained_bytes_of(data)
        except Exception:
            b = 0
        if b:
            out[name] = out.get(name, 0) + b
    return out


def retained_epochs_of(data) -> List[dict]:
    """Observability rows for one table's retained-epoch list."""
    retained = getattr(data, "_retained_epochs", None)
    if not retained:
        return []
    cur = data._manifest
    pins = getattr(data, "_pin_counts", {})
    with _clock_lock:
        items = sorted(retained.items())
    out = []
    for v, m in items:
        out.append({
            "version": v,
            "epoch": int(getattr(m, "epoch", 0)),
            "wal_seq": int(getattr(m, "wal_seq", 0)),
            "pins": int(pins.get(v, 0)),
            "current": v == cur.version,
            "bytes": 0 if v == cur.version
            else _manifest_extra_bytes(m, cur),
        })
    return out


def trim_unpinned(tables) -> int:
    """Degradation-ladder step: drop every retained epoch no pin holds
    (keeping only the current manifest) and evict device-cache entries
    for versions that are neither pinned nor current.  Returns how many
    epochs/cache entries were trimmed."""
    trimmed = 0
    for _nm, data in tables:
        if not hasattr(data, "_manifest"):
            continue
        with _clock_lock:
            trimmed += _trim_retained_locked(data, keep_unpinned=0)
        pinned = pinned_versions(data)
        cache = getattr(data, "_device_cache", None)
        if cache:
            cur_ver = data._manifest.version
            from snappydata_tpu.storage.device import _cache_budget

            for k in [k for k in list(cache)
                      if k[0] != cur_ver and k[0] not in pinned]:
                cache.pop(k, None)
                _cache_budget.forget(cache, k)
                trimmed += 1
    if trimmed:
        _reg().inc("mvcc_epoch_trims", trimmed)
    return trimmed
