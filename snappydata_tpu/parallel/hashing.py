"""Spark-compatible Murmur3 x86_32 hashing.

The reference's single most load-bearing trick for avoiding shuffles is
that store bucket placement uses the SAME hash as Catalyst's
HashPartitioning (StoreHashFunction.computeHash, core/.../store/
StoreHashFunction.scala:109-118) — so a join or group-by keyed on the
partitioning column needs no exchange. We reproduce that contract:
`murmur3_hash_np` matches Spark's Murmur3_x86_32 with seed 42 for
int/long inputs (each int is hashed as its 4-byte little-endian block;
longs hash low then high word, matching Spark's hashLong).

Vectorized numpy for placement, jnp twin for in-jit repartitioning.
"""

from __future__ import annotations

import numpy as np

SPARK_SEED = np.uint32(42)

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k1(k1):
    k1 = (k1 * _C1).astype(np.uint32)
    k1 = _rotl32(k1, 15)
    return (k1 * _C2).astype(np.uint32)


def _mix_h1(h1, k1):
    h1 = h1 ^ k1
    h1 = _rotl32(h1, 13)
    return (h1 * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)


def _fmix(h1, length):
    h1 = h1 ^ np.uint32(length)
    h1 = h1 ^ (h1 >> np.uint32(16))
    h1 = (h1 * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h1 = h1 ^ (h1 >> np.uint32(13))
    h1 = (h1 * np.uint32(0xC2B2AE35)).astype(np.uint32)
    return h1 ^ (h1 >> np.uint32(16))


def hash_int32_np(values: np.ndarray, seed=SPARK_SEED) -> np.ndarray:
    """Spark Murmur3_x86_32.hashInt for a vector of int32."""
    with np.errstate(over="ignore"):
        k1 = _mix_k1(values.astype(np.int64).astype(np.uint32))
        h1 = _mix_h1(np.broadcast_to(np.uint32(seed),
                                     values.shape).astype(np.uint32), k1)
        return _fmix(h1, 4).astype(np.int32)


def hash_int64_np(values: np.ndarray, seed=SPARK_SEED) -> np.ndarray:
    """Spark Murmur3_x86_32.hashLong: low word then high word."""
    with np.errstate(over="ignore"):
        v = values.astype(np.int64)
        low = (v & 0xFFFFFFFF).astype(np.uint32)
        high = ((v >> 32) & 0xFFFFFFFF).astype(np.uint32)
        h1 = np.broadcast_to(np.uint32(seed), v.shape).astype(np.uint32)
        h1 = _mix_h1(h1, _mix_k1(low))
        h1 = _mix_h1(h1, _mix_k1(high))
        return _fmix(h1, 8).astype(np.int32)


def hash_bytes(b: bytes, seed=SPARK_SEED) -> int:
    """Spark Murmur3_x86_32.hashUnsafeBytes: little-endian 4-byte words,
    then each remaining byte mixed as a full (sign-extended) word."""
    with np.errstate(over="ignore"):
        n = len(b)
        aligned = n - n % 4
        h1 = np.uint32(seed)
        if aligned:
            for w in np.frombuffer(b, dtype="<u4", count=aligned // 4):
                h1 = _mix_h1(h1, _mix_k1(np.uint32(w)))
        for i in range(aligned, n):
            byte = b[i]
            if byte > 127:
                byte -= 256
            h1 = _mix_h1(h1, _mix_k1(np.uint32(byte & 0xFFFFFFFF)))
        return int(np.int32(_fmix(h1, n)))


def hash_strings_np(values, seed=SPARK_SEED) -> np.ndarray:
    """UTF8 murmur3 for an object/str column; NULL hashes to the seed
    (Spark's HashPartitioning skips null children, leaving the seed)."""
    cache = {}
    out = np.empty(len(values), dtype=np.int32)
    for i, v in enumerate(values):
        h = cache.get(v)
        if h is None:
            if v is None:
                h = int(np.int32(np.uint32(seed)))
            elif isinstance(v, bytes):
                # numpy 'S' arrays route here: hash the UTF-8 content,
                # not the repr "b'...'" (the same logical value stored as
                # str vs bytes must land in the same bucket)
                h = hash_bytes(v, seed)
            else:
                h = hash_bytes(str(v).encode("utf-8"), seed)
            cache[v] = h
        out[i] = h
    return out


def murmur3_hash_np(values: np.ndarray, seed=SPARK_SEED) -> np.ndarray:
    """Hash a column the way Spark's HashPartitioning would."""
    values = np.asarray(values)
    if values.dtype.kind in ("O", "U", "S"):
        return hash_strings_np(values, seed)
    if values.dtype in (np.dtype(np.int8), np.dtype(np.int16),
                        np.dtype(np.int32), np.dtype(np.bool_)):
        return hash_int32_np(values, seed)
    if values.dtype == np.dtype(np.int64):
        return hash_int64_np(values, seed)
    if values.dtype == np.dtype(np.float32):
        # match Java floatToIntBits semantics Spark relies on: -0.0f
        # normalizes to 0.0f and every NaN to the canonical NaN pattern
        v = np.where(values == 0.0, np.float32(0.0), values)
        bits = v.view(np.int32)
        bits = np.where(np.isnan(v), np.int32(0x7FC00000), bits)
        return hash_int32_np(bits, seed)
    if values.dtype == np.dtype(np.float64):
        v = np.where(values == 0.0, np.float64(0.0), values)
        bits = v.view(np.int64)
        bits = np.where(np.isnan(v), np.int64(0x7FF8000000000000), bits)
        return hash_int64_np(bits, seed)
    raise TypeError(f"unhashable dtype {values.dtype}")


def bucket_of_np(values: np.ndarray, num_buckets: int) -> np.ndarray:
    """Bucket id for each key: Spark's Pmod(hash, n) (non-negative mod)."""
    h = murmur3_hash_np(values).astype(np.int64)
    return ((h % num_buckets) + num_buckets) % num_buckets
