"""Exactly-once sink + streaming query tests (ref analogue:
SnappyStoreSinkProviderSuite, 568 LoC — duplicate batches, CDC event
types, conflation, restart resume)."""

import json

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.streaming import (EventType, FileSource, MemorySource,
                                      SnappySink, StreamingQuery)


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE target (k INT PRIMARY KEY, v STRING) USING row")
    yield sess
    sess.stop()


def _batch(ks, vs, events=None):
    cols = {"k": np.array(ks, dtype=np.int64),
            "v": np.array(vs, dtype=object)}
    if events is not None:
        cols["_eventType"] = np.array(events, dtype=np.int64)
    return cols


def test_sink_basic_and_duplicate_batch(s):
    sink = SnappySink(s, "q1", "target")
    assert sink.process_batch(0, _batch([1, 2], ["a", "b"]))
    assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 2
    # same batch id replayed (failure before state commit) → idempotent
    assert sink.process_batch(0, _batch([1, 2], ["a", "b"]))
    assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 2
    # strictly older batch → dropped entirely
    sink.process_batch(1, _batch([3], ["c"]))
    assert not sink.process_batch(0, _batch([9], ["x"]))
    assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 3


def test_sink_cdc_event_types(s):
    sink = SnappySink(s, "q2", "target")
    sink.process_batch(0, _batch([1, 2, 3], ["a", "b", "c"],
                                 [EventType.INSERT] * 3))
    sink.process_batch(1, _batch([2, 3], ["B", "ignored"],
                                 [EventType.UPDATE, EventType.DELETE]))
    rows = dict(s.sql("SELECT k, v FROM target ORDER BY k").rows())
    assert rows == {1: "a", 2: "B"}


def test_sink_conflation_last_event_wins(s):
    sink = SnappySink(s, "q3", "target", conflation=True)
    sink.process_batch(0, _batch(
        [5, 5, 5], ["first", "second", "third"],
        [EventType.INSERT, EventType.UPDATE, EventType.UPDATE]))
    assert s.sql("SELECT v FROM target WHERE k = 5").rows() == [("third",)]


def test_state_table_shared_across_queries(s):
    a = SnappySink(s, "qa", "target")
    b = SnappySink(s, "qb", "target")
    a.process_batch(4, _batch([10], ["x"]))
    assert a.last_batch_id() == 4
    assert b.last_batch_id() == -1


def test_streaming_query_resume_after_restart(s):
    src = MemorySource()
    for i in range(3):
        src.add_batch(_batch([100 + i], [f"v{i}"]))
    q = StreamingQuery(s, "resume_q", src, "target")
    assert q.process_available() == 3
    assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 3
    # "restart": a new query object over the same source replays nothing
    q2 = StreamingQuery(s, "resume_q", src, "target")
    assert q2.process_available() == 0
    src.add_batch(_batch([200], ["new"]))
    assert q2.process_available() == 1
    assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 4


def test_streaming_into_column_table_with_keys(s):
    s.sql("CREATE TABLE events (id INT, metric DOUBLE) USING column "
          "OPTIONS (key_columns 'id')")
    sink = SnappySink(s, "qc", "events")
    sink.process_batch(0, {"id": np.array([1, 2]),
                           "metric": np.array([0.5, 1.5])})
    sink.process_batch(0, {"id": np.array([1, 2]),
                           "metric": np.array([0.5, 1.5])})  # dup replay
    assert s.sql("SELECT count(*) FROM events").rows()[0][0] == 2
    assert s.sql("SELECT sum(metric) FROM events").rows()[0][0] == 2.0


def test_file_source(tmp_path, s):
    d = tmp_path / "stream"
    d.mkdir()
    (d / "00.json").write_text("\n".join(
        json.dumps({"k": i, "v": f"row{i}"}) for i in range(4)))
    (d / "01.json").write_text(json.dumps(
        {"k": 0, "v": "updated", "_eventType": 1}))
    q = StreamingQuery(s, "file_q", FileSource(str(d), ["k", "v"]),
                       "target")
    assert q.process_available() == 2
    rows = dict(s.sql("SELECT k, v FROM target ORDER BY k").rows())
    assert rows[0] == "updated" and rows[3] == "row3"


def test_background_thread_drains(s):
    src = MemorySource()
    q = StreamingQuery(s, "bg_q", src, "target", interval_s=0.01).start()
    try:
        for i in range(5):
            src.add_batch(_batch([300 + i], [f"bg{i}"]))
        import time

        deadline = time.time() + 5
        while time.time() < deadline:
            if s.sql("SELECT count(*) FROM target").rows()[0][0] == 5:
                break
            time.sleep(0.05)
        assert s.sql("SELECT count(*) FROM target").rows()[0][0] == 5
        assert q.last_error is None
    finally:
        q.stop()
    assert not q.is_active
