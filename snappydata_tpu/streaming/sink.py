"""Exactly-once streaming sink.

Direct behavioral port of the reference's structured-streaming sink
protocol (core/.../streaming/SnappySinkCallback.scala:49-360):

- state table `snappysys_internal____sink_state_table(query_id, batch_id)`
  records the last batch id processed per query (:196-216): a batch id
  ≤ the recorded one marks the batch `possible_duplicate`.
- `_eventType` column (insert=0 / update=1 / delete=2) drives CDC
  semantics; events are conflated to the last one per key when
  `conflation` is on (DefaultSnappySinkCallback.process:239).
- duplicate batches replay idempotently: inserts become puts on key'd
  tables (so re-applying is a no-op), mirroring the reference's
  possibleDuplicate handling.
- retries with backoff on transient conflicts (processBatchWithRetries
  :166-181).
"""

from __future__ import annotations

import enum
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from snappydata_tpu import config


class EventType(enum.IntEnum):
    INSERT = 0
    UPDATE = 1
    DELETE = 2


EVENT_TYPE_COLUMN = "_eventType"


class SnappySink:
    def __init__(self, session, query_name: str, table: str,
                 conflation: bool = False):
        self.session = session
        self.query_name = query_name
        self.table = table.lower()
        self.conflation = conflation
        props = config.global_properties()
        self.state_table = props.sink_state_table
        self.max_retries = props.sink_max_retries
        self._ensure_state_table()

    def _ensure_state_table(self) -> None:
        self.session.sql(
            f"CREATE TABLE IF NOT EXISTS {self.state_table} "
            f"(query_id STRING PRIMARY KEY, batch_id BIGINT) USING row")

    # -- the exactly-once contract ---------------------------------------

    def last_batch_id(self) -> int:
        row = self.session.get(self.state_table, (self.query_name,))
        return int(row[1]) if row is not None else -1

    def process_batch(self, batch_id: int, columns: Dict[str, np.ndarray]
                      ) -> bool:
        """Apply one micro-batch. Returns False when the batch was already
        fully processed (skipped). `columns` maps target column names to
        arrays, optionally plus `_eventType`."""
        attempt = 0
        while True:
            try:
                return self._process_once(batch_id, columns)
            except Exception:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                time.sleep(0.05 * attempt)

    def _process_once(self, batch_id: int, columns) -> bool:
        last = self.last_batch_id()
        if batch_id < last:
            return False  # strictly older than the recorded batch: drop
        possible_duplicate = batch_id == last
        # APPLY first, record progress after: a crash between the two
        # replays the batch, which the idempotent apply (puts on key'd
        # tables) tolerates. Record-first would instead LOSE the batch on
        # crash — restart fetches from last_batch_id()+1 (review finding).
        # Keyless tables can duplicate on crash replay; the reference's
        # exactly-once likewise requires key columns.
        self._apply(columns, possible_duplicate)
        self.session.put(self.state_table, (self.query_name, batch_id))
        return True

    def _apply(self, columns: Dict[str, np.ndarray],
               possible_duplicate: bool) -> None:
        info = self.session.catalog.describe(self.table)
        names = [f.name for f in info.schema.fields]
        events = columns.get(EVENT_TYPE_COLUMN)
        n = len(np.asarray(columns[names[0]]))
        key_cols = list(info.key_columns)

        if events is None:
            arrays = [np.asarray(columns[c]) for c in names]
            if key_cols:
                # always upsert on key'd tables: crash replay of a batch
                # whose progress record was lost must be a no-op
                self._put_arrays(info, arrays)
            else:
                # keyless replay can't dedupe — the reference has the same
                # semantics (exactly-once needs key columns)
                self._insert_arrays(info, arrays)
            return

        events = np.asarray(events).astype(np.int64)
        order = np.arange(n)
        if self.conflation and key_cols:
            # keep only the LAST event per key (ref conflation)
            kidx = [names.index(k) for k in key_cols]
            seen = {}
            for i in range(n):
                key = tuple(np.asarray(columns[names[j]])[i] for j in kidx)
                seen[key] = i
            order = np.array(sorted(seen.values()), dtype=np.int64)
        deletes = order[events[order] == EventType.DELETE]
        upserts = order[events[order] != EventType.DELETE]

        if len(deletes) and key_cols:
            self.session.delete_keys(
                self.table, key_cols,
                [np.asarray(columns[k])[deletes] for k in key_cols])
        if len(upserts):
            arrays = [np.asarray(columns[c])[upserts] for c in names]
            if key_cols:
                self._put_arrays(info, arrays)
            else:
                self._insert_arrays(info, arrays)

    # all writes go through session APIs so a durable session journals
    # them (review finding: direct info.data calls bypassed the WAL)
    def _insert_arrays(self, info, arrays: List[np.ndarray]) -> None:
        self.session.insert_arrays(self.table, arrays)

    def _put_arrays(self, info, arrays: List[np.ndarray]) -> None:
        self.session.put_arrays(self.table, arrays)
