"""Arrow Flight SQL protocol surface: wire-format messages + dispatch.

The reference serves any JDBC/ODBC client through its thrift/DRDA network
servers (cluster/README-thrift.md:20-35). The TPU-native equivalent is
Arrow Flight SQL — the OPEN protocol that stock ADBC / JDBC-FlightSQL
drivers speak. This module implements the protobuf wire format of the
public `arrow.flight.protocol.sql` messages (hand-rolled varint codec —
the protocol is stable and tiny; no protobuf runtime needed) plus the
server-side dispatch used by SnappyFlightServer:

  GetFlightInfo(CommandStatementQuery)      → FlightInfo + ticket
  DoGet(TicketStatementQuery)               → result record batches
  GetFlightInfo/DoGet(CommandGetCatalogs / CommandGetDbSchemas /
      CommandGetTables)                     → spec-schema catalog rows
  DoAction(CreatePreparedStatement / ClosePreparedStatement)
  DoPut(CommandPreparedStatementQuery)      → bind '?' parameters
  GetFlightInfo/DoGet(CommandPreparedStatementQuery)
  DoPut(CommandStatementUpdate)             → DoPutUpdateResult

Message field numbers follow the public FlightSql.proto (apache/arrow,
format/FlightSql.proto); a conformance client lives in
`FlightSqlClient` below for tests and for environments without an ADBC
driver installed.
"""

from __future__ import annotations

import json
import threading
from snappydata_tpu.utils import locks
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

_SQL_NS = "type.googleapis.com/arrow.flight.protocol.sql."


# ---------------------------------------------------------------------
# protobuf wire codec (varint + length-delimited only — all FlightSql
# messages use wire types 0 and 2)
# ---------------------------------------------------------------------

def _put_varint(out: bytearray, v: int) -> None:
    if v < 0:
        # proto varints are two's-complement over 64 bits: negative
        # int32/int64 values encode as 10 bytes (e.g. the spec'd
        # DoPutUpdateResult.record_count = -1 for 'unknown'). Without the
        # mask the arithmetic shift below never terminates.
        v &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def varint_to_int64(v: int) -> int:
    """Decoded varints are raw unsigned 64-bit values; reinterpret as the
    signed int64 proto3 int32/int64 fields carry (-1 arrives as 2^64-1)."""
    v &= 0xFFFFFFFFFFFFFFFF
    return v - (1 << 64) if v >= (1 << 63) else v


def _get_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = v = 0
    while True:
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def encode_fields(fields: List[Tuple[int, object]]) -> bytes:
    """fields: (field_number, value) — str/bytes → length-delimited,
    int/bool → varint, list/tuple → REPEATED field (one entry per
    element). Nones AND proto3 defaults (0, False, empty str/bytes) are
    skipped for SINGULAR fields only — that is proto3 canonical
    serialization; a repeated-field element that happens to be a default
    value (e.g. an empty string in CommandGetTables.table_types) is a
    real element and must stay on the wire (advisor round 5). Matches
    the official runtime byte for byte (golden fixtures generated with
    google.protobuf — tests/test_flightsql_golden.py)."""
    out = bytearray()

    def put_one(num: int, val, skip_defaults: bool) -> None:
        if isinstance(val, bool):
            if skip_defaults and not val:
                return
            _put_varint(out, (num << 3) | 0)
            _put_varint(out, 1 if val else 0)
        elif isinstance(val, int):
            if skip_defaults and val == 0:
                return
            _put_varint(out, (num << 3) | 0)
            _put_varint(out, val)
        else:
            raw = val.encode("utf-8") if isinstance(val, str) else bytes(val)
            if skip_defaults and not raw:
                return
            _put_varint(out, (num << 3) | 2)
            _put_varint(out, len(raw))
            out.extend(raw)

    for num, val in fields:
        if val is None:
            continue
        if isinstance(val, (list, tuple)):
            for el in val:
                if el is not None:
                    put_one(num, el, skip_defaults=False)
        else:
            put_one(num, val, skip_defaults=True)
    return bytes(out)


def decode_fields(buf: bytes) -> Dict[int, list]:
    """→ {field_number: [raw values]} (varints as int, delimited as
    bytes)."""
    out: Dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _get_varint(buf, pos)
        num, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _get_varint(buf, pos)
        elif wire == 2:
            ln, pos = _get_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(num, []).append(v)
    return out


def pack_any(msg_name: str, payload: bytes) -> bytes:
    """google.protobuf.Any {type_url=1, value=2}."""
    return encode_fields([(1, _SQL_NS + msg_name), (2, payload)])


def unpack_any(buf: bytes) -> Optional[Tuple[str, bytes]]:
    """→ (short message name, payload) when this is a FlightSql Any."""
    try:
        f = decode_fields(buf)
    except (IndexError, ValueError):
        return None
    urls = f.get(1)
    if not urls:
        return None
    url = urls[0].decode("utf-8", "replace")
    if not url.startswith(_SQL_NS):
        return None
    value = f.get(2, [b""])[0]
    return url[len(_SQL_NS):], value


def _s(f: Dict[int, list], num: int, default: Optional[str] = None):
    v = f.get(num)
    return v[0].decode("utf-8") if v else default


def _b(f: Dict[int, list], num: int) -> Optional[bytes]:
    v = f.get(num)
    return bytes(v[0]) if v else None


# ---------------------------------------------------------------------
# server-side dispatch
# ---------------------------------------------------------------------

class FlightSqlHandler:
    """FlightSQL request handling over a SnappySession provider.

    `session_for(body)` mirrors SnappyFlightServer._session_for: resolves
    the caller's authenticated session from headers already validated by
    the server middleware."""

    def __init__(self, server):
        self.server = server
        self._prepared: Dict[bytes, dict] = {}
        self._lock = locks.named_lock("flightsql.handles")
        self._next_handle = 0

    # -- helpers -------------------------------------------------------

    def _session(self, context):
        return self.server._session_from_context(context)

    def _catalog_rows(self, sess, kind: str, f: Dict[int, list]):
        """Spec-defined result sets for the catalog commands
        (FlightSql.proto: GetCatalogs/GetDbSchemas/GetTables schemas)."""
        if kind == "CommandGetCatalogs":
            return pa.table({"catalog_name": pa.array(["snappydata"],
                                                      pa.utf8())})
        if kind == "CommandGetDbSchemas":
            return pa.table({
                "catalog_name": pa.array(["snappydata"], pa.utf8()),
                "db_schema_name": pa.array(["app"], pa.utf8())})
        # CommandGetTables
        pattern = _s(f, 3)
        # repeated table_types (field 4): empty list = no filter; an
        # empty-string ELEMENT is a real (nothing-matching) filter
        # entry, preserved by the repeated-aware codec
        type_filter = {v.decode("utf-8", "replace").upper()
                       for v in f.get(4, [])}
        include_schema = bool(f.get(5, [0])[0])
        names, types, schemas = [], [], []
        if not type_filter or "TABLE" in type_filter:
            for info in sess.catalog.list_tables():
                nm = info.name
                if pattern and not _like_match(pattern, nm):
                    continue
                names.append(nm)
                types.append("TABLE")
                if include_schema:
                    fields = [pa.field(fl.name, _ARROW_OF(fl.dtype),
                                       fl.nullable)
                              for fl in info.schema.fields
                              if not fl.name.startswith("__")]
                    schemas.append(pa.schema(fields)
                                   .serialize().to_pybytes())
        if not type_filter or "VIEW" in type_filter:
            for vname in sorted(getattr(sess.catalog, "_views", {})):
                if pattern and not _like_match(pattern, vname):
                    continue
                names.append(vname)
                types.append("VIEW")
                if include_schema:
                    schemas.append(pa.schema([]).serialize().to_pybytes())
        cols = {
            "catalog_name": pa.array(["snappydata"] * len(names),
                                     pa.utf8()),
            "db_schema_name": pa.array(["app"] * len(names), pa.utf8()),
            "table_name": pa.array(names, pa.utf8()),
            "table_type": pa.array(types, pa.utf8()),
        }
        if include_schema:
            cols["table_schema"] = pa.array(schemas, pa.binary())
        return pa.table(cols)

    # -- GetFlightInfo -------------------------------------------------

    def flight_info(self, context, descriptor, kind: str, payload: bytes):
        import pyarrow.flight as flight

        f = decode_fields(payload)
        sess = self._session(context)
        if kind == "CommandStatementQuery":
            query = _s(f, 1, "")
            ticket_payload = pack_any(
                "TicketStatementQuery",
                encode_fields([(1, json.dumps({"sql": query})
                                .encode("utf-8"))]))
            schema = self._query_schema(sess, query, ())
        elif kind == "CommandPreparedStatementQuery":
            handle = _b(f, 1) or b""
            with self._lock:
                st = self._prepared.get(handle)
            if st is None:
                raise flight.FlightServerError(
                    "unknown prepared statement handle")
            ticket_payload = pack_any(kind, payload)
            schema = self._query_schema(sess, st["sql"],
                                        st.get("params", ()))
        elif kind in ("CommandGetCatalogs", "CommandGetDbSchemas",
                      "CommandGetTables"):
            ticket_payload = pack_any(kind, payload)
            schema = self._catalog_rows(sess, kind, f).schema
        else:
            raise flight.FlightServerError(
                f"unsupported FlightSQL command {kind}")
        endpoint = flight.FlightEndpoint(
            ticket_payload, [flight.Location(self.server._location)])
        return flight.FlightInfo(schema, descriptor, [endpoint], -1, -1)

    def _query_schema(self, sess, sql: str, params) -> "pa.Schema":
        # already-prepared shapes answer from the serving registry's
        # cached schema; everything else analyzes WITHOUT registering —
        # GetFlightInfo of ad-hoc literal-bearing SQL must not churn
        # real prepared handles out of the registry LRU
        schema = None
        try:
            from snappydata_tpu.serving import registry_for

            handle = registry_for(sess.catalog).peek(sess, sql)
            if handle is not None:
                schema = handle.schema
        except Exception:
            schema = None
        if schema is None:
            schema = sess.query_schema(sql)
        return _widen_decimal_schema(pa.schema(
            [pa.field(fl.name, _ARROW_OF(fl.dtype), fl.nullable)
             for fl in schema.fields]))

    # -- DoGet ---------------------------------------------------------

    def do_get(self, context, kind: str, payload: bytes):
        import pyarrow.flight as flight

        from snappydata_tpu.cluster.flight_server import result_to_arrow

        f = decode_fields(payload)
        sess = self._session(context)
        if kind == "TicketStatementQuery":
            body = json.loads((_b(f, 1) or b"{}").decode("utf-8"))
            result = sess.sql(body["sql"],
                              params=tuple(body.get("params", ())))
            table = _widen_decimal_table(result_to_arrow(result))
        elif kind == "CommandPreparedStatementQuery":
            handle = _b(f, 1) or b""
            with self._lock:
                st = self._prepared.get(handle)
            if st is None:
                raise flight.FlightServerError(
                    "unknown prepared statement handle")
            # serving registry: wire-level prepares get compile-once too
            # — the second execute of a handle is a serving_prepared_hits
            # hit, and concurrent executes fuse into one device dispatch
            result = sess.serving_sql(st["sql"],
                                      params=tuple(st.get("params", ())))
            table = _widen_decimal_table(result_to_arrow(result))
        elif kind in ("CommandGetCatalogs", "CommandGetDbSchemas",
                      "CommandGetTables"):
            table = self._catalog_rows(sess, kind, f)
        else:
            raise flight.FlightServerError(
                f"unsupported FlightSQL ticket {kind}")
        # 0-row results still need one (empty) batch carrying the schema;
        # pa.record_batch([], schema=non-empty-schema) raises — build the
        # empty arrays explicitly
        batches = table.to_batches(max_chunksize=65536) or \
            [pa.RecordBatch.from_arrays(
                [pa.array([], type=f.type) for f in table.schema],
                schema=table.schema)]
        return flight.GeneratorStream(table.schema, iter(batches))

    # -- DoAction ------------------------------------------------------

    def do_action(self, context, kind: str, payload: bytes):
        f = decode_fields(payload)
        sess = self._session(context)
        if kind == "ActionCreatePreparedStatementRequest":
            sql = _s(f, 1, "")
            with self._lock:
                self._next_handle += 1
                handle = f"ps{self._next_handle}".encode("utf-8")
                self._prepared[handle] = {"sql": sql, "params": ()}
            if sql.lstrip().lower().startswith(("select", "with",
                                                "values")):
                # an explicit wire-level prepare IS the registry's
                # reason to exist: build the compile-once entry now so
                # the first execute is already a serving hit
                try:
                    from snappydata_tpu.serving import ServingError

                    try:
                        sess.prepare(sql)
                    except ServingError:
                        pass
                except Exception:   # schema path reports real errors
                    pass
                schema = self._query_schema(sess, sql, ())
            else:
                schema = pa.schema([])
            result = encode_fields([
                (1, handle), (2, schema.serialize().to_pybytes())])
            return [pack_any("ActionCreatePreparedStatementResult",
                             result)]
        if kind == "ActionClosePreparedStatementRequest":
            handle = _b(f, 1) or b""
            with self._lock:
                self._prepared.pop(handle, None)
            return [b""]
        import pyarrow.flight as flight

        raise flight.FlightServerError(
            f"unsupported FlightSQL action {kind}")

    # -- DoPut ---------------------------------------------------------

    def do_put(self, context, kind: str, payload: bytes, reader, writer):
        import pyarrow.flight as flight

        f = decode_fields(payload)
        sess = self._session(context)
        if kind == "CommandStatementUpdate":
            sql = _s(f, 1, "")
            result = sess.sql(sql)
            # spec: record_count = -1 means 'unknown' (statements like
            # DDL whose result carries no row count) — encoded as a
            # 10-byte two's-complement varint
            n = int(result.rows()[0][0]) if result.num_rows and \
                result.columns and np.issubdtype(
                    np.asarray(result.columns[0]).dtype, np.number) else -1
            writer.write(encode_fields([(1, n)]))   # DoPutUpdateResult
            return
        if kind == "CommandPreparedStatementQuery":
            handle = _b(f, 1) or b""
            with self._lock:
                st = self._prepared.get(handle)
            if st is None:
                raise flight.FlightServerError(
                    "unknown prepared statement handle")
            table = reader.read_all()
            if table.num_rows:
                row = [col[0].as_py() for col in table.columns]
                with self._lock:
                    st["params"] = tuple(row)
            writer.write(encode_fields([(1, handle)]))
            return
        raise flight.FlightServerError(
            f"unsupported FlightSQL DoPut {kind}")


def _widen_decimal_schema(schema: "pa.Schema") -> "pa.Schema":
    """FlightSQL surface only: decimals travel as decimal128(38, s) so
    the GetFlightInfo schema and the DoGet stream ALWAYS agree — the
    engine's int64-overflow fallback can produce totals wider than the
    declared precision, and stock drivers that pre-allocate readers
    from FlightInfo reject a stream whose types differ. (The plain
    Flight ticket surface keeps exact declared types — the in-repo
    client and the exchange path read the stream schema directly.)"""
    fields = []
    for f in schema:
        if pa.types.is_decimal(f.type) and f.type.precision < 38:
            f = pa.field(f.name, pa.decimal128(38, f.type.scale),
                         f.nullable)
        fields.append(f)
    return pa.schema(fields)


def _widen_decimal_table(table: "pa.Table") -> "pa.Table":
    wide = _widen_decimal_schema(table.schema)
    return table if wide == table.schema else table.cast(wide)


def _like_match(pattern: str, name: str) -> bool:
    """SQL LIKE pattern (% and _) matching for catalog filters."""
    import re

    rx = re.escape(pattern).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, name, re.IGNORECASE) is not None


def _ARROW_OF(dtype):
    from snappydata_tpu.cluster.flight_server import _arrow_type

    return _arrow_type(dtype)


# ---------------------------------------------------------------------
# conformance client (tests / environments without an ADBC driver)
# ---------------------------------------------------------------------

class FlightSqlClient:
    """Protocol-conformant FlightSQL client: speaks the public message
    encoding over a plain pyarrow FlightClient — what an ADBC FlightSQL
    driver sends on the wire."""

    def __init__(self, address: str, user: Optional[str] = None,
                 password: Optional[str] = None):
        import pyarrow.flight as flight

        self._conn = flight.connect(f"grpc://{address}")
        self._opts = None
        if user is not None:
            import base64

            cred = base64.b64encode(
                f"{user}:{password}".encode("utf-8")).decode("ascii")
            self._opts = flight.FlightCallOptions(
                headers=[(b"authorization", b"Basic " + cred.encode())])

    def _info(self, kind: str, payload: bytes):
        import pyarrow.flight as flight

        desc = flight.FlightDescriptor.for_command(pack_any(kind, payload))
        return self._conn.get_flight_info(desc, self._opts)

    def _read(self, info):
        ticket = info.endpoints[0].ticket
        return self._conn.do_get(ticket, self._opts).read_all()

    def execute(self, sql: str) -> pa.Table:
        info = self._info("CommandStatementQuery",
                          encode_fields([(1, sql)]))
        return self._read(info)

    def execute_update(self, sql: str) -> int:
        import pyarrow.flight as flight

        desc = flight.FlightDescriptor.for_command(
            pack_any("CommandStatementUpdate", encode_fields([(1, sql)])))
        writer, reader = self._conn.do_put(
            desc, pa.schema([]), self._opts)
        writer.done_writing()
        buf = reader.read()
        writer.close()
        if buf is None:
            return 0
        f = decode_fields(buf.to_pybytes())
        return varint_to_int64(int(f.get(1, [0])[0]))

    def get_tables(self, pattern: Optional[str] = None,
                   include_schema: bool = False,
                   table_types: Optional[Sequence[str]] = None) -> pa.Table:
        payload = encode_fields([(3, pattern),
                                 (4, list(table_types or ())),
                                 (5, include_schema)])
        return self._read(self._info("CommandGetTables", payload))

    def get_catalogs(self) -> pa.Table:
        return self._read(self._info("CommandGetCatalogs", b""))

    def get_db_schemas(self) -> pa.Table:
        return self._read(self._info("CommandGetDbSchemas", b""))

    def prepare(self, sql: str) -> "PreparedStatement":
        import pyarrow.flight as flight

        results = list(self._conn.do_action(
            flight.Action("CreatePreparedStatement",
                          pack_any("ActionCreatePreparedStatementRequest",
                                   encode_fields([(1, sql)]))),
            self._opts))
        got = unpack_any(results[0].body.to_pybytes())
        assert got is not None and \
            got[0] == "ActionCreatePreparedStatementResult"
        f = decode_fields(got[1])
        return PreparedStatement(self, _b(f, 1) or b"")

    def close(self) -> None:
        self._conn.close()


class PreparedStatement:
    def __init__(self, client: FlightSqlClient, handle: bytes):
        self.client = client
        self.handle = handle

    def execute(self, params: Sequence = ()) -> pa.Table:
        import pyarrow.flight as flight

        payload = encode_fields([(1, self.handle)])
        if params:
            desc = flight.FlightDescriptor.for_command(
                pack_any("CommandPreparedStatementQuery", payload))
            arrays = [pa.array([p]) for p in params]
            names = [f"p{i}" for i in range(len(params))]
            tbl = pa.table(dict(zip(names, arrays)))
            writer, reader = self.client._conn.do_put(
                desc, tbl.schema, self.client._opts)
            writer.write_table(tbl)
            writer.done_writing()
            reader.read()
            writer.close()
        info = self.client._info("CommandPreparedStatementQuery", payload)
        return self.client._read(info)

    def close(self) -> None:
        import pyarrow.flight as flight

        list(self.client._conn.do_action(
            flight.Action("ClosePreparedStatement",
                          pack_any("ActionClosePreparedStatementRequest",
                                   encode_fields([(1, self.handle)]))),
            self.client._opts))
