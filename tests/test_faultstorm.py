"""The seeded fault-storm scheduler (reliability/faultstorm.py): every
fault the storm injects across the WAL / checkpoint / tier / prefetch /
admission seams must be accounted as recovered-in-place or a typed
retryable error followed by verified crash-recovery — never a wrong
row, never an untyped failure.  Also the bench.py --check contract
around the faultstorm detail record."""

import pytest

from snappydata_tpu.reliability import failpoints as rfail, faultstorm

pytestmark = [pytest.mark.faults, pytest.mark.chaos]


@pytest.fixture(autouse=True)
def _clean():
    rfail.clear()
    yield
    rfail.clear()


def test_storm_fully_accounted(tmp_path):
    res = faultstorm.run_storm(str(tmp_path), seed=1717, rounds=14)
    assert res["injected"] > 0, "a 14-round storm must land some faults"
    assert res["value_mismatches"] == 0, res["unexpected"]
    assert res["unexpected"] == []
    assert res["accounted"] == res["injected"], res
    assert res["recovery_ratio"] == 1.0
    assert res["rows_final"] > 0
    # the controlled corruption phase must be exercised across seeds
    # often enough that the ledger moves in a default run — but a
    # single short seed isn't guaranteed to draw it, so only sanity-
    # check the counters that did move are consistent
    assert res["tier"]["tier_rebuild_failures"] == 0
    assert res["tier"]["tier_quarantined_files"] == \
        res["tier"]["tier_rebuilds"]


def test_storm_is_seed_deterministic(tmp_path):
    a = faultstorm.run_storm(str(tmp_path / "a"), seed=31, rounds=8)
    b = faultstorm.run_storm(str(tmp_path / "b"), seed=31, rounds=8)
    for key in ("injected", "recovered", "typed_errors",
                "crash_recoveries", "rows_final", "fired_by_point"):
        assert a[key] == b[key], (key, a[key], b[key])


def test_bench_check_guards_faultstorm():
    import bench

    base = {"value": 1.0, "detail": {}}
    good = {"value": 1.0, "detail": {"faultstorm": {
        "injected": 9, "accounted": 9, "recovery_ratio": 1.0,
        "value_mismatches": 0, "unexpected": []}}}
    assert bench.check_regression(good, base) == []
    wrong_rows = {"value": 1.0, "detail": {"faultstorm": {
        "injected": 9, "accounted": 9, "recovery_ratio": 1.0,
        "value_mismatches": 2, "unexpected": ["scan sum diverged"]}}}
    fails = bench.check_regression(wrong_rows, base)
    assert any("wrong rows" in f for f in fails)
    unaccounted = {"value": 1.0, "detail": {"faultstorm": {
        "injected": 10, "accounted": 8, "recovery_ratio": 0.8,
        "value_mismatches": 0, "unexpected": []}}}
    fails = bench.check_regression(unaccounted, base)
    assert any("recovery ratio" in f for f in fails)
    assert bench.check_regression(unaccounted, base,
                                  fault_recovery=0.75) == []
