"""SQL-registered functions (CREATE FUNCTION): the python body runs on
the traced values and fuses into the compiled query program (ref:
CreateAndLoadAirlineDataJob.scala registers UDFs the JVM way).

Run: PYTHONPATH=. python examples/sql_functions.py
"""

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


def main():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE fares (base DOUBLE, surge DOUBLE) USING column")
    rng = np.random.default_rng(1)
    s.insert_arrays("fares", [rng.random(100_000) * 40,
                              1 + rng.random(100_000)])
    s.sql("CREATE FUNCTION total_fare AS "
          "'lambda base, surge: jnp.round(base * surge + 2.5, 2)' "
          "RETURNS DOUBLE")
    r = s.sql("SELECT count(*), avg(total_fare(base, surge)) FROM fares "
              "WHERE total_fare(base, surge) > 30")
    print("rows over $30 and their avg:", r.rows()[0])


if __name__ == "__main__":
    main()
