"""Stratified reservoir sampling.

Reference behavior (docs/aqp.md:24-43): a SAMPLE TABLE declares QCS (query
column set) columns and a sampling fraction; the sampler keeps a reservoir
PER STRATUM (distinct QCS combination) so rare groups stay represented,
and every sampled row carries `snappy_sampler_weight` = observed/kept for
unbiased scale-up of SUM/COUNT.

Vectorized host implementation (ingest-side); the observe() inner loop is
numpy per-stratum partitioning + Vitter-style acceptance, which keeps up
with the row-buffer ingest path. On-device reservoir update kernels are a
later optimization, per SURVEY.md §7.9.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


RESERVOIR_WEIGHT_COLUMN = "snappy_sampler_weight"
# hidden ("__"-prefixed) per-row stratum id: closed-form error estimation
# needs within-stratum sample moments, so every materialized sample row
# carries the integer id of the stratum (QCS combination) it came from
STRATUM_ID_COLUMN = "__stratum_id"


class StratifiedReservoir:
    def __init__(self, qcs_indices: Sequence[int], num_columns: int,
                 reservoir_size: int = 50, seed: int = 0):
        self.qcs = list(qcs_indices)
        self.num_columns = num_columns
        self.cap = reservoir_size
        self._rng = np.random.default_rng(seed)
        self._lock = locks.named_lock("aqp.reservoir")
        # stratum key -> (list of row tuples (len == cap max), seen count)
        self._strata: Dict[tuple, Tuple[List[tuple], int]] = {}
        # stable stratum → integer id (materialization order)
        self._stratum_ids: Dict[tuple, int] = {}
        self.version = 0

    def observe(self, arrays: Sequence[np.ndarray]) -> None:
        arrays = [np.asarray(a) for a in arrays]
        n = int(arrays[0].shape[0])
        if n == 0:
            return
        keys = list(zip(*(arrays[i].tolist() for i in self.qcs))) \
            if self.qcs else [()] * n
        with self._lock:
            for i, key in enumerate(keys):
                rows, seen = self._strata.get(key, ([], 0))
                seen += 1
                if len(rows) < self.cap:
                    rows.append(tuple(a[i] for a in arrays))
                else:
                    # classic reservoir: replace with prob cap/seen
                    j = int(self._rng.integers(0, seen))
                    if j < self.cap:
                        rows[j] = tuple(a[i] for a in arrays)
                self._strata[key] = (rows, seen)
            self.version += 1

    def stats(self) -> Dict[tuple, Tuple[int, int]]:
        with self._lock:
            return {k: (len(rows), seen)
                    for k, (rows, seen) in self._strata.items()}

    def to_arrays(self, dtypes) -> Tuple[List[np.ndarray], np.ndarray,
                                         np.ndarray]:
        """Materialize the sample: per-column arrays + weight column +
        stratum-id column (stable insertion-order ids)."""
        with self._lock:
            all_rows: List[tuple] = []
            weights: List[float] = []
            stratum_ids: List[int] = []
            for key, (rows, seen) in self._strata.items():
                sid = self._stratum_ids.setdefault(key,
                                                   len(self._stratum_ids))
                w = seen / max(1, len(rows))
                for r in rows:
                    all_rows.append(r)
                    weights.append(w)
                    stratum_ids.append(sid)
        cols: List[np.ndarray] = []
        for ci in range(self.num_columns):
            vals = [r[ci] for r in all_rows]
            dt = dtypes[ci]
            if dt.name == "string":
                cols.append(np.array(vals, dtype=object))
            else:
                cols.append(np.array(
                    [0 if v is None else v for v in vals],
                    dtype=dt.np_dtype))
        return (cols, np.array(weights, dtype=np.float64),
                np.array(stratum_ids, dtype=np.int64))


class SampleTableMaintainer:
    """Keeps a SAMPLE table's storage in sync with its base table: base
    inserts feed the reservoir, and the sample's column store is refreshed
    lazily before reads (ref: SampleInsertExec keeps samples transactional
    with base inserts)."""

    def __init__(self, sample_info, base_info, reservoir: StratifiedReservoir):
        self.sample_info = sample_info
        self.base_info = base_info
        self.reservoir = reservoir
        self._materialized_version = -1

    def on_insert(self, arrays, nulls=None) -> None:
        self.reservoir.observe(arrays)

    def refresh(self) -> None:
        if self._materialized_version == self.reservoir.version:
            return
        dtypes = [f.dtype for f in self.base_info.schema.fields]
        cols, weights, sids = self.reservoir.to_arrays(dtypes)
        self.sample_info.data.truncate()
        if len(weights):
            self.sample_info.data.insert_arrays(
                list(cols) + [weights, sids])
        self._materialized_version = self.reservoir.version
