"""Self-healing storage under injected faults (the tentpole's survival
half): tier-file corruption is quarantined and the batch REBUILT from a
surviving source (retained MVCC epoch, then the durable store) instead
of failing the query; with no source left the failure is a typed
`TierQuarantinedError`; memmap EIO gets one bounded re-read; a short
write aborts the spill with the batch still resident; the prefetch
worker self-restarts through injected deaths; and admission pressure
kicks the demotion ladder in the background."""

import os
import time

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.reliability import failpoints as rfail
from snappydata_tpu.storage import mvcc, tier

pytestmark = [pytest.mark.faults, pytest.mark.outofcore]


@pytest.fixture(autouse=True)
def _clean_failpoints():
    rfail.clear()
    rfail.reseed(4242)
    yield
    rfail.clear()


@pytest.fixture
def small_batches():
    props = config.global_properties()
    old = (props.column_batch_rows, props.column_max_delta_rows,
           props.scan_tile_bytes,
           props.tier_device_bytes, props.tier_host_bytes,
           props.tier_prefetch_depth)
    props.column_batch_rows = 256
    props.column_max_delta_rows = 256
    yield props
    (props.column_batch_rows, props.column_max_delta_rows,
     props.scan_tile_bytes,
     props.tier_device_bytes, props.tier_host_bytes,
     props.tier_prefetch_depth) = old


def _load(sess, n=1200, seed=7):
    rng = np.random.default_rng(seed)
    sess.sql("CREATE TABLE big (k STRING, v DOUBLE, w BIGINT) USING column")
    k = rng.choice(np.array(["a", "b", "c", "d"], dtype=object), n)
    v = rng.normal(100.0, 10.0, n)
    w = rng.integers(0, 1000, n, dtype=np.int64)
    sess.catalog.describe("big").data.insert_arrays([k, v, w])
    return k, v, w


def _c(name):
    return global_registry().counter(name)


def _corrupt_first_batch(data):
    col = data._manifest.views[0].batch.columns[1]  # v DOUBLE
    assert isinstance(col.data, np.memmap)
    path = str(col.data.filename)
    with open(path, "r+b") as fh:   # flip one part byte under the CRC
        fh.seek(col.data.offset)
        b = fh.read(1)
        fh.seek(col.data.offset)
        fh.write(bytes([b[0] ^ 0xFF]))
    return path


# -- quarantine + rebuild --------------------------------------------------

def test_injected_corruption_heals_from_retained_epoch(small_batches):
    """corrupt_bytes via the failpoint on the DEMOTE write; promotion's
    CRC catches it, the file is quarantined, and the batch grafts back
    from the retained pre-demotion epoch — values exact, query-visible
    error: none."""
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    data = sess.catalog.describe("big").data
    q = ("SELECT k, count(*), sum(v), min(w) FROM big "
         "GROUP BY k ORDER BY k")
    expected = sess.sql(q).rows()
    rfail.arm("tier.write", "corrupt_bytes", param=4, count=1)
    assert tier.demote_host([("big", data)], 1 << 40) > 0
    rfail.clear()
    q0, r0 = _c("tier_quarantined_files"), _c("tier_rebuilds")
    assert tier.promote_table(data) > 0           # heals, no raise
    assert _c("tier_quarantined_files") == q0 + 1
    assert _c("tier_rebuilds") == r0 + 1
    assert not any(isinstance(vw.batch.columns[1].data, np.memmap)
                   for vw in data._manifest.views)
    got = sess.sql(q).rows()
    assert len(got) == len(expected)
    for e, g in zip(expected, got):
        assert g[0] == e[0] and g[1] == e[1] and g[3] == e[3]
        assert g[2] == pytest.approx(e[2], rel=1e-9)


def test_corruption_heals_from_durable_store(tmp_path, small_batches):
    """With the retained epochs trimmed away, the rebuild falls through
    to the checkpointed batch file in the session's DiskStore."""
    sess = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                         recover=False)
    _load(sess)
    sess.checkpoint()                 # batch-<id>.col on disk
    data = sess.catalog.describe("big").data
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    assert tier.demote_host([("big", data)], 1 << 40) > 0
    mvcc.trim_unpinned([("big", data)])   # drop the resident twin
    assert not getattr(data, "_retained_epochs", None)
    _corrupt_first_batch(data)
    r0 = _c("tier_rebuilds")
    assert tier.promote_table(data) > 0
    assert _c("tier_rebuilds") == r0 + 1
    got = sess.sql(q).rows()
    assert int(got[0][0]) == int(expected[0][0])
    assert float(got[0][1]) == pytest.approx(float(expected[0][1]),
                                             rel=1e-9)
    sess.disk_store.close()


def test_corruption_without_source_raises_typed(small_batches):
    """No retained epoch, no durable store: the quarantine still
    happens, but the failure surfaces as the TYPED TierQuarantinedError
    (operator-actionable), not a bare CorruptRecordError."""
    sess = SnappySession(catalog=Catalog())
    # a table name no earlier-checkpointed DiskStore in this process
    # knows, so the durable-store fallback cannot accidentally serve
    sess.sql("CREATE TABLE lone (k STRING, v DOUBLE, w BIGINT) "
             "USING column")
    rng = np.random.default_rng(7)
    sess.catalog.describe("lone").data.insert_arrays(
        [rng.choice(np.array(["a", "b"], dtype=object), 1200),
         rng.normal(100.0, 10.0, 1200),
         rng.integers(0, 1000, 1200, dtype=np.int64)])
    data = sess.catalog.describe("lone").data
    assert tier.demote_host([("lone", data)], 1 << 40) > 0
    mvcc.trim_unpinned([("lone", data)])
    path = _corrupt_first_batch(data)
    f0, q0 = _c("tier_rebuild_failures"), _c("tier_quarantined_files")
    with pytest.raises(tier.TierQuarantinedError):
        tier.promote_table(data)
    assert _c("tier_rebuild_failures") == f0 + 1
    assert _c("tier_quarantined_files") == q0 + 1
    assert os.path.exists(path + ".quarantined")
    assert not os.path.exists(path)


# -- bounded retry / graceful abort ----------------------------------------

def test_memmap_eio_retried_once(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    data = sess.catalog.describe("big").data
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    assert tier.demote_host([("big", data)], 1 << 40) > 0
    rfail.arm("tier.memmap_read", "return_errno", count=1)
    t0 = _c("tier_read_retries")
    assert tier.promote_table(data) > 0    # one bounded re-read heals
    assert _c("tier_read_retries") == t0 + 1
    assert sess.sql(q).rows() == expected


def test_short_write_aborts_spill_batch_stays_resident(small_batches):
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    data = sess.catalog.describe("big").data
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    rfail.arm("tier.write", "short_write", param=64)
    b0 = tier.tier_file_bytes()
    tier.demote_host([("big", data)], 1 << 40)
    rfail.clear()
    # every spill aborted: nothing on disk, nothing memmapped, values up
    assert tier.tier_file_bytes() == b0
    assert not any(isinstance(vw.batch.columns[1].data, np.memmap)
                   for vw in data._manifest.views)
    assert sess.sql(q).rows() == expected


# -- prefetch worker self-restart ------------------------------------------

def test_prefetch_worker_restarts_after_injected_kill(small_batches):
    from snappydata_tpu.storage import prefetch

    sess = SnappySession(catalog=Catalog())
    _load(sess, n=3000)
    q = "SELECT k, count(*), sum(v) FROM big GROUP BY k ORDER BY k"
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    rfail.arm("prefetch.worker", "kill_worker", count=1)
    r0, d0 = _c("prefetch_worker_restarts"), _c("prefetch_worker_deaths")
    w0 = _c("prefetch_windows_warmed")
    got = sess.sql(q).rows()
    assert _c("prefetch_worker_deaths") == d0 + 1
    assert _c("prefetch_worker_restarts") == r0 + 1, \
        "the supervised worker must restart, not degrade to inline"
    assert _c("prefetch_windows_warmed") > w0, \
        "the restarted worker should still warm look-ahead windows"
    assert len(got) == len(expected)
    for e, g in zip(expected, got):
        assert g[0] == e[0] and g[1] == e[1]
        assert g[2] == pytest.approx(e[2], rel=1e-9)
    snap = prefetch.worker_snapshot()
    assert snap["worker_restarts"] >= 1


def test_prefetch_restart_cap(small_batches, monkeypatch):
    """A worker that dies EVERY time exhausts tier_prefetch_max_restarts
    and degrades to inline binds — bounded, never an infinite respawn
    loop — with values still exact."""
    from snappydata_tpu.storage.prefetch import TilePrefetcher

    def boom(self):
        raise RuntimeError("injected perma-death")

    monkeypatch.setattr(TilePrefetcher, "_loop", boom)
    sess = SnappySession(catalog=Catalog())
    _load(sess, n=3000)
    q = "SELECT count(*), sum(v) FROM big"
    expected = sess.sql(q).rows()
    small_batches.scan_tile_bytes = 2 * 256 * 32
    r0 = _c("prefetch_worker_restarts")
    cap = int(config.global_properties().tier_prefetch_max_restarts)
    assert sess.sql(q).rows() == expected
    assert _c("prefetch_worker_restarts") - r0 <= cap


# -- pressure-driven background demotion -----------------------------------

def test_pressure_demote_direct(small_batches):
    from snappydata_tpu.resource.broker import global_broker

    sess = SnappySession(catalog=Catalog())
    _load(sess)
    sess.sql("SELECT sum(v) FROM big")     # warm device plates
    d0 = _c("tier_pressure_demotions")
    n = tier.pressure_demote(global_broker(), target_bytes=0)
    assert n > 0
    assert _c("tier_pressure_demotions") == d0 + 1


def test_admission_pressure_kicks_background_demotion(small_batches):
    from snappydata_tpu.resource.broker import global_broker

    props = config.global_properties()
    saved = (props.memory_limit_bytes, props.tier_pressure_watermark)
    sess = SnappySession(catalog=Catalog())
    _load(sess)
    sess.sql("SELECT sum(v) FROM big")
    broker = global_broker()
    try:
        host, device = broker.measured_bytes(max_age_s=0.0)
        measured = host + device
        assert measured > 0
        # land measured residency BETWEEN the pressure watermark and the
        # high watermark: background relief, not synchronous degrade
        props.memory_limit_bytes = int(measured * 4)
        props.tier_pressure_watermark = 0.1
        w0 = _c("tier_pressure_wakeups")
        p0 = _c("tier_pressure_demotions")
        sess.sql("SELECT count(*) FROM big")   # admission sees pressure
        assert _c("tier_pressure_wakeups") == w0 + 1
        deadline = time.time() + 10.0
        while time.time() < deadline \
                and _c("tier_pressure_demotions") == p0:
            time.sleep(0.02)
        assert _c("tier_pressure_demotions") > p0, \
            "the background ladder pass never ran"
        # single-flight: a second admission while nothing is running
        # may wake again, but the flag must have been released
        with broker._pressure_lock:
            running = broker._pressure_running
        assert not running
    finally:
        (props.memory_limit_bytes, props.tier_pressure_watermark) = saved
