"""Data type system.

Covers the SQL surface the reference supports for column/row tables
(ref: SnappyDDLParser column data types; encoders/.../encoding/
ColumnEncoding.scala typeId registry :766-774). Physical mapping is
TPU-first: every type lowers to a fixed-width device dtype; variable-width
types (STRING/DECIMAL) lower to dictionary codes / scaled integers so the
hot loops stay vectorized with static shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class DataType:
    name: str

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.name

    @property
    def np_dtype(self) -> np.dtype:
        if self.name in ("array", "map", "struct"):
            return np.dtype(object)
        return _NP[self.name]

    def device_dtype(self) -> np.dtype:
        """dtype of the decoded on-device representation."""
        from snappydata_tpu import config

        if self.name == "string":
            return np.dtype(np.int32)  # dictionary codes
        if self.name == "decimal":
            if getattr(self, "is_exact", False):
                return np.dtype(np.int64)  # scaled unscaled-value ints
            return np.dtype(np.float64 if config.use_float64() else np.float32)
        if self.name in ("double", "float") and not config.use_float64():
            return np.dtype(np.float32)
        return self.np_dtype


@dataclasses.dataclass(frozen=True)
class ArrayType(DataType):
    """ARRAY<T>: stored as python lists (host); queries referencing array
    columns evaluate on the host path (device arrays are a later round)."""

    element: "DataType" = None

    def __str__(self):
        return f"array<{self.element}>"


@dataclasses.dataclass(frozen=True)
class MapType(DataType):
    """MAP<K,V>: python dicts, host-evaluated like ARRAY."""

    key: "DataType" = None
    value: "DataType" = None

    def __str__(self):
        return f"map<{self.key},{self.value}>"


@dataclasses.dataclass(frozen=True)
class StructType(DataType):
    """STRUCT<name: type, ...>: python dicts keyed by field name (host
    values); field access via element_at(col, 'name') / named_struct
    literals (ref: SerializedRow complex values,
    encoders/.../catalyst/util/SerializedRow.scala)."""

    fields: tuple = ()   # Tuple[Tuple[str, DataType], ...]

    def __str__(self):
        inner = ", ".join(f"{n}: {t}" for n, t in self.fields)
        return f"struct<{inner}>"

    def field_type(self, name: str) -> Optional["DataType"]:
        for n, t in self.fields:
            if n.lower() == name.lower():
                return t
        return None


@dataclasses.dataclass(frozen=True)
class DecimalType(DataType):
    """DECIMAL(p, s). TPU-first physical mapping (ref: exact BigDecimal
    semantics, encoders/.../encoding/ColumnEncoding.scala:137-140
    readDecimal):

    - p <= 18 ("exact"): DEVICE representation is the scaled int64
      unscaled value (v * 10^s) — SUM/MIN/MAX/COUNT/GROUP BY and
      +,-,*,% / comparisons run as fast native integer ops and stay
      EXACT; results decode to decimal.Decimal at the client edge. The
      HOST mirror (plates, WAL, deltas, hosteval fallback, and
      cross-server partial aggregates re-entering the distributed
      merge) stays float64, which round-trips any
      <= 15-significant-digit decimal exactly — so end-to-end
      exactness holds through p=15 (per-shard partials included) and
      device aggregation exactness through p=18.
    - p > 18: lowers to the float path (f32 plates on TPU with f64
      accumulators, <= 1e-6 relative — the pre-round-5 behavior).
    """

    precision: int = 38
    scale: int = 2

    def __str__(self) -> str:  # pragma: no cover - trivial
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_exact(self) -> bool:
        from snappydata_tpu import config

        return (self.precision <= 18
                and config.global_properties().decimal_exact)

    @property
    def scale_factor(self) -> int:
        return 10 ** self.scale


BOOLEAN = DataType("boolean")
BYTE = DataType("byte")
SHORT = DataType("short")
INT = DataType("int")
LONG = DataType("long")
FLOAT = DataType("float")
DOUBLE = DataType("double")
STRING = DataType("string")
DATE = DataType("date")          # int32 days since epoch
TIMESTAMP = DataType("timestamp")  # int64 microseconds since epoch
DECIMAL = DecimalType("decimal")

_NP = {
    "boolean": np.dtype(np.bool_),
    "byte": np.dtype(np.int8),
    "short": np.dtype(np.int16),
    "int": np.dtype(np.int32),
    "long": np.dtype(np.int64),
    "float": np.dtype(np.float32),
    "double": np.dtype(np.float64),
    "string": np.dtype(object),
    "date": np.dtype(np.int32),
    "timestamp": np.dtype(np.int64),
    "decimal": np.dtype(np.float64),
}

_BY_NAME = {
    "boolean": BOOLEAN, "bool": BOOLEAN,
    "byte": BYTE, "tinyint": BYTE,
    "short": SHORT, "smallint": SHORT,
    "int": INT, "integer": INT,
    "long": LONG, "bigint": LONG,
    "float": FLOAT, "real": FLOAT,
    "double": DOUBLE,
    "string": STRING, "varchar": STRING, "char": STRING, "clob": STRING,
    "date": DATE,
    "timestamp": TIMESTAMP,
    "decimal": DECIMAL, "numeric": DECIMAL,
}


def parse_type(name: str, args: Optional[list] = None,
               element: Optional[DataType] = None,
               key: Optional[DataType] = None,
               fields: Optional[list] = None) -> DataType:
    if name.lower() == "array":
        return ArrayType("array", element or DOUBLE)
    if name.lower() == "map":
        return MapType("map", key or STRING, element or DOUBLE)
    if name.lower() == "struct":
        return StructType("struct", tuple(fields or ()))
    base = _BY_NAME.get(name.lower())
    if base is None:
        raise ValueError(f"unknown data type: {name}")
    if base.name == "decimal" and args:
        prec = int(args[0])
        scale = int(args[1]) if len(args) > 1 else 0
        return DecimalType("decimal", prec, scale)
    return base


def is_numeric(dt: DataType) -> bool:
    return dt.name in ("byte", "short", "int", "long", "float", "double",
                       "decimal", "date", "timestamp")


def is_integral(dt: DataType) -> bool:
    return dt.name in ("byte", "short", "int", "long", "date", "timestamp")


def is_floating(dt: DataType) -> bool:
    return dt.name in ("float", "double", "decimal")


def common_type(a: DataType, b: DataType) -> DataType:
    """Numeric type promotion for binary expressions."""
    if a.name == b.name:
        if a.name == "decimal" and a != b:
            return _decimal_align_type(a, b)
        return a
    if "decimal" in (a.name, b.name):
        dec, other = (a, b) if a.name == "decimal" else (b, a)
        if other.name in ("float", "double"):
            return DOUBLE
        if other.name in _INT_DIGITS:
            return _decimal_align_type(dec, _int_as_decimal(other))
        if other.name == "string":
            return STRING
        return DOUBLE
    order = ["boolean", "byte", "short", "int", "date", "long", "timestamp",
             "float", "decimal", "double"]
    if a.name in order and b.name in order:
        return _BY_NAME[max(a.name, b.name, key=order.index)]
    if STRING in (a, b):
        return STRING
    raise TypeError(f"incompatible types: {a} vs {b}")


# ---------------------------------------------------------------------------
# Exact-decimal type algebra (shared by the analyzer's expr_type and the
# runtime's scaled-int lowering so declared scale always matches the
# computed representation). Result precision/scale follow Spark's
# DecimalPrecision rules, capped: a result that would exceed precision
# 18 lowers to DOUBLE instead (int64 can't hold it; the reference holds
# p <= 38 via BigDecimal — documented divergence).
# ---------------------------------------------------------------------------

DECIMAL_EXACT_MAX_PRECISION = 18

_INT_DIGITS = {"boolean": 1, "byte": 3, "short": 5, "int": 10, "long": 19}


def _int_as_decimal(t: DataType) -> "DecimalType":
    return DecimalType("decimal", _INT_DIGITS[t.name], 0)


def _decimal_align_type(a: "DecimalType", b: "DecimalType") -> DataType:
    s = max(a.scale, b.scale)
    p = max(a.precision - a.scale, b.precision - b.scale) + s
    if p > DECIMAL_EXACT_MAX_PRECISION:
        return DOUBLE
    return DecimalType("decimal", p, s)


def decimal_binop_type(op: str, a: DataType, b: DataType
                       ) -> Optional[DataType]:
    """Result type of a +,-,*,%,/ over operands where at least one side
    is decimal. None = not a decimal-typed operation (caller falls back
    to common_type). DOUBLE = the operation leaves the exact domain."""
    if "decimal" not in (a.name, b.name):
        return None
    if op == "/":
        return DOUBLE
    for t in (a, b):
        if t.name in ("float", "double") or (
                t.name not in _INT_DIGITS and t.name != "decimal"):
            return DOUBLE
    da = a if a.name == "decimal" else _int_as_decimal(a)
    db = b if b.name == "decimal" else _int_as_decimal(b)
    if op == "*":
        p = da.precision + db.precision + 1
        s = da.scale + db.scale
        if p > DECIMAL_EXACT_MAX_PRECISION or not (
                isinstance(da, DecimalType) and da.is_exact
                and isinstance(db, DecimalType) and db.is_exact):
            return DOUBLE
        return DecimalType("decimal", p, s)
    if op in ("+", "-", "%"):
        s = max(da.scale, db.scale)
        p = max(da.precision - da.scale, db.precision - db.scale) + s + 1
        if p > DECIMAL_EXACT_MAX_PRECISION:
            return DOUBLE
        return DecimalType("decimal", p, s)
    return None


def decimal_sum_type(dt: DataType) -> DataType:
    """SUM over a decimal column: widen precision (Spark: p+10), capped
    at the exact-int64 limit — the in-trace overflow check reroutes to
    the host path if a group total could actually exceed int64."""
    if not isinstance(dt, DecimalType) or not dt.is_exact:
        return DOUBLE
    return DecimalType("decimal",
                       min(dt.precision + 10, DECIMAL_EXACT_MAX_PRECISION),
                       dt.scale)


def decimal_to_unscaled(dt: DataType, arr) -> np.ndarray:
    """Host-domain (float) decimal values -> scaled int64 unscaled
    values, rounding half away from zero at the column scale (HALF_UP,
    matching _dec_rescale_int and java BigDecimal — np.round would tie
    to even and disagree with the device rescale path)."""
    a = np.asarray(arr, dtype=np.float64) * float(dt.scale_factor)
    return (np.sign(a) * np.floor(np.abs(a) + 0.5)).astype(np.int64)


def unscaled_to_python(dt: DataType, v: int):
    """Scaled int64 -> decimal.Decimal at the column scale."""
    import decimal as _d

    return _d.Decimal(int(v)).scaleb(-dt.scale)


def decimal_float_converter(dt: DataType):
    """Column-level converter: float-domain decimal value ->
    decimal.Decimal quantized at the column scale, with the quantizer
    hoisted once (per-cell construction was measurable on streamed
    exports). Exact whenever the f64 faithfully represents the decimal,
    i.e. <= 15 significant digits."""
    import decimal as _d

    q = _d.Decimal(1).scaleb(-dt.scale)

    def conv(v):
        return _d.Decimal(repr(float(v))).quantize(
            q, rounding=_d.ROUND_HALF_UP)

    return conv


def float_to_python_decimal(dt: DataType, v: float):
    """One-off variant of decimal_float_converter."""
    return decimal_float_converter(dt)(v)


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    dtype: DataType
    nullable: bool = True


@dataclasses.dataclass(frozen=True)
class Schema:
    fields: tuple

    def __init__(self, fields):
        object.__setattr__(self, "fields", tuple(fields))

    def names(self):
        return [f.name for f in self.fields]

    def field(self, name: str) -> Field:
        name_l = name.lower()
        for f in self.fields:
            if f.name.lower() == name_l:
                return f
        raise KeyError(f"no such column: {name}")

    def index(self, name: str) -> int:
        name_l = name.lower()
        for i, f in enumerate(self.fields):
            if f.name.lower() == name_l:
                return i
        raise KeyError(f"no such column: {name}")

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)


def python_value(dt: DataType, v: Any) -> Any:
    """Coerce a parsed literal to the column's python/numpy domain."""
    if v is None:
        return None
    if dt.name in ("byte", "short", "int", "long", "date", "timestamp"):
        return int(v)
    if dt.name in ("float", "double", "decimal"):
        return float(v)
    if dt.name == "boolean":
        return bool(v)
    return str(v)
