"""Kill → rejoin → rebalance on a 3-server cluster (ref:
CALL SYS.REBALANCE_ALL_BUCKETS(), rebalance-all-buckets.md; HA walkthrough
docs/architecture/cluster_architecture.md).

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/rebalance_cluster.py
"""

import numpy as np

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.cluster import LocatorNode, ServerNode
from snappydata_tpu.cluster.distributed import DistributedSession


def main():
    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[sv.flight_address for sv in servers])
    try:
        ds.sql("CREATE TABLE t (k BIGINT, v DOUBLE) USING column "
               "OPTIONS (partition_by 'k', redundancy '1')")
        rng = np.random.default_rng(2)
        ds.insert_arrays("t", [rng.integers(0, 90_000, 60_000)
                               .astype(np.int64), np.ones(60_000)])

        def counts():
            return [sum(1 for b in range(ds.num_buckets)
                        if ds.bucket_map[b] == m) for m in range(3)]

        print("buckets per member:", counts())
        servers[2].stop()
        ds.mark_server_failed(2)
        print("after member death:", counts(),
              "count:", ds.sql("SELECT count(*) FROM t").rows()[0][0])
        servers[2] = ServerNode(locator.address,
                                SnappySession(catalog=Catalog())).start()
        ds.replace_server(2, servers[2].flight_address)
        out = ds.rebalance()
        print("rebalanced:", out)
        print("count unchanged:",
              ds.sql("SELECT count(*) FROM t").rows()[0][0])
    finally:
        ds.close()
        for sv in servers:
            try:
                sv.stop()
            except Exception:
                pass
        locator.stop()


if __name__ == "__main__":
    main()
