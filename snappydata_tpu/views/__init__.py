"""Incrementally-maintained materialized aggregates.

`matview.py` holds the whole subsystem: definition validation, the
delta-fold partial programs, the [G]-space device-resident state with
{2^k, 1.5*2^k} bucket-ladder growth, subtraction on deletes, staleness,
checkpoint/recovery glue, and the observability snapshot.
"""

from snappydata_tpu.views.matview import (MaterializedView, MatViewError,
                                          matviews, matviews_on,
                                          view_snapshot)

__all__ = ["MaterializedView", "MatViewError", "matviews", "matviews_on",
           "view_snapshot"]
