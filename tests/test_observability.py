"""End-to-end observability suite: request tracing (trace-id
propagation across a real 2-server cluster), EXPLAIN ANALYZE
value-asserted against the engine's own counters, log-bucketed
histogram quantile correctness, slow-query log + ring bounds, the
REST/dashboard surfaces, trace-aware error reporting, and the
tracing-disabled overhead guard."""

import json
import time
import threading
import urllib.request

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability import tracing
from snappydata_tpu.observability.metrics import (MetricsRegistry, Timer,
                                                 global_registry)

pytestmark = pytest.mark.observability


@pytest.fixture(autouse=True)
def _restore_knobs():
    props = config.global_properties()
    saved = (props.tracing_enabled, props.trace_ring_entries,
             props.slow_query_ms)
    yield props
    (props.tracing_enabled, props.trace_ring_entries,
     props.slow_query_ms) = saved


def _mk_session(n: int = 1000) -> SnappySession:
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE t (g BIGINT, v DOUBLE) USING column")
    s.insert_arrays("t", [np.arange(n, dtype=np.int64) % 4,
                          np.arange(n, dtype=np.float64)])
    return s


# ----------------------------------------------------------------------
# histogram timers
# ----------------------------------------------------------------------

def test_histogram_quantiles_uniform_distribution():
    t = Timer()
    for i in range(1, 1001):            # 1ms .. 1000ms uniform
        t.record(i / 1000.0)
    d = t.to_dict()
    assert d["count"] == 1000
    assert d["min_s"] == 0.001 and d["max_s"] == 1.0
    # log-bucketed (4/octave) + intra-bucket interpolation: each
    # quantile lands within 25% of the exact order statistic
    for key, exact in (("p50_s", 0.500), ("p99_s", 0.990),
                       ("p999_s", 0.999)):
        assert abs(d[key] - exact) / exact < 0.25, (key, d[key], exact)
    assert d["p50_s"] <= d["p99_s"] <= d["p999_s"]


def test_histogram_quantiles_bimodal_tail():
    """The histogram exists for exactly this: 100 fast requests + 1
    outlier — the mean hides it, p99.9 must not."""
    t = Timer()
    for _ in range(100):
        t.record(0.001)
    t.record(1.0)
    d = t.to_dict()
    assert d["p50_s"] < 0.002
    assert d["p999_s"] > 0.5            # the outlier is visible
    assert d["mean_s"] < 0.02           # ... and the mean hid it
    # constant distribution: p50 == p99 == the single value (clamped to
    # observed min/max, so exact)
    t2 = Timer()
    for _ in range(50):
        t2.record(0.25)
    d2 = t2.to_dict()
    assert d2["p50_s"] == d2["p99_s"] == d2["p999_s"] == 0.25


def test_query_timer_surfaces_quantiles_in_snapshot():
    s = _mk_session()
    for _ in range(3):
        s.sql("SELECT g, sum(v) FROM t GROUP BY g")
    snap = global_registry().snapshot()
    q = snap["timers"]["query"]
    assert {"p50_s", "p99_s", "p999_s"} <= set(q)
    assert 0 < q["p50_s"] <= q["p99_s"] <= q["p999_s"] <= q["max_s"]
    s.stop()


def test_snapshot_gauge_touching_registry_does_not_deadlock():
    """Satellite regression: gauge callables used to run while HOLDING
    the non-reentrant registry lock, so a gauge that reads the registry
    (a ledger walk refreshing a gauge cache) self-deadlocked."""
    r = MetricsRegistry()
    r.inc("x", 7)
    r.gauge("self_reader", lambda: float(r.counter("x")))
    out = {}

    def snap():
        out["snap"] = r.snapshot()

    th = threading.Thread(target=snap, daemon=True)
    th.start()
    th.join(timeout=5)
    assert not th.is_alive(), "snapshot() deadlocked on a registry gauge"
    assert out["snap"]["gauges"]["self_reader"] == 7.0


def test_prometheus_exposition_types_histograms_collisions():
    r = MetricsRegistry()
    # distinct raw names, one sanitized form: must NOT silently overwrite
    r.inc("a.b", 1)
    r.inc("a_b", 2)
    r.gauge("g1", lambda: 3.5)
    for ms in (1, 2, 5, 10, 500):
        r.record_time("lat", ms / 1000.0)
    out = r.to_prometheus()
    assert "# TYPE" in out and "# HELP" in out
    assert "# TYPE snappy_tpu_a_b_total counter" in out
    # the collision got a deterministic suffix; both values survive
    values = sorted(int(ln.rsplit(" ", 1)[1]) for ln in out.splitlines()
                    if ln.startswith("snappy_tpu_a_b") and
                    ln.split(" ")[0].endswith("_total"))
    assert values == [1, 2]
    assert "# TYPE snappy_tpu_lat_seconds histogram" in out
    assert 'snappy_tpu_lat_seconds_bucket{le="+Inf"} 5' in out
    assert "snappy_tpu_lat_seconds_count 5" in out
    # cumulative bucket counts are monotone
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in out.splitlines()
            if ln.startswith("snappy_tpu_lat_seconds_bucket")]
    assert cums == sorted(cums)
    # quantiles ride as a sibling gauge family
    assert 'snappy_tpu_lat_seconds_q{quantile="0.999"}' in out


# ----------------------------------------------------------------------
# trace ring / slow-query log / disabled overhead
# ----------------------------------------------------------------------

def test_trace_ring_bounded(_restore_knobs):
    _restore_knobs.trace_ring_entries = 5
    s = _mk_session()
    tracing.ring().clear()
    before = tracing.ring().recorded
    for i in range(12):
        s.sql(f"SELECT count(*) FROM t WHERE g = {i % 4}")
    assert tracing.ring().recorded - before >= 12
    assert len(tracing.ring().traces(100)) <= 5
    s.stop()


def test_slow_query_log_threshold(_restore_knobs):
    s = _mk_session()
    tracing.ring().clear()
    _restore_knobs.slow_query_ms = 1e-4   # everything is "slow"
    c0 = global_registry().counter("slow_queries")
    s.sql("SELECT sum(v) FROM t")
    slow = tracing.ring().slow()
    assert slow and slow[0]["sql"].startswith("SELECT sum(v)")
    # the slow entry keeps its FULL span tree
    assert "root" in slow[0] and slow[0]["root"]["children"]
    assert global_registry().counter("slow_queries") > c0
    _restore_knobs.slow_query_ms = 1e9    # nothing is slow
    n = len(tracing.ring().slow())
    s.sql("SELECT sum(v) FROM t")
    assert len(tracing.ring().slow()) == n
    s.stop()


def test_tracing_disabled_records_nothing_and_spans_are_cheap(
        _restore_knobs):
    _restore_knobs.tracing_enabled = False
    s = _mk_session()
    tracing.ring().clear()
    s.sql("SELECT sum(v) FROM t")
    assert tracing.ring().traces(100) == []
    assert tracing.current() is None
    # the overhead guard's substrate: an untraced span is one contextvar
    # read, no allocation — 20k of them must stay well under 100ms
    t0 = time.perf_counter()
    for _ in range(20000):
        with tracing.span("x"):
            pass
    assert time.perf_counter() - t0 < 0.5
    s.stop()


def test_trace_span_children_capped(_restore_knobs):
    with tracing.request_scope("cap test", user="t", kind="session",
                               force=True) as tr:
        for _ in range(5000):
            with tracing.span("tick"):
                pass
    assert len(tr.root.children) <= 256
    assert tr.root.attrs["children_truncated"] > 0


# ----------------------------------------------------------------------
# EXPLAIN ANALYZE
# ----------------------------------------------------------------------

def _line(rows, needle):
    for r in rows:
        if needle in r[0]:
            return r[0]
    raise AssertionError(f"no line containing {needle!r} in "
                         f"{[r[0] for r in rows]}")


def _field(line, key) -> str:
    for tok in line.replace("]", " ").replace("[", " ").split():
        if tok.startswith(key + "="):
            return tok.split("=", 1)[1]
    raise AssertionError(f"{key}= not in {line!r}")


def test_explain_analyze_counts_match_engine_counters():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE big (k BIGINT, v DOUBLE) USING column")
    n = 262144   # exactly 2 full default batches, k ascending
    s.insert_arrays("big", [np.arange(n, dtype=np.int64),
                            np.arange(n, dtype=np.float64)])
    q = "SELECT count(*), sum(v) FROM big WHERE k >= 200000"
    # expected counters from a DIRECT run of the same query
    expect = s.sql(q).rows()[0]
    c0 = global_registry().counters_snapshot()
    s.sql(q)
    c1 = global_registry().counters_snapshot()
    seen = c1.get("column_batches_seen", 0) - \
        c0.get("column_batches_seen", 0)
    skipped = c1.get("column_batches_skipped", 0) - \
        c0.get("column_batches_skipped", 0)
    assert seen == 2 and skipped == 1   # min/max stats prune batch 0
    rows = s.sql("EXPLAIN ANALYZE " + q).rows()
    scan = _line(rows, "Scan big")
    assert int(_field(scan, "batches_seen")) == seen
    assert int(_field(scan, "skipped_stats")) == skipped
    assert int(_field(scan, "rows")) == n
    footer = _line(rows, "trace_id=")
    assert int(_field(footer, "rows_out")) == 1
    assert expect[0] == n - 200000      # the ANALYZE run really ran it
    stats = _line(rows, "batches_seen=2")
    assert _field(stats, "skipped_stats") == "1"
    # phase breakdown + trace id present and joinable against the ring
    phases = _line(rows, "phases:")
    assert "bind=" in phases and "transfer=" in phases
    tid = _field(footer, "trace_id")
    assert tracing.ring().get(tid), "EXPLAIN ANALYZE trace not in ring"
    s.stop()


def test_explain_analyze_strategy_and_plain_explain():
    s = _mk_session()
    q = "SELECT g, count(*), sum(v) FROM t GROUP BY g"
    rows = s.sql("EXPLAIN ANALYZE " + q).rows()
    agg = _line(rows, "HashAggregate")
    assert "strategy=" in agg
    assert int(_field(agg, "rows_out")) == 4
    scan = _line(rows, "Scan t")
    assert "code_domain=" in scan
    # plain EXPLAIN: no execution, no runtime footer
    plain = s.sql("EXPLAIN " + q).rows()
    assert not any("rows_out=" in r[0] for r in plain)
    assert not any("batches_seen=" in r[0] for r in plain)
    s.stop()


def test_explain_analyze_works_with_tracing_disabled(_restore_knobs):
    _restore_knobs.tracing_enabled = False
    s = _mk_session()
    rows = s.sql("EXPLAIN ANALYZE SELECT sum(v) FROM t").rows()
    footer = _line(rows, "rows_out=")
    assert int(_field(footer, "rows_out")) == 1
    assert "phases:" in _line(rows, "phases:")
    s.stop()


# ----------------------------------------------------------------------
# trace-aware errors
# ----------------------------------------------------------------------

def test_errors_carry_trace_id():
    from snappydata_tpu.cluster.distributed import DistributedError
    from snappydata_tpu.resource.context import CancelException

    with tracing.request_scope("SELECT 1", user="t", kind="session",
                               force=True) as tr:
        ce = CancelException("deadline")
        de = DistributedError("member lost")
    assert ce.trace_id == tr.trace_id
    assert f"[trace {tr.trace_id}]" in str(ce)
    assert de.trace_id == tr.trace_id
    assert f"[trace {tr.trace_id}]" in str(de)
    # untraced: no id, message unchanged
    ce2 = CancelException("deadline")
    assert ce2.trace_id is None and "[trace" not in str(ce2)


# ----------------------------------------------------------------------
# cluster propagation: one trace id, client → fan-out legs → servers
# ----------------------------------------------------------------------

def test_trace_propagates_across_two_server_cluster():
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    locator = LocatorNode().start()
    servers = [ServerNode(locator.address,
                          SnappySession(catalog=Catalog())).start()
               for _ in range(2)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    try:
        ds.sql("CREATE TABLE tx (k BIGINT, amt DOUBLE) USING column "
               "OPTIONS (partition_by 'k')")
        rng = np.random.default_rng(7)
        k = rng.integers(0, 500, 4000).astype(np.int64)
        amt = rng.random(4000)
        ds.insert_arrays("tx", [k, amt])
        tracing.ring().clear()
        got = ds.sql("SELECT count(*), sum(amt) FROM tx").rows()[0]
        assert got[0] == 4000
        assert abs(got[1] - float(amt.sum())) < 1e-6
        # the lead minted ONE id for the request ...
        leads = [t for t in tracing.ring().traces(100)
                 if t["kind"] == "lead" and t["sql"].startswith("SELECT")]
        assert leads, "no lead trace recorded"
        tid = leads[0]["trace_id"]
        full = tracing.ring().get(tid)
        lead = next(t for t in full if t["kind"] == "lead")
        # ... with one fan-out leg span per member under it
        members = [sp for sp in lead["root"]["children"]
                   if sp["name"] == "member"]
        addrs = {sp["attrs"]["addr"] for sp in members}
        assert len(addrs) == 2, (addrs, lead)
        # ... and BOTH servers opened their own trace under the SAME id
        # (in-process test cluster: every member shares one ring, so the
        # server traces are distinguished by their origin address)
        origins = {t["origin"] for t in full if t["kind"] == "server"}
        assert len(origins) == 2, full
        # the member spans stitched the per-call flight spans too
        assert any(c["name"].startswith("flight")
                   for sp in members for c in sp.get("children", ()))
    finally:
        ds.close()
        for s in servers:
            s.stop()
        locator.stop()


# ----------------------------------------------------------------------
# REST + dashboard surfaces
# ----------------------------------------------------------------------

def test_rest_traces_endpoint_and_dashboard():
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    s = _mk_session()
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    base = f"http://{svc.host}:{svc.port}"
    try:
        # POST /sql mints a trace id and returns it
        req = urllib.request.Request(
            base + "/sql",
            data=json.dumps({"sql": "SELECT sum(v) FROM t"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["rows"] and "trace_id" in body
        tid = body["trace_id"]
        # the ring lists it ...
        with urllib.request.urlopen(base + "/status/api/v1/traces",
                                    timeout=5) as resp:
            listing = json.loads(resp.read())
        assert listing["tracing_enabled"] is True
        assert any(t["trace_id"] == tid for t in listing["traces"])
        # ... and serves the full span tree by id
        with urllib.request.urlopen(
                base + f"/status/api/v1/traces?trace_id={tid}",
                timeout=5) as resp:
            detail = json.loads(resp.read())
        assert detail["traces"] and \
            detail["traces"][0]["root"]["children"]
        assert "phases_ms" in detail["traces"][0]
        # slow view answers (empty is fine with the knob off)
        with urllib.request.urlopen(
                base + "/status/api/v1/traces?slow=1", timeout=5) as resp:
            assert "slow" in json.loads(resp.read())
        with urllib.request.urlopen(base + "/dashboard",
                                    timeout=5) as resp:
            html = resp.read().decode()
        assert "Tracing" in html and tid in html
        # /metrics/prometheus carries the histogram exposition
        with urllib.request.urlopen(base + "/metrics/prometheus",
                                    timeout=5) as resp:
            prom = resp.read().decode()
        assert "# TYPE snappy_tpu_query_seconds histogram" in prom
        assert 'snappy_tpu_query_seconds_q{quantile="0.999"}' in prom
    finally:
        svc.stop()
        s.stop()


def test_rest_error_body_carries_trace_id():
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    s = _mk_session()
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        req = urllib.request.Request(
            f"http://{svc.host}:{svc.port}/sql",
            data=json.dumps(
                {"sql": "SELECT nope FROM no_such_table"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        body = json.loads(ei.value.read())
        assert "error" in body and body.get("trace_id")
        # the failed request's trace landed in the ring, status=error
        hits = tracing.ring().get(body["trace_id"])
        assert hits and hits[0]["status"] == "error"
    finally:
        svc.stop()
        s.stop()


# ----------------------------------------------------------------------
# serving-path + bench-guard logic
# ----------------------------------------------------------------------

def test_serving_trace_annotations():
    s = _mk_session()
    h = s.prepare("SELECT count(*) FROM t WHERE g = ?")
    tracing.ring().clear()
    h.execute((1,))
    h.execute((2,))
    traces = tracing.ring().traces(10)
    kinds = [t["kind"] for t in traces]
    assert kinds.count("serving") >= 2
    tid = [t for t in traces if t["kind"] == "serving"][0]["trace_id"]
    detail = tracing.ring().get(tid)[0]
    assert detail["root"]["attrs"]["serving_registry"] == "hit"
    s.stop()


def test_bench_tracing_overhead_guard_logic():
    import bench

    base = {"value": 1e6, "detail": {}}
    over = {"value": 1e6, "detail": {"tracing": {
        "overhead_pct": 5.0, "geomean_on": 95.0, "geomean_off": 100.0}}}
    fails = bench.check_regression(over, base)
    assert any("tracing overhead" in f for f in fails)
    ok = {"value": 1e6, "detail": {"tracing": {
        "overhead_pct": 1.2, "geomean_on": 99.0, "geomean_off": 100.0}}}
    assert not bench.check_regression(ok, base)
    # records predating the tracing section stay comparable
    assert not bench.check_regression(base, base)
