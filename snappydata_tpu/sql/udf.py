"""SQL-registered scalar functions (UDFs).

Reference parity: CREATE FUNCTION registers a named function callable
inside SQL expressions (SnappyDDLParser.scala:765 createFunction,
dispatched at :1056 — there a JVM class from a jar; here a Python
expression over array values). TPU-first twist: the body is evaluated
on the TRACED values inside the compiled query program, so a UDF built
from jax/numpy-style ops fuses into the same XLA executable as the rest
of the plan — no per-row interpreter, no host round trip. The host
fallback path evaluates the identical body on numpy arrays.

    CREATE FUNCTION taxed AS 'lambda price, rate: price * (1 + rate)'
        RETURNS DOUBLE
    SELECT taxed(l_extendedprice, l_tax) FROM lineitem

The body must be a Python lambda (or a named-function expression)
operating elementwise with jnp/np-compatible ops; it is compiled with
`eval` in a restricted namespace (jnp, np, math lambdas only — no
builtins). Creating a function is a code-execution surface and is gated
exactly like EXEC PYTHON on network-derived sessions.

Functions live in `catalog._functions` (persisted through aux DDL
replay like policies/indexes); the active catalog's registry is exposed
to the expression compilers through a contextvar that the session
installs around each query.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Callable, Optional

from snappydata_tpu import types as T


@dataclasses.dataclass
class UdfDef:
    name: str
    body: str
    returns: Optional[T.DataType]
    fn: Callable


_active: contextvars.ContextVar = contextvars.ContextVar(
    "udf_registry", default=None)


@contextlib.contextmanager
def using(catalog):
    """Install `catalog`'s function registry for the current execution
    (expression compilation + host evaluation read it via lookup())."""
    tok = _active.set(getattr(catalog, "_functions", None))
    try:
        yield
    finally:
        _active.reset(tok)


def lookup(name: str) -> Optional[UdfDef]:
    reg = _active.get()
    if not reg:
        return None
    return reg.get(name.lower())


def compile_body(name: str, body: str) -> Callable:
    """eval the function body in a restricted namespace. The DDL surface
    is admin-gated (same as EXEC PYTHON); the restriction keeps honest
    functions honest, it is not a sandbox."""
    import math

    import jax.numpy as jnp
    import numpy as np

    ns = {"jnp": jnp, "np": np, "math": math, "__builtins__": {
        "abs": abs, "min": min, "max": max, "len": len, "float": float,
        "int": int, "round": round}}
    try:
        fn = eval(body, ns)  # noqa: S307 — gated DDL surface
    except Exception as e:
        raise ValueError(f"CREATE FUNCTION {name}: body does not "
                         f"evaluate ({e})")
    if not callable(fn):
        raise ValueError(f"CREATE FUNCTION {name}: body must evaluate "
                         f"to a callable (e.g. a lambda)")
    return fn


def register(catalog, name: str, body: str,
             returns: Optional[T.DataType]) -> UdfDef:
    if not hasattr(catalog, "_functions"):
        catalog._functions = {}
    from snappydata_tpu.sql import ast

    low = name.lower()
    if low in ast.AGG_FUNCS:
        raise ValueError(f"cannot redefine aggregate function {name}")
    d = UdfDef(low, body, returns, compile_body(name, body))
    catalog._functions[low] = d
    catalog.generation += 1   # cached plans baked the old body
    return d


def unregister(catalog, name: str, if_exists: bool) -> bool:
    reg = getattr(catalog, "_functions", {})
    if name.lower() not in reg:
        if if_exists:
            return False
        raise ValueError(f"function not found: {name}")
    del reg[name.lower()]
    catalog.generation += 1
    return True
