"""ARRAY<T> column support (host-evaluated; ref: complex types surface,
ComplexTypeSerializer) — storage, literals, size/contains/element_at,
subscripts, NULLs, persistence."""

import numpy as np
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    yield sess
    sess.stop()


def test_array_create_insert_select(s):
    s.sql("CREATE TABLE t (id INT, tags ARRAY<STRING>) USING column")
    s.sql("INSERT INTO t VALUES (1, array('a', 'b')), (2, array('c')), "
          "(3, NULL)")
    rows = s.sql("SELECT id, tags FROM t ORDER BY id").rows()
    assert rows[0] == (1, ["a", "b"])
    assert rows[1] == (2, ["c"])
    assert rows[2][1] is None


def test_array_functions(s):
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(10, 20, 30)), (2, array(5))")
    assert s.sql("SELECT id, size(v) FROM t ORDER BY id").rows() == \
        [(1, 3), (2, 1)]
    assert s.sql("SELECT id FROM t WHERE array_contains(v, 20)").rows() == \
        [(1,)]
    # subscript (0-based) and element_at (1-based)
    assert s.sql("SELECT v[0], element_at(v, 2) FROM t WHERE id = 1"
                 ).rows() == [(10, 20)]
    # out-of-bounds → NULL
    assert s.sql("SELECT element_at(v, 9) FROM t WHERE id = 2"
                 ).rows()[0][0] is None


def test_array_rollover_and_nonarray_queries_stay_on_device(s):
    from snappydata_tpu.observability.metrics import global_registry

    s.sql("CREATE TABLE t (k INT, v ARRAY<INT>) USING column "
          "OPTIONS (column_max_delta_rows '4')")
    for i in range(10):
        s.sql(f"INSERT INTO t VALUES ({i}, array({i}, {i + 1}))")
    assert s.sql("SELECT size(v) FROM t WHERE k = 7").rows() == [(2,)]
    # a query not touching the array column still runs on device
    before = global_registry().counter("host_fallbacks")
    assert s.sql("SELECT sum(k) FROM t").rows()[0][0] == sum(range(10))
    assert global_registry().counter("host_fallbacks") == before


def test_array_contains_null_needle(s):
    # a NULL needle yields NULL (filtered out), not a match (review fix)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>, nn INT) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2), 1), (2, array(3), NULL)")
    assert s.sql("SELECT id FROM t WHERE array_contains(v, nn)").rows() == \
        [(1,)]


def test_group_by_and_distinct_on_arrays(s):
    # unhashable list cells must not crash GROUP BY/DISTINCT (review fix)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2)), (2, array(1, 2)), "
          "(3, array(9))")
    assert s.sql("SELECT v, count(*) FROM t GROUP BY v ORDER BY 2 DESC"
                 ).rows() == [([1, 2], 2), ([9], 1)]
    assert len(s.sql("SELECT DISTINCT v FROM t").rows()) == 2


def test_numpy_array_cells_persist(tmp_path):
    # numpy values inside array cells serialize to the WAL (review fix)
    import numpy as np

    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.insert("t", (1, np.array([1, 2])), (2, np.array([3, 4])))
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    assert s2.sql("SELECT id, v FROM t ORDER BY id").rows() == \
        [(1, [1, 2]), (2, [3, 4])]


def test_array_persistence(tmp_path):
    s = SnappySession(catalog=Catalog(), data_dir=str(tmp_path),
                      recover=False)
    s.sql("CREATE TABLE t (id INT, v ARRAY<INT>) USING column")
    s.sql("INSERT INTO t VALUES (1, array(1, 2)), (2, NULL)")
    s.checkpoint()
    s.sql("INSERT INTO t VALUES (3, array(9))")  # WAL tail
    s.disk_store.close()
    s2 = SnappySession(data_dir=str(tmp_path))
    rows = s2.sql("SELECT id, v FROM t ORDER BY id").rows()
    assert rows == [(1, [1, 2]), (2, None), (3, [9])]
