"""TPC-DS reporting-family harness (ref: TPCDSQuerySnappyBenchmark) —
canonical query text over the synthetic star schema, value-asserted
against pandas oracles, single-node and distributed."""

import numpy as np
import pandas as pd
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.utils import tpcds


@pytest.fixture(scope="module")
def sess():
    s = SnappySession(catalog=Catalog())
    tpcds.load_tpcds(s, sf=0.003, seed=11)
    yield s
    s.stop()


def _frames(seed=11, sf=0.003):
    sz = tpcds.table_sizes(sf)   # shared sizing: oracle == loaded data
    dd = tpcds.gen_date_dim(seed=seed)
    return {
        "date_dim": pd.DataFrame(dd),
        "item": pd.DataFrame(tpcds.gen_item(sz["item"], seed + 1)),
        "store_sales": pd.DataFrame(tpcds.gen_store_sales(
            sz["store_sales"], len(dd["d_date_sk"]), sz["item"],
            sz["customer"], sz["store"], seed + 5)),
    }


def test_q3_matches_pandas(sess):
    f = _frames()
    j = (f["store_sales"]
         .merge(f["date_dim"], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
         .merge(f["item"], left_on="ss_item_sk", right_on="i_item_sk"))
    j = j[(j.i_manufact_id == 100) & (j.d_moy == 11)]
    exp = (j.groupby(["d_year", "i_brand_id", "i_brand"])
           .ss_ext_sales_price.sum().reset_index())
    got = sess.sql(tpcds.Q3).rows()
    assert len(got) == min(100, len(exp))
    by_key = {(r.d_year, r.i_brand_id): r.ss_ext_sales_price
              for r in exp.itertuples()}
    for year, brand_id, brand, total in got:
        assert total == pytest.approx(by_key[(year, brand_id)])
    # ordering: per year, totals descend
    for a, b in zip(got, got[1:]):
        if a[0] == b[0]:
            assert a[3] >= b[3] - 1e-9


@pytest.mark.parametrize("qname", ["q42", "q52", "q55", "q19"])
def test_queries_run_and_are_consistent(sess, qname):
    r = sess.sql(tpcds.QUERIES[qname])
    rows = r.rows()
    # every query aggregates a positive price column over a non-empty
    # join at this scale
    assert rows, qname
    totals = [row[-1] for row in rows]
    assert all(t is None or t > 0 for t in totals)
    assert totals == sorted([t for t in totals], reverse=True)


@pytest.mark.slow
def test_tpcds_distributed_equals_single_node():
    from snappydata_tpu.cluster import LocatorNode, ServerNode
    from snappydata_tpu.cluster.distributed import DistributedSession

    locator = LocatorNode().start()
    servers = [ServerNode(locator.address, SnappySession(catalog=Catalog()))
               .start() for _ in range(3)]
    ds = DistributedSession(
        server_addresses=[s.flight_address for s in servers])
    single = SnappySession(catalog=Catalog())
    try:
        tpcds.load_tpcds(ds, sf=0.002, seed=7, partition_sales=True)
        tpcds.load_tpcds(single, sf=0.002, seed=7)
        for qname, q in tpcds.QUERIES.items():
            got = ds.sql(q).rows()
            exp = single.sql(q).rows()
            assert len(got) == len(exp), qname
            for a, b in zip(got, exp):
                assert a[:-1] == b[:-1], qname
                assert a[-1] == pytest.approx(b[-1]), qname
    finally:
        ds.close()
        single.stop()
        for s in servers:
            s.stop()
        locator.stop()
