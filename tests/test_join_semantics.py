"""Join semantics at the edges the device path hands to the host
evaluator: non-equi conditions, ON-clause residuals on outer joins
(NULL-extension, not filtering), and NULL join keys. Ref: Spark/Catalyst
join semantics the reference inherits (SnappyStrategies join selection
falls back the same way)."""

import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.catalog import Catalog


@pytest.fixture()
def s():
    sess = SnappySession(catalog=Catalog())
    sess.sql("CREATE TABLE jl (id INT, v INT) USING column")
    sess.sql("INSERT INTO jl VALUES (1, 10), (2, 20), (3, NULL)")
    sess.sql("CREATE TABLE jr (id INT, w INT) USING column")
    sess.sql("INSERT INTO jr VALUES (2, 200), (3, 300), (4, NULL)")
    yield sess
    sess.stop()


def test_non_equi_inner_join(s):
    got = s.sql("SELECT a.id, b.id FROM jl a JOIN jr b ON a.id < b.id "
                "ORDER BY a.id, b.id").rows()
    assert got == [(1, 2), (1, 3), (1, 4), (2, 3), (2, 4), (3, 4)]


def test_non_equi_join_on_values(s):
    got = s.sql("SELECT a.id, b.id FROM jl a JOIN jr b ON a.v > b.w "
                "ORDER BY a.id, b.id").rows()
    assert got == []   # NULL comparisons never match
    got = s.sql("SELECT a.id, b.id FROM jl a JOIN jr b ON a.v < b.w "
                "ORDER BY a.id, b.id").rows()
    assert got == [(1, 2), (1, 3), (2, 2), (2, 3)]


def test_left_join_residual_null_extends_not_drops(s):
    """ON-clause residuals on an OUTER join NULL-extend failing rows —
    filtering them out (the old behavior) loses left rows entirely."""
    got = s.sql(
        "SELECT a.id, b.id FROM jl a LEFT JOIN jr b "
        "ON a.id = b.id AND b.w > 250 ORDER BY a.id").rows()
    # id=2 matches id 2 but w=200 fails the residual -> NULL-extended
    assert got == [(1, None), (2, None), (3, 3)]


def test_right_and_full_outer_with_residual(s):
    got = s.sql(
        "SELECT a.id, b.id FROM jl a RIGHT JOIN jr b "
        "ON a.id = b.id AND a.v >= 20 ORDER BY b.id").rows()
    assert got == [(2, 2), (None, 3), (None, 4)]
    got = s.sql(
        "SELECT a.id, b.id FROM jl a FULL JOIN jr b "
        "ON a.id = b.id AND a.v >= 20 "
        "ORDER BY a.id NULLS LAST, b.id NULLS LAST").rows()
    assert got == [(1, None), (2, 2), (3, None), (None, 3), (None, 4)]


def test_left_join_pure_non_equi(s):
    got = s.sql("SELECT a.id, b.id FROM jl a LEFT JOIN jr b "
                "ON a.v < b.w ORDER BY a.id, b.id NULLS LAST").rows()
    assert got == [(1, 2), (1, 3), (2, 2), (2, 3), (3, None)]


def test_exists_with_pure_non_equi_correlation(s):
    got = s.sql("SELECT id FROM jl a WHERE EXISTS "
                "(SELECT 1 FROM jr b WHERE b.w > a.v) "
                "ORDER BY id").rows()
    assert got == [(1,), (2,)]
    got = s.sql("SELECT id FROM jl a WHERE NOT EXISTS "
                "(SELECT 1 FROM jr b WHERE b.w > a.v) "
                "ORDER BY id").rows()
    assert got == [(3,)]


def test_cross_join(s):
    got = s.sql("SELECT count(*) FROM jl CROSS JOIN jr").rows()
    assert got == [(9,)]


def test_null_keys_never_match_in_outer_join(s):
    s.sql("CREATE TABLE nk1 (k VARCHAR, x INT) USING column")
    s.sql("INSERT INTO nk1 VALUES ('a', 1), (NULL, 2)")
    s.sql("CREATE TABLE nk2 (k VARCHAR, y INT) USING column")
    s.sql("INSERT INTO nk2 VALUES ('a', 10), (NULL, 20)")
    got = s.sql("SELECT n1.x, n2.y FROM nk1 n1 FULL JOIN nk2 n2 "
                "ON n1.k = n2.k ORDER BY n1.x NULLS LAST, "
                "n2.y NULLS LAST").rows()
    assert got == [(1, 10), (2, None), (None, 20)]
