"""SQL front end: lexer, recursive-descent parser, logical plans, analyzer.

Covers the dialect surface the reference defines with its parboiled2 PEG
grammar (core/.../SnappyParser.scala:73, SnappyDDLParser.scala:301-1056):
full SELECT (joins, group-by, having, order, limit, case, in/between/like),
DDL (CREATE TABLE ... USING COLUMN|ROW OPTIONS (...), DROP, TRUNCATE),
DML (INSERT INTO ... VALUES/SELECT, PUT INTO, UPDATE, DELETE), and literal
tokenization into ParamLiteral for plan-cache reuse (ref: ParamLiteral.scala,
SnappySession.sqlPlan:2571).
"""

from snappydata_tpu.sql.parser import parse  # noqa: F401
from snappydata_tpu.sql import ast  # noqa: F401
