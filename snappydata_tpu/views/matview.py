"""Materialized aggregate views maintained by delta-folding partial programs.

CREATE MATERIALIZED VIEW <name> AS <single-relation group-by aggregate>
stores the aggregate in the PR 4 partial representation: a group-key
dictionary (host) plus per-slot accumulator arrays (device, one aligned
[G] space) sized on the {2^k, 1.5*2^k} bucket ladder so growth implies
only logarithmically many reallocations.  Every ingest delta runs through
the view's compiled partial program over a scratch delta table — the same
decomposition (`engine/partial_agg.decompose_aggregate`) the tiled scan
and the distributed scatter path use — and the resulting per-group slots
scatter-merge into the stored state on device (`.at[idx].add/min/max`,
the elementwise form of `executor.merge_tile_outs`).  Dashboards that
re-read the view pay O(G), not O(N).

Maintenance semantics:
- inserts (session insert/insert_arrays, SQL INSERT, bulk lanes, the
  streaming sink's keyless lane) fold the delta batch: O(delta);
- deletes SUBTRACT exactly when every slot is invertible (sum / count /
  sumsq families over int64 or f64); a min/max slot cannot un-see a
  value, so deletes mark the view STALE instead;
- updates and keyed upserts (PUT on key'd tables) mark STALE — the old
  image is not cheaply available on those paths;
- STALE views re-aggregate the base table in full on the next read (or
  explicit REFRESH MATERIALIZED VIEW) and resume delta folding.

NULL bookkeeping: each non-count slot carries a "seen" count (non-null
contributions per group, an extra count(arg) item in the partial
program) — exact under subtraction; the read path emits SQL NULL for
groups whose seen count is zero.  A hidden count(*) slot (`__rc`) tracks
live rows per group so a fully-deleted group drops out of the view
exactly as a re-aggregation would drop it.

Durability: view state checkpoints through the DiskStore with a recorded
WAL high-watermark seq (the checkpoint fence); crash recovery reloads the
state and folds ONLY the WAL tail past the watermark — the PR 2 chaos
invariant (no acked row lost, no double-fold) extends to view state.
"""

from __future__ import annotations

import threading
from snappydata_tpu.utils import locks
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.sql import ast


class MatViewError(ValueError):
    """Definition not maintainable as a materialized aggregate."""


def _norm(name: str) -> str:
    return name.lower().removeprefix("app.")


def matviews(catalog) -> Dict[str, "MaterializedView"]:
    return getattr(catalog, "_matviews", {})


def matviews_on(catalog, table: str) -> List["MaterializedView"]:
    t = _norm(table)
    return [mv for mv in matviews(catalog).values() if mv.base_table == t]


def _rewrite_relation(plan: ast.Plan, new_name: str) -> ast.Plan:
    """Replace the single UnresolvedRelation leaf with `new_name`."""
    if isinstance(plan, ast.UnresolvedRelation):
        return ast.UnresolvedRelation(new_name)
    if isinstance(plan, ast.Filter):
        return ast.Filter(_rewrite_relation(plan.child, new_name),
                          plan.condition)
    if isinstance(plan, ast.SubqueryAlias):
        return _rewrite_relation(plan.child, new_name)
    raise MatViewError(
        f"materialized views support a single base relation "
        f"(got {type(plan).__name__})")


def _acc_np_dtype(dt: Optional[T.DataType]) -> np.dtype:
    """Accumulator dtype for one slot: float domains widen to f64 (the
    same policy as the executor's [G] partials), everything integral
    accumulates exactly in int64."""
    if dt is not None and dt.name in ("float", "double", "decimal"):
        return np.dtype(np.float64)
    return np.dtype(np.int64)


def _extreme_fill(np_dtype: np.dtype, positive: bool):
    from snappydata_tpu.ops.reduction import _extreme_of

    return _extreme_of(np_dtype, positive)


class _null_cm:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _data_version(data) -> int:
    if hasattr(data, "snapshot"):
        return int(data.snapshot().version)
    return int(getattr(data, "version", 0))


def _concat_pending(entries):
    """Coalesce a pending-fold journal into maximal same-sign runs:
    [(arrays, nulls, sign)].  Sum/count slots commute within a sign, so
    concatenating preserves the fold result exactly while bounding the
    replay at O(sign flips) partial-program runs instead of O(commits)."""
    out = []
    run: List[tuple] = []
    run_sign = 0

    def flush():
        if not run:
            return
        if len(run) == 1:
            out.append((run[0][1], run[0][2], run_sign))
            return
        ncols = len(run[0][1])
        arrays, nulls = [], []
        for ci in range(ncols):
            parts = [np.asarray(e[1][ci]) for e in run]
            if any(p.dtype == object for p in parts):
                parts = [np.asarray(p, dtype=object) for p in parts]
            arrays.append(np.concatenate(parts))
            mparts, any_mask = [], False
            for e in run:
                m = e[2][ci] if e[2] is not None else None
                if m is not None:
                    any_mask = True
                    mparts.append(np.asarray(m, dtype=bool))
                else:
                    mparts.append(np.zeros(len(np.asarray(e[1][ci])),
                                           dtype=bool))
            nulls.append(np.concatenate(mparts) if any_mask else None)
        out.append((arrays, nulls, run_sign))

    for _ver, arrays, nulls, sign in entries:
        if run and sign != run_sign:
            flush()
            run = []
        run_sign = sign
        run.append((None, arrays, nulls))
    flush()
    return out


class MaterializedView:
    """One maintained view: definition + partial programs + [G] state."""

    def __init__(self, name: str, base_table: str, sql_text: str):
        self.name = _norm(name)
        self.base_table = _norm(base_table)
        self.sql_text = sql_text          # full CREATE DDL (persisted)
        self.select_sql = ""              # the AS <select> body
        self._lock = locks.named_rlock("views.matview")
        # definition (filled by define())
        self.group_exprs: Tuple[ast.Expr, ...] = ()
        self.slot_kinds: List[str] = []   # decomposed slot kind per __p
        self.seen_slots: List[Optional[int]] = []  # __n output ordinal
        self.rc_slot = -1                 # hidden count(*) output ordinal
        self.delta_partial_sql = ""       # partial program over __mv_delta
        self.base_partial_sql = ""        # partial program over the base
        self.merge_sql = ""               # re-aggregation over __mv_partials
        self.partial_schema: T.Schema = None   # __g*/__p*/__n*/__rc fields
        self.output_schema: T.Schema = None    # the view's visible schema
        self.subtractable = True          # no min/max slot
        # state ----------------------------------------------------------
        self._keys: List[np.ndarray] = []          # host, [cap] each
        self._key_nulls: List[np.ndarray] = []     # host bool [cap]
        self._vals: List = []                      # device jnp [cap]
        self._seen: List = []                      # device jnp int64 [cap]
        self._rowcount = None                      # device jnp int64 [cap]
        self._index: Dict[tuple, int] = {}
        self._g = 0
        self._cap = 0
        self.stale = True                 # until the first refresh
        self._dirty = True                # backing table out of date
        self.wal_seq = 0                  # checkpoint fence (high watermark)
        self._refresh_version = -1        # base data version at refresh
        # refresh-without-mutation_lock machinery (storage/mvcc): while a
        # full refresh rescans the base OUTSIDE any lock, concurrent
        # commits keep flowing — their deltas land in the pending-fold
        # journal (with the base version they committed at) and replay
        # on top of the rebuilt state for versions past the rescan's
        # pinned epoch.  _refresh_lock serializes whole refreshes.
        self._refresh_lock = locks.named_lock("views.matview_refresh")
        self._refreshing = False
        self._pending: List[tuple] = []   # (base_version, arrays, nulls, sign)
        self._pending_dirtied = False     # raced mark_stale/minmax delete
        self._PENDING_CAP = 256           # journal bound: beyond it, stay stale
        # evidence counters (also bumped in the global registry)
        self.folds = 0
        self.rows_folded = 0
        self.full_refreshes = 0
        self.stale_marks = 0
        self._scratch = None              # lazy scratch session
        self._base_fields_cache = None
        self._delta_tok = None

    # -- definition --------------------------------------------------------

    @classmethod
    def define(cls, session, name: str, plan: ast.Plan,
               sql_text: str) -> "MaterializedView":
        """Validate + compile the maintenance programs for `plan` (the
        parsed AS-select).  Raises MatViewError on shapes that cannot be
        maintained incrementally."""
        from snappydata_tpu.engine.partial_agg import (NotDecomposableError,
                                                       decompose_aggregate)
        from snappydata_tpu.sql.optimizer import optimize
        from snappydata_tpu.sql.render import RenderError, render_expr, \
            render_plan

        node = plan
        having = None
        if isinstance(node, (ast.Sort, ast.Limit, ast.Distinct)):
            raise MatViewError(
                "ORDER BY / LIMIT / DISTINCT are not allowed in a "
                "materialized view definition — apply them when querying "
                "the view")
        if isinstance(node, ast.Filter) and isinstance(node.child,
                                                       ast.Aggregate):
            having = node.condition
            node = node.child
        if not isinstance(node, ast.Aggregate):
            raise MatViewError(
                "a materialized view must be a GROUP BY aggregate "
                "(SELECT <keys/aggregates> FROM t [WHERE ...] "
                "GROUP BY ...)")
        if node.grouping_sets:
            raise MatViewError(
                "ROLLUP/CUBE/GROUPING SETS views are not supported")
        for e in list(node.group_exprs) + list(node.agg_exprs) + \
                ([having] if having is not None else []):
            for sub in ast.walk(e):
                if isinstance(sub, (ast.ScalarSubquery, ast.InSubquery,
                                    ast.ExistsSubquery, ast.WindowFunc)):
                    raise MatViewError(
                        "subqueries/window functions are not supported "
                        "in materialized view definitions")

        # single-relation child ([Filter] over the base table)
        probe = node.child
        while isinstance(probe, (ast.Filter, ast.SubqueryAlias)):
            probe = probe.children()[0]
        if not isinstance(probe, ast.UnresolvedRelation):
            raise MatViewError(
                "materialized views support a single-relation aggregate "
                "(no joins/unions yet)")
        base = _norm(probe.name)
        base_info = session.catalog.lookup_table(base)
        if base_info is None:
            raise MatViewError(f"base table not found: {probe.name}")
        if base_info.provider == "sample":
            raise MatViewError(
                "materialized views over sample tables are not supported")
        if base_info.options.get("materialized_view"):
            raise MatViewError(
                "materialized views over materialized views are not "
                "supported")

        mv = cls(name, base, sql_text)
        try:
            from snappydata_tpu.sql.render import render_plan as _rp

            mv.select_sql = _rp(plan if having is None
                                else ast.Filter(node, having))
        except Exception:
            mv.select_sql = sql_text
        try:
            partial_plan, merged_select, _n_slots, merged_having = \
                decompose_aggregate(node, having)
        except NotDecomposableError as e:
            raise MatViewError(f"not incrementally maintainable: {e}")
        groups = list(node.group_exprs)
        # recover the slot table decompose built (kind per __p ordinal)
        slot_items = list(partial_plan.agg_exprs)[len(groups):]
        kinds: List[str] = []
        for it in slot_items:
            fn = it.child
            if isinstance(fn, ast.Func) and fn.name == "count" \
                    and not fn.args:
                kinds.append("count_star")
            elif isinstance(fn, ast.Func) and fn.name == "count_distinct":
                raise MatViewError(
                    "count(DISTINCT ...) cannot be folded incrementally")
            elif isinstance(fn, ast.Func):
                # sum/min/max/count — sumsq arrives as sum(arg*arg)
                kinds.append(fn.name)
            else:  # pragma: no cover - decompose only emits Funcs
                raise MatViewError(f"unexpected partial item {it!r}")
        mv.slot_kinds = kinds
        mv.subtractable = not any(k in ("min", "max") for k in kinds)
        # null bookkeeping: one count(arg) per non-count slot, plus the
        # hidden live-rows count(*) every view carries
        aug_items = list(partial_plan.agg_exprs)
        seen_slots: List[Optional[int]] = []
        for i, (it, kind) in enumerate(zip(slot_items, kinds)):
            if kind in ("count", "count_star"):
                seen_slots.append(None)
                continue
            arg = it.child.args[0]
            seen_slots.append(len(aug_items))
            aug_items.append(ast.Alias(ast.Func("count", (arg,)),
                                       f"__n{i}"))
        mv.seen_slots = seen_slots
        mv.rc_slot = len(aug_items)
        aug_items.append(ast.Alias(ast.Func("count", ()), "__rc"))
        mv.group_exprs = tuple(groups)

        aug_partial = ast.Aggregate(partial_plan.child, tuple(groups),
                                    tuple(aug_items))
        try:
            mv.base_partial_sql = render_plan(aug_partial)
            delta_plan = ast.Aggregate(
                _rewrite_relation(partial_plan.child, "__mv_delta"),
                tuple(groups), tuple(aug_items))
            mv.delta_partial_sql = render_plan(delta_plan)
            merge_items = ", ".join(render_expr(e) for e in merged_select)
            msql = f"SELECT {merge_items} FROM __mv_partials"
            if groups:
                msql += " GROUP BY " + ", ".join(
                    f"__g{i}" for i in range(len(groups)))
            if merged_having is not None:
                msql += f" HAVING {render_expr(merged_having)}"
            mv.merge_sql = msql
        except RenderError as e:
            raise MatViewError(f"definition is not renderable: {e}")

        # validate + capture schemas by analyzing against the live catalog
        from snappydata_tpu.session import _output_schema

        resolved_p, _ = session.analyzer.analyze_plan(
            optimize(aug_partial, session.catalog))
        mv.partial_schema = _output_schema(resolved_p)
        resolved_v, _ = session.analyzer.analyze_plan(
            optimize(plan, session.catalog))
        out = _output_schema(resolved_v)
        # backing storage lives in the HOST value domain: decimals ride f64
        mv.output_schema = T.Schema([
            T.Field(f.name, T.DOUBLE if f.dtype.name == "decimal"
                    else f.dtype, True) for f in out.fields])
        for i, k in enumerate(kinds):
            f = mv.partial_schema.fields[len(groups) + i]
            if k in ("min", "max") and f.dtype.name == "string":
                raise MatViewError(
                    "min/max over string columns is not supported in "
                    "materialized views")
        for f in mv.partial_schema.fields[:len(groups)]:
            if f.dtype.name in ("array", "map", "struct"):
                raise MatViewError(
                    "complex-typed group keys are not supported")
        mv.bind_base(base_info)
        return mv

    # -- state plumbing ----------------------------------------------------

    def _n_groups_cols(self) -> int:
        return len(self.group_exprs)

    def _slot_field(self, i: int) -> T.Field:
        return self.partial_schema.fields[self._n_groups_cols() + i]

    def _reset_state(self) -> None:
        import jax.numpy as jnp

        ng, ns = self._n_groups_cols(), len(self.slot_kinds)
        self._cap = 0
        self._g = 0
        self._index = {}
        self._keys = [np.empty(0, dtype=self._key_np_dtype(i))
                      for i in range(ng)]
        self._key_nulls = [np.empty(0, dtype=np.bool_) for _ in range(ng)]
        self._vals = [jnp.empty(0, dtype=self._acc_dtype(i))
                      for i in range(ns)]
        self._seen = [jnp.empty(0, dtype=jnp.int64)
                      if self.seen_slots[i] is not None else None
                      for i in range(ns)]
        self._rowcount = jnp.empty(0, dtype=jnp.int64)

    def _key_np_dtype(self, i: int):
        dt = self.partial_schema.fields[i].dtype
        return object if dt.name == "string" else dt.np_dtype

    def _acc_dtype(self, i: int) -> np.dtype:
        return _acc_np_dtype(self._slot_field(i).dtype)

    def _fill_value(self, i: int):
        kind = self.slot_kinds[i]
        dt = self._acc_dtype(i)
        if kind == "min":
            return _extreme_fill(dt, True)
        if kind == "max":
            return _extreme_fill(dt, False)
        return dt.type(0)

    def _grow(self, need: int) -> None:
        """Bucket-ladder reallocation: capacity only ever takes values in
        {2^k, 1.5*2^k}, so a growing view reallocates O(log G) times."""
        import jax.numpy as jnp

        from snappydata_tpu.storage.device import batch_bucket

        new_cap = batch_bucket(max(1, need))
        if new_cap <= self._cap:
            return
        pad = new_cap - self._cap
        for i in range(len(self._keys)):
            filler = np.zeros(pad, dtype=object) \
                if self._keys[i].dtype == object \
                else np.zeros(pad, dtype=self._keys[i].dtype)
            self._keys[i] = np.concatenate([self._keys[i], filler])
            self._key_nulls[i] = np.concatenate(
                [self._key_nulls[i], np.zeros(pad, dtype=np.bool_)])
        for i in range(len(self._vals)):
            fill = jnp.full(pad, self._fill_value(i),
                            dtype=self._acc_dtype(i))
            self._vals[i] = jnp.concatenate([self._vals[i], fill])
            if self._seen[i] is not None:
                self._seen[i] = jnp.concatenate(
                    [self._seen[i], jnp.zeros(pad, dtype=jnp.int64)])
        self._rowcount = jnp.concatenate(
            [self._rowcount, jnp.zeros(pad, dtype=jnp.int64)])
        self._cap = new_cap
        global_registry().inc("view_state_regrows")

    def state_nbytes(self) -> int:
        total = 0
        for a in self._keys:
            total += int(a.nbytes) if a.dtype != object else 8 * a.size
        for a in self._key_nulls:
            total += int(a.nbytes)
        for a in list(self._vals) + list(self._seen) + [self._rowcount]:
            if a is not None:
                # dtype/size are static metadata — never np.asarray a
                # device array here (ledger/metrics scrape this on the
                # admission hot path; a copy would ship the whole state)
                total += int(a.dtype.itemsize) * int(a.size)
        return total

    # -- scratch sessions --------------------------------------------------

    def _scratch_session(self):
        """One throwaway in-memory session per view holding the delta
        table (base schema, decimals as DOUBLE) and the partial-rows
        table the read path re-aggregates — never journaled."""
        if self._scratch is not None:
            return self._scratch
        from snappydata_tpu.catalog import Catalog
        from snappydata_tpu.engine.partial_agg import ddl_type
        from snappydata_tpu.session import SnappySession

        s = SnappySession(catalog=Catalog())
        s._in_tile = True   # partial/merge SQL must never re-tile
        fields_sql = ", ".join(
            f"{f.name} {ddl_type(f.dtype)}" for f in self._base_fields())
        s.sql(f"CREATE TABLE __mv_delta ({fields_sql}) USING column")
        ng = self._n_groups_cols()
        pf = []
        for i, f in enumerate(
                self.partial_schema.fields[:ng + len(self.slot_kinds)]):
            if i < ng:
                pf.append(f"{f.name} {ddl_type(f.dtype)}")
            else:
                acc = self._acc_dtype(i - ng)
                pf.append(f"{f.name} "
                          f"{'DOUBLE' if acc == np.float64 else 'BIGINT'}")
        s.sql(f"CREATE TABLE __mv_partials ({', '.join(pf)}) USING column")
        self._scratch = s
        return s

    def _base_fields(self):
        if self._base_fields_cache is None:
            raise MatViewError(f"view {self.name} not bound to its base")
        return self._base_fields_cache

    def bind_base(self, base_info) -> None:
        """Capture the base schema the maintenance programs run against
        (ALTER TABLE on the base marks the view stale and rebinds)."""
        self._base_fields_cache = [
            T.Field(f.name, T.DOUBLE if f.dtype.name == "decimal"
                    else f.dtype, f.nullable)
            for f in base_info.schema.fields]

    def invalidate_scratch(self) -> None:
        with self._lock:
            if self._scratch is not None:
                try:
                    self._scratch.stop()
                except Exception:
                    pass
                self._scratch = None
            self._delta_tok = None

    # -- folding -----------------------------------------------------------

    def _normalize_delta(self, arrays, nulls):
        """Ingest arrays arrive in several host flavors (typed arrays +
        null masks, or object arrays with embedded None from row-table
        lanes).  Normalize to what the scratch column table ingests."""
        fields = self._base_fields()
        if len(arrays) != len(fields):
            raise MatViewError("delta arity does not match the base table")
        out_arrays, out_nulls = [], []
        nulls = list(nulls) if nulls is not None else [None] * len(arrays)
        for a, m, f in zip(arrays, nulls, fields):
            a = np.asarray(a)
            if f.dtype.name in ("string", "array", "map", "struct"):
                out_arrays.append(np.asarray(a, dtype=object))
                out_nulls.append(np.asarray(m, dtype=bool)
                                 if m is not None else None)
                continue
            if a.dtype == object:
                none_mask = np.fromiter((v is None for v in a),
                                        dtype=np.bool_, count=len(a))
                filled = np.array([0 if v is None else v for v in a],
                                  dtype=f.dtype.np_dtype)
                m = none_mask if m is None \
                    else (np.asarray(m, dtype=bool) | none_mask)
                out_arrays.append(filled)
                out_nulls.append(m if m.any() else None)
                continue
            out_arrays.append(a.astype(f.dtype.np_dtype, copy=False))
            out_nulls.append(np.asarray(m, dtype=bool)
                             if m is not None else None)
        return out_arrays, out_nulls

    def fold_delta(self, arrays, nulls, sign: int = 1,
                   version: Optional[int] = None) -> None:
        """Fold one ingest delta into the stored state: run the compiled
        partial program over the delta rows, then scatter-merge the
        per-group slots into the aligned [G] space on device.  sign=-1
        subtracts (delete path; only valid when `subtractable`)."""
        reg = global_registry()
        with self._lock:
            if self._refreshing:
                # a full refresh is rescanning the base WITHOUT holding
                # mutation_lock (the old design stalled every committer
                # behind the scan): divert this commit's delta to the
                # pending journal — the refresh replays entries past its
                # pinned epoch on top of the rebuilt state
                if sign < 0 and not self.subtractable:
                    self._pending_dirtied = True
                    return
                if len(self._pending) >= self._PENDING_CAP:
                    # journal bound: give up on this refresh converging
                    # (stays stale, next read re-aggregates)
                    self._pending_dirtied = True
                    return
                n = int(np.asarray(arrays[0]).shape[0]) if arrays else 0
                if n:
                    self._pending.append((version, list(arrays),
                                          list(nulls) if nulls is not None
                                          else None, sign))
                    reg.inc("view_pending_folds")
                return
            if self.stale:
                return   # stale views re-aggregate at next read anyway
            if sign < 0 and not self.subtractable:
                self.mark_stale("delete on a min/max view")
                return
            if version is not None and version <= self._refresh_version:
                return   # delta already covered by the refresh scan
            n = int(np.asarray(arrays[0]).shape[0]) if arrays else 0
            if n == 0:
                return
            try:
                # locklint: lock-order-undeclared,blocking-under-lock the
                # fold's scratch session is STORE-LESS (_scratch_session):
                # its statements never take the durable store's
                # mutation_lock or reach wal_sync/fsync — the static
                # chain through SnappySession.sql is unreachable here;
                # device waits are the O(delta) fold itself
                res = self._run_partial_over_delta(arrays, nulls)
                self._merge_partial(res, sign)
            except Exception as e:  # noqa: BLE001 — never break ingest
                reg.inc("view_fold_errors")
                self.mark_stale(f"fold error: {e}")
                return
            self._dirty = True
            self.folds += 1
            self.rows_folded += n
            reg.inc("view_delta_folds")
            reg.inc("view_rows_folded", n)
            if sign < 0:
                reg.inc("view_subtract_folds")

    def _run_partial_over_delta(self, arrays, nulls):
        from snappydata_tpu.storage import mvcc

        # the scratch table is rewritten per fold: an outer statement's
        # pin must NOT capture it (the second fold under one pin would
        # re-read the first fold's manifest) — scratch reads are live
        with mvcc.unpinned_scope():
            return self._run_partial_over_delta_unpinned(arrays, nulls)

    def _run_partial_over_delta_unpinned(self, arrays, nulls):
        s = self._scratch_session()
        info = s.catalog.describe("__mv_delta")
        info.data.truncate()
        na, nn = self._normalize_delta(arrays, nulls)
        info.data.insert_arrays(
            na, nulls=nn if any(m is not None for m in nn) else None)
        # compile-once: the scratch catalog never changes after setup, so
        # the tokenized partial plan stays plan-cache-hot across folds
        if self._delta_tok is None:
            from snappydata_tpu.sql.analyzer import tokenize_plan
            from snappydata_tpu.sql.optimizer import optimize
            from snappydata_tpu.sql.parser import parse

            pplan = optimize(parse(self.delta_partial_sql).plan, s.catalog)
            resolved, _ = s.analyzer.analyze_plan(pplan)
            self._delta_tok = tokenize_plan(resolved)
        tokenized, params = self._delta_tok
        from snappydata_tpu.engine.result import to_host_domain

        return to_host_domain(s.executor.execute(tokenized, tuple(params)))

    def _key_tuple(self, cols, nulls, r: int) -> tuple:
        out = []
        for c, m in zip(cols, nulls):
            if m is not None and m[r]:
                out.append(None)
            else:
                v = c[r]
                out.append(v.item() if hasattr(v, "item") else v)
        return tuple(out)

    def _merge_partial(self, res, sign: int) -> None:
        import jax.numpy as jnp

        n = res.num_rows
        if n == 0:
            return
        ng = self._n_groups_cols()
        gcols = [res.columns[i] for i in range(ng)]
        gnulls = [res.nulls[i] for i in range(ng)]
        idx = np.empty(n, dtype=np.int64)
        fresh: List[int] = []
        for r in range(n):
            key = self._key_tuple(gcols, gnulls, r)
            at = self._index.get(key)
            if at is None:
                if sign < 0:
                    # subtracting a group that never existed: the state
                    # diverged — degrade to a full re-aggregation rather
                    # than go negative
                    raise MatViewError(f"unknown group in subtract: {key}")
                at = self._g + len(fresh)
                self._index[key] = at
                fresh.append(r)
            idx[r] = at
        if fresh:
            need = self._g + len(fresh)
            if need > self._cap:
                self._grow(need)
            for ci in range(ng):
                for r in fresh:
                    at = idx[r]
                    if gnulls[ci] is not None and gnulls[ci][r]:
                        self._key_nulls[ci][at] = True
                    else:
                        self._keys[ci][at] = gcols[ci][r]
            self._g = need
        jidx = jnp.asarray(idx)
        for i, kind in enumerate(self.slot_kinds):
            col = np.asarray(res.columns[ng + i])
            nmask = res.nulls[ng + i]
            acc = self._acc_dtype(i)
            if kind in ("min", "max"):
                fill = self._fill_value(i)
                vals = np.where(nmask, fill, col).astype(acc) \
                    if nmask is not None else col.astype(acc)
                v = jnp.asarray(vals)
                self._vals[i] = self._vals[i].at[jidx].min(v) \
                    if kind == "min" else self._vals[i].at[jidx].max(v)
            else:
                vals = np.where(nmask, 0, col).astype(acc) \
                    if nmask is not None else col.astype(acc)
                self._vals[i] = self._vals[i].at[jidx].add(
                    sign * jnp.asarray(vals))
            so = self.seen_slots[i]
            if so is not None:
                cnt = np.asarray(res.columns[so]).astype(np.int64)
                self._seen[i] = self._seen[i].at[jidx].add(
                    sign * jnp.asarray(cnt))
        rc = np.asarray(res.columns[self.rc_slot]).astype(np.int64)
        self._rowcount = self._rowcount.at[jidx].add(
            sign * jnp.asarray(rc))

    # -- staleness / refresh ----------------------------------------------

    def mark_stale(self, reason: str = "") -> None:
        with self._lock:
            if self._refreshing:
                # raced a lock-free refresh: its rebuilt state must not
                # publish as fresh (the mark arrived mid-rescan)
                self._pending_dirtied = True
            if not self.stale:
                self.stale = True
                self.stale_marks += 1
                self._dirty = True
                global_registry().inc("view_stale_marks")

    def reset_empty(self, wal_seq: int = 0) -> None:
        """TRUNCATE of the base table: the aggregate of nothing."""
        with self._lock:
            if self._refreshing:
                # a TRUNCATE racing a lock-free refresh: the in-flight
                # rescan's result is pre-truncate — poison it
                self._pending_dirtied = True
            self._reset_state()
            self.stale = False
            self._dirty = True
            self.wal_seq = wal_seq

    def refresh_full(self, session) -> None:
        """Re-aggregate the base table through the session's full engine
        (tiled scans and all) and rebuild the state — the stale-exit and
        REFRESH MATERIALIZED VIEW path.

        The rescan runs WITHOUT mutation_lock: it pins one storage epoch
        (the outer statement's, when ambient — the "stale-refresh reads
        under the outer query's epoch" contract) and aggregates that
        immutable manifest while committers keep publishing.  Deltas
        committed during the scan divert to the pending-fold journal
        (see fold_delta) and replay on top of the rebuilt state for
        versions PAST the pinned epoch — versions at or below it are
        already inside the scan.  The old design held mutation_lock
        across the whole rescan, stalling every writer behind one long
        analytic read (the PR 6 ABBA fix was a symptom of that lock
        discipline)."""
        from snappydata_tpu.engine.result import to_host_domain
        from snappydata_tpu.storage import mvcc

        ds = session.disk_store
        with self._refresh_lock:
            base = session.catalog.lookup_table(self.base_table)
            if base is None:
                raise MatViewError(
                    f"base table dropped: {self.base_table}")
            with self._lock:
                self.bind_base(base)
                self.invalidate_scratch()
                # open the journal BEFORE pinning: every commit published
                # after the pin lands in it (never silently lost)
                self._refreshing = True
                self._pending = []
                self._pending_dirtied = False
            try:
                pin = mvcc.current_pin()
                own_scope = _null_cm()
                if pin is None and hasattr(base.data, "_manifest"):
                    # REFRESH statement / recovery path: no ambient pin —
                    # take one so the rescan reads one epoch end to end
                    own_scope = mvcc.pinned_scope(session.catalog,
                                                  [self.base_table])
                with own_scope:
                    pin = mvcc.current_pin()
                    col_pin = pin is not None \
                        and hasattr(base.data, "_manifest")
                    # a column-manifest pin makes the rescan race-free
                    # WITHOUT any lock; otherwise (snapshot_isolation
                    # off, or a row-table base) fall back to the old
                    # discipline — mutation_lock across the rescan — or
                    # a commit racing the scan could be both partially
                    # seen by it AND journal-replayed on top (double
                    # count)
                    lock_cm = _null_cm() if col_pin or ds is None \
                        else ds.mutation_lock
                    with lock_cm:
                        if col_pin:
                            manifest = pin.repin(base.data)
                            v0 = int(manifest.version)
                            fence = int(manifest.wal_seq)
                        else:
                            if pin is not None:
                                # the pin's earlier row capture may
                                # predate the fence: re-capture NOW,
                                # under the lock
                                pin.repin_row(base.data)
                            v0 = _data_version(base.data)
                            fence = ds.current_wal_seq() if ds is not None \
                                else 0
                        res = to_host_domain(
                            session.sql(self.base_partial_sql))
                with self._lock:
                    self._reset_state()
                    self.stale = False
                    self._merge_partial(res, 1)
                    self._refresh_version = v0
                    # replay commits that raced the rescan (version past
                    # the pinned epoch; None = provenance unknown,
                    # replay).  Same-sign runs concatenate into ONE
                    # partial-program pass — a committer hammering
                    # single-row inserts during a long rescan must not
                    # cost one scratch query per diverted commit
                    pend = [p for p in self._pending
                            if p[0] is None or p[0] > v0]
                    self._pending = []
                    if self._pending_dirtied:
                        # a min/max delete (or journal overflow / raced
                        # TRUNCATE / ALTER) hit mid-refresh: the rebuilt
                        # state cannot be trusted — stay stale (next
                        # read re-aggregates) and SKIP the replay: its
                        # entries may not even match the schema any
                        # more, and the result is discarded regardless
                        self.stale = True
                    else:
                        for parrays, pnulls, psign in _concat_pending(pend):
                            # locklint: lock-order-undeclared,blocking-under-lock
                            # same store-less scratch-session invariant as
                            # fold_delta's call
                            pres = self._run_partial_over_delta(
                                parrays, pnulls)
                            self._merge_partial(pres, psign)
                            self.folds += 1
                            global_registry().inc("view_pending_replays")
                    self._dirty = True
                    self.full_refreshes += 1
                    self.wal_seq = fence
                    # close the journal INSIDE the same lock hold as the
                    # replay: a fold landing between replay and a later
                    # flag flip would be appended and then discarded
                    self._refreshing = False
                    global_registry().inc("view_full_refreshes")
            finally:
                with self._lock:
                    if self._refreshing:
                        # error path (scan raised / admission rejected):
                        # diverted folds are lost with the journal — the
                        # state must not pass for fresh
                        self._refreshing = False
                        self._pending = []
                        self.stale = True

    # -- read path ---------------------------------------------------------

    def _live_rows(self) -> np.ndarray:
        """Indices of groups with live rows (a fully-deleted group drops
        out of the view exactly as a re-aggregation would drop it)."""
        rc = np.asarray(self._rowcount)[:self._g]
        return np.flatnonzero(rc > 0)

    def partial_rows(self):
        """(names, arrays, nulls) of the stored [G] partial state — the
        host image the merge re-aggregates and the checkpoint writes."""
        ng, ns = self._n_groups_cols(), len(self.slot_kinds)
        names = [f"__g{i}" for i in range(ng)] + \
                [f"__p{i}" for i in range(ns)]
        live = self._live_rows()
        arrays, nulls = [], []
        for i in range(ng):
            kvals = self._keys[i][:self._g][live].copy()
            kn = self._key_nulls[i][:self._g][live]
            if kn.any() and kvals.dtype == object:
                kvals[kn] = None   # placeholder 0s are not strings
            arrays.append(kvals)
            nulls.append(kn.copy() if kn.any() else None)
        for i in range(ns):
            vals = np.asarray(self._vals[i])[:self._g][live].copy()
            so = self.seen_slots[i]
            if so is not None:
                seen = np.asarray(self._seen[i])[:self._g][live]
                mask = seen <= 0
                nulls.append(mask if mask.any() else None)
            else:
                nulls.append(None)
            arrays.append(vals)
        return names, arrays, nulls

    def finalize(self):
        """Merged (final) Result of the maintained state: O(G) work."""
        from snappydata_tpu.storage import mvcc

        # __mv_partials is truncated + re-filled per merge: like the
        # delta scratch, it must never be captured into an outer pin
        with self._lock, mvcc.unpinned_scope():
            # locklint: blocking-under-lock store-less scratch session —
            # truncate/re-fill never journals or fsyncs
            s = self._scratch_session()
            info = s.catalog.describe("__mv_partials")
            info.data.truncate()
            names, arrays, nulls = self.partial_rows()
            n_live = int(arrays[0].shape[0]) if arrays else 0
            if n_live:
                info.data.insert_arrays(
                    arrays,
                    nulls=nulls if any(m is not None for m in nulls)
                    else None)
            elif not self.group_exprs:
                # global aggregate over an empty table: one identity
                # partial row (counts 0, value slots NULL) so the merge
                # emits count(*) = 0 / sum = NULL, matching SQL
                idr, idn = [], []
                for i, kind in enumerate(self.slot_kinds):
                    idr.append(np.zeros(1, dtype=self._acc_dtype(i)))
                    idn.append(None if kind in ("count", "count_star")
                               else np.ones(1, dtype=np.bool_))
                info.data.insert_arrays(idr, nulls=idn)
            res = s.sql(self.merge_sql)
            res.names = [f.name for f in self.output_schema.fields]
            return res

    def sync(self, session) -> None:
        """Bring the queryable backing table up to date: full refresh if
        stale, then re-merge into the backing rows only when folds
        dirtied the state since the last sync.

        Under an ambient snapshot pin (the outer query's), the base
        table is RE-pinned right before the merge, briefly under
        mutation_lock so no committer can sit between journal-apply and
        fold: at that instant state == aggregate(base@pin), and the
        query then reads base rows and view rows that agree exactly —
        the base-vs-view skew window PR 6 left open is closed.  Lock
        order stays mutation_lock → view lock, the same order every
        ingest fold uses (_journal_then holds mutation_lock when
        fold_delta takes the view lock)."""
        from snappydata_tpu.storage import mvcc

        for _attempt in range(2):
            if not self.stale:
                break
            self.refresh_full(session)
        pin = mvcc.current_pin()
        base = session.catalog.lookup_table(self.base_table) \
            if pin is not None else None
        ds = session.disk_store
        lock_cm = ds.mutation_lock \
            if (pin is not None and ds is not None) else _null_cm()
        with lock_cm:
            # locklint: blocking-under-lock the O(G) device merge runs
            # under mutation_lock BY DESIGN (base and view must agree to
            # the row within one statement — PR 11); scratch reads are
            # store-less, so no fsync hides in here
            self._sync_merge(session, pin, base)

    def _sync_merge(self, session, pin, base) -> None:
        # locklint: blocking-under-lock the O(G) device merge runs under
        # mutation_lock BY DESIGN (base rows and view rows must agree to
        # the row within one statement — PR 11); scratch reads are
        # store-less, so no fsync hides in here
        with self._lock:
            if self.stale:
                return   # a racing dirtier won: next read re-aggregates
            if pin is not None and base is not None \
                    and hasattr(base.data, "_manifest"):
                # caller holds mutation_lock (durable sessions): no
                # commit is mid journal→apply→fold, so the re-pinned
                # epoch is exactly what the folded state aggregates
                pin.repin(base.data)
            if not self._dirty:
                return
            # locklint: blocking-under-lock finalize reads the partial
            # state through the STORE-LESS scratch session (no journal,
            # no fsync); the device wait is the merge itself
            merged = self.finalize()
            backing = session.catalog.lookup_table(self.name)
            if backing is None:
                return
            cols, masks = [], []
            for c, m, f in zip(merged.columns, merged.nulls,
                               self.output_schema.fields):
                arr = np.asarray(c)
                if f.dtype.name == "string":
                    cols.append(np.asarray(arr, dtype=object))
                elif arr.dtype == object:
                    nm = np.fromiter((v is None for v in arr),
                                     dtype=np.bool_, count=len(arr))
                    cols.append(np.array(
                        [0 if v is None else v for v in arr],
                        dtype=f.dtype.np_dtype))
                    m = nm if m is None else (np.asarray(m) | nm)
                else:
                    cols.append(arr.astype(f.dtype.np_dtype, copy=False))
                masks.append(np.asarray(m, dtype=bool)
                             if m is not None else None)
            backing.data.truncate()
            if merged.num_rows:
                backing.data.insert_arrays(
                    cols, nulls=masks if any(m is not None for m in masks)
                    else None)
            if pin is not None and hasattr(backing.data, "_manifest"):
                # the base was repinned forward above — move the backing
                # with it, or a pin that already read the view (fold →
                # re-read inside one pinned scope) would keep the
                # pre-merge manifest and skew base-vs-view WITHIN the pin
                pin.repin(backing.data)
            self._dirty = False
            global_registry().inc("view_syncs")

    def evict_state(self) -> None:
        """Resource-broker degradation: drop the device/host state and
        fall back to stale (one full re-aggregation at next read)."""
        with self._lock:
            self._reset_state()
            self.stale = True
            self._dirty = True
            self.invalidate_scratch()
            global_registry().inc("view_state_evictions")

    def dispose(self) -> None:
        """DROP MATERIALIZED VIEW: release state + scratch sessions so
        the broker ledger line goes to zero immediately."""
        with self._lock:
            self._reset_state()
            self.stale = True
            self.invalidate_scratch()

    # -- checkpoint / recovery --------------------------------------------

    def state_record(self, base_rows: Optional[int] = None
                     ) -> Tuple[dict, List[Optional[np.ndarray]]]:
        """(header, arrays) for the CRC-framed state checkpoint.  The
        record is written compacted (live groups only).  `base_rows`
        (the base table's live row count at checkpoint time) lets
        recovery detect a base that lost unjournaled rows — the state
        would claim rows the WAL can never replay, so a mismatch
        degrades to STALE instead of wrong answers."""
        with self._lock:
            names, arrays, nulls = self.partial_rows()
            live = self._live_rows()
            seen = [np.asarray(s)[:self._g][live].copy()
                    if s is not None else None for s in self._seen]
            rc = np.asarray(self._rowcount)[:self._g][live].copy()
            header = {
                "kind": "matview_state",
                "name": self.name,
                "base_table": self.base_table,
                "wal_seq": int(self.wal_seq),
                "groups": int(live.size),
                # a checkpoint racing a lock-free refresh persists STALE:
                # folds are diverted to the pending journal right now, so
                # this state image misses them — recovery re-aggregates
                "stale": bool(self.stale or self._refreshing),
                "n_arrays": len(arrays),
            }
            if base_rows is not None:
                header["base_rows"] = int(base_rows)
            # layout: partial arrays, their null masks, seen counts, rc
            return header, list(arrays) + list(nulls) + seen + [rc]

    def load_state(self, header: dict, parts: List[Optional[np.ndarray]]
                   ) -> None:
        """Rebuild the [G] state from a checkpoint record."""
        import jax.numpy as jnp

        with self._lock:
            ng, ns = self._n_groups_cols(), len(self.slot_kinds)
            n_arr = int(header["n_arrays"])
            arrays = parts[:n_arr]
            nulls = parts[n_arr:2 * n_arr]
            seen = parts[2 * n_arr:2 * n_arr + ns]
            rc = parts[2 * n_arr + ns]
            g = int(header["groups"])
            self._reset_state()
            if g:
                self._grow(g)
            self._g = g
            for i in range(ng):
                a = np.asarray(arrays[i])
                if self._keys[i].dtype == object:
                    a = np.asarray(a, dtype=object)
                self._keys[i][:g] = a
                if nulls[i] is not None:
                    self._key_nulls[i][:g] = np.asarray(nulls[i],
                                                        dtype=bool)
            for i in range(ns):
                vals = np.asarray(arrays[ng + i]).astype(self._acc_dtype(i))
                if g:
                    self._vals[i] = self._vals[i].at[:g].set(
                        jnp.asarray(vals))
                if self._seen[i] is not None and seen[i] is not None and g:
                    self._seen[i] = self._seen[i].at[:g].set(
                        jnp.asarray(np.asarray(seen[i], dtype=np.int64)))
            if g and rc is not None:
                self._rowcount = self._rowcount.at[:g].set(
                    jnp.asarray(np.asarray(rc, dtype=np.int64)))
            gcols = [self._keys[i][:g] for i in range(ng)]
            gnulls = [self._key_nulls[i][:g]
                      if self._key_nulls[i][:g].any() else None
                      for i in range(ng)]
            self._index = {self._key_tuple(gcols, gnulls, r): r
                           for r in range(g)}
            self.wal_seq = int(header.get("wal_seq", 0))
            self.stale = bool(header.get("stale", False))
            self._dirty = True

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "base_table": self.base_table,
                "sql": self.select_sql,
                "groups": int(self._g),
                "capacity": int(self._cap),
                "slots": list(self.slot_kinds),
                "subtractable": self.subtractable,
                "stale": bool(self.stale),
                "dirty": bool(self._dirty),
                "state_bytes": self.state_nbytes(),
                "wal_seq": int(self.wal_seq),
                "delta_folds": self.folds,
                "rows_folded": self.rows_folded,
                "full_refreshes": self.full_refreshes,
                "stale_marks": self.stale_marks,
            }


# -- session-facing maintenance hooks ------------------------------------


_MANAGED = threading.local()


class managed_base_write:
    """Scope marking a base-table mutation as session-managed (journaled
    + folded by the session / WAL replay).  Data-layer writes OUTSIDE
    this scope bypass both the WAL and the fold hook, so the unmanaged-
    write guard marks dependent views stale instead of letting them
    silently diverge."""

    def __enter__(self):
        _MANAGED.depth = getattr(_MANAGED, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _MANAGED.depth = getattr(_MANAGED, "depth", 1) - 1
        return False


def in_managed_write() -> bool:
    return getattr(_MANAGED, "depth", 0) > 0


def register_unmanaged_write_guard(catalog, info) -> None:
    """Hook the base table's data-layer insert callback so a raw
    `ColumnTableData.insert_arrays` (bench loaders, tests, embedders
    poking the storage layer directly) marks dependent views STALE —
    one re-aggregation at next read — rather than serving rows the
    view never folded.  One guard per data object; it looks views up
    dynamically so DROP needs no deregistration."""
    data = info.data
    if any(getattr(cb, "_mv_guard", False) for cb in data.on_insert):
        return
    ref = weakref.ref(catalog)

    def guard(arrays, nulls=None, _table=info.name):
        if in_managed_write():
            return
        cat = ref()
        if cat is None:
            return
        mvs = matviews_on(cat, _table)
        if mvs:
            global_registry().inc("view_unmanaged_writes")
            for mv in mvs:
                mv.mark_stale("unmanaged direct write to base")

    guard._mv_guard = True
    data.on_insert.append(guard)


def fold_ingest(catalog, table: str, arrays, nulls, sign: int = 1) -> None:
    """Fold one applied ingest delta into every view over `table`."""
    mvs = matviews_on(catalog, table)
    if not mvs:
        return
    info = catalog.lookup_table(_norm(table))
    version = _data_version(info.data) if info is not None else None
    for mv in mvs:
        mv.fold_delta(arrays, nulls, sign=sign, version=version)


def mark_stale(catalog, table: str, reason: str) -> None:
    for mv in matviews_on(catalog, table):
        mv.mark_stale(reason)


def on_truncate(catalog, table: str, wal_seq: int = 0) -> None:
    for mv in matviews_on(catalog, table):
        mv.reset_empty(wal_seq)


def wrap_delete_predicate(catalog, table: str, pred):
    """Wrap a delete predicate to capture the doomed rows' column values
    (+ null masks where the storage exposes them), so subtractable views
    can fold the deleted rows with sign=-1.  Returns (wrapped_pred,
    captured) — captured is None when the table has no views."""
    mvs = matviews_on(catalog, table)
    if not mvs:
        return pred, None
    info = catalog.lookup_table(_norm(table))
    if info is None:
        return pred, None
    names = [f.name for f in info.schema.fields]
    captured: List[Tuple[Dict[str, np.ndarray],
                         Dict[str, Optional[np.ndarray]]]] = []

    def wrapped(cols):
        hit = np.asarray(pred(cols))
        # capture only rows the delete will actually REMOVE: the storage
        # intersects the predicate with its live mask after this returns,
        # so a re-matching predicate (or capacity padding) must not be
        # subtracted from the views a second time
        live_of = getattr(cols, "live_mask", None)
        live = live_of() if live_of is not None else None
        eff = (hit & np.asarray(live)) if live is not None else hit
        if eff.any():
            vals = {c: np.asarray(cols[c])[eff] for c in names}
            mask_of = getattr(cols, "null_mask", None)
            masks = {}
            for c in names:
                m = mask_of(c) if mask_of is not None else None
                masks[c] = np.asarray(m)[eff] if m is not None else None
            captured.append((vals, masks))
        return hit

    return wrapped, captured


def _captured_to_arrays(info, captured):
    """Concatenate per-batch captured {name: values}/{name: mask} pairs
    into full-width delta arrays + null masks."""
    names = [f.name for f in info.schema.fields]
    arrays, nulls = [], []
    for nm in names:
        parts = [c[0][nm] for c in captured]
        arrays.append(np.concatenate(
            [np.asarray(p, dtype=object) if np.asarray(p).dtype == object
             else np.asarray(p) for p in parts]))
        mparts, any_mask = [], False
        for c in captured:
            m = c[1].get(nm)
            n = len(np.asarray(c[0][nm]))
            if m is not None:
                any_mask = True
                mparts.append(np.asarray(m, dtype=bool))
            else:
                mparts.append(np.zeros(n, dtype=bool))
        nulls.append(np.concatenate(mparts) if any_mask else None)
    return arrays, nulls


def fold_deleted(catalog, table: str, captured) -> None:
    """Subtract captured deleted rows from every view over `table` (or
    mark stale when a view has min/max slots)."""
    mvs = matviews_on(catalog, table)
    if not mvs or not captured:
        return
    info = catalog.lookup_table(_norm(table))
    arrays, nulls = _captured_to_arrays(info, captured)
    # the post-apply base version rides along like fold_ingest's: a
    # refresh racing this delete needs it to decide whether its rescan
    # already observed the deletion (replaying it twice would
    # double-subtract)
    version = _data_version(info.data) if info is not None else None
    for mv in mvs:
        if mv.subtractable:
            mv.fold_delta(arrays, nulls, sign=-1, version=version)
        else:
            mv.mark_stale("delete on a min/max view")


def replay_fold(catalog, table: str, arrays, nulls, seq: int) -> None:
    """WAL-replay fold: only records PAST a view's checkpointed
    high-watermark fold (the tail) — records at or below it were folded
    before the state checkpoint was written (no double-fold)."""
    mvs = matviews_on(catalog, table)
    if not mvs:
        return
    reg = global_registry()
    for mv in mvs:
        if mv.stale or seq <= mv.wal_seq:
            continue
        mv.fold_delta(arrays, nulls, sign=1)
        reg.inc("view_replay_folds")


def replay_fold_deleted(catalog, table: str, captured, seq: int) -> None:
    mvs = [mv for mv in matviews_on(catalog, table)
           if not mv.stale and seq > mv.wal_seq]
    if not mvs or not captured:
        return
    info = catalog.lookup_table(_norm(table))
    arrays, nulls = _captured_to_arrays(info, captured)
    reg = global_registry()
    for mv in mvs:
        if mv.subtractable:
            mv.fold_delta(arrays, nulls, sign=-1)
            reg.inc("view_replay_folds")
        else:
            mv.mark_stale("replayed delete on a min/max view")


def view_snapshot(catalog) -> dict:
    """REST `/status/api/v1/views` + dashboard section payload."""
    snap = global_registry().snapshot()
    c = snap["counters"]
    views = [mv.snapshot() for mv in matviews(catalog).values()]
    return {
        "views": sorted(views, key=lambda v: v["name"]),
        "view_state_bytes": sum(v["state_bytes"] for v in views),
        "view_delta_folds": c.get("view_delta_folds", 0),
        "view_rows_folded": c.get("view_rows_folded", 0),
        "view_subtract_folds": c.get("view_subtract_folds", 0),
        "view_full_refreshes": c.get("view_full_refreshes", 0),
        "view_stale_marks": c.get("view_stale_marks", 0),
        "view_syncs": c.get("view_syncs", 0),
        "view_reads": c.get("view_reads", 0),
        "view_state_regrows": c.get("view_state_regrows", 0),
        "view_fold_errors": c.get("view_fold_errors", 0),
        "view_state_evictions": c.get("view_state_evictions", 0),
        "view_replay_folds": c.get("view_replay_folds", 0),
        "view_unmanaged_writes": c.get("view_unmanaged_writes", 0),
    }


# -- resource-broker ledger hooks ----------------------------------------

_ledgered_catalogs: "weakref.WeakSet" = weakref.WeakSet()


def ledger_catalog(catalog) -> None:
    """Track a catalog whose views count toward the broker ledger."""
    _ledgered_catalogs.add(catalog)


def matview_state_nbytes() -> int:
    """Total live view-state bytes — the broker's ledger line."""
    total = 0
    for cat in list(_ledgered_catalogs):
        for mv in matviews(cat).values():
            try:
                total += mv.state_nbytes()
            except Exception:
                pass
    return total


def evict_all_states() -> int:
    """Degradation ladder hook: drop every view state (stale + refresh
    at next read), like the gidx/join caches.  Returns bytes freed."""
    freed = 0
    for cat in list(_ledgered_catalogs):
        for mv in matviews(cat).values():
            try:
                freed += mv.state_nbytes()
                mv.evict_state()
            except Exception:
                pass
    return freed
