"""Client: failover-aware Flight connection (the snappydata JDBC-driver
analogue — jdbc:snappydata://host:port with locator-based failover,
jdbc/.../Constant.scala:29-33)."""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.flight as flight


class SnappyClient:
    def __init__(self, address: Optional[str] = None,
                 locator: Optional[str] = None,
                 token: Optional[str] = None):
        """Connect directly (`address`='host:port') or discover query
        servers through a locator ('host:port' of the locator service).
        `token` authenticates every request when the server has
        auth_tokens configured."""
        self._token = token
        self._addresses: List[str] = []
        if address:
            self._addresses.append(address)
        self._locator = locator
        self._conn: Optional[flight.FlightClient] = None
        if locator and not address:
            self._refresh_from_locator()

    def _refresh_from_locator(self) -> None:
        from snappydata_tpu.cluster.locator import LocatorClient

        lc = LocatorClient(self._locator, member_id="client", role="client")
        try:
            members = lc.members()
        finally:
            lc.close()
        self._addresses = [f"{m.host}:{m.port}" for m in members
                           if m.port and m.role in ("server", "lead")]

    def _client(self) -> flight.FlightClient:
        if self._conn is not None:
            return self._conn
        last_err: Optional[Exception] = None
        for addr in list(self._addresses):
            try:
                conn = flight.connect(f"grpc://{addr}")
                list(conn.do_action(flight.Action("ping", b"")))
                self._conn = conn
                return conn
            except Exception as e:  # failover to the next member
                last_err = e
        if self._locator:
            self._refresh_from_locator()
            for addr in self._addresses:
                try:
                    conn = flight.connect(f"grpc://{addr}")
                    list(conn.do_action(flight.Action("ping", b"")))
                    self._conn = conn
                    return conn
                except Exception as e:
                    last_err = e
        raise ConnectionError(f"no reachable member: {last_err}")

    def _invalidate(self) -> None:
        self._conn = None

    def sql(self, sql: str, params: Sequence = ()) -> pa.Table:
        """Query → Arrow table (record-batch paged by Flight)."""
        ticket = flight.Ticket(json.dumps(
            self._with_token({"sql": sql, "params": list(params)})
        ).encode("utf-8"))
        try:
            return self._client().do_get(ticket).read_all()
        except (flight.FlightUnavailableError, ConnectionError):
            self._invalidate()
            return self._client().do_get(ticket).read_all()

    def execute(self, sql: str, params: Sequence = ()) -> dict:
        """DDL/DML via action (no result paging needed)."""
        body = json.dumps(self._with_token(
            {"sql": sql, "params": list(params)})).encode()
        try:
            results = list(self._client().do_action(
                flight.Action("sql", body)))
        except (flight.FlightUnavailableError, ConnectionError):
            self._invalidate()
            results = list(self._client().do_action(
                flight.Action("sql", body)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def insert(self, table: str, columns: dict) -> None:
        """Bulk columnar ingest via do_put. `columns` is a name → array
        dict or a ready pyarrow Table."""
        arrow = columns if isinstance(columns, pa.Table) else \
            pa.table(columns)
        if self._token is not None:
            descriptor = flight.FlightDescriptor.for_command(json.dumps(
                {"table": table, "token": self._token}).encode("utf-8"))
        else:
            descriptor = flight.FlightDescriptor.for_path(table)
        writer, _ = self._client().do_put(descriptor, arrow.schema)
        writer.write_table(arrow)
        writer.close()

    def repartition(self, body: dict) -> dict:
        """Ask this server to hash-repartition its shard of body['table']
        by body['key'] into body['dest'] across body['servers'] (the
        shuffle-exchange fan-out)."""
        raw = json.dumps(self._with_token(dict(body))).encode("utf-8")
        results = list(self._client().do_action(
            flight.Action("repartition", raw)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def ping(self) -> None:
        """Liveness probe (raises if the member is unreachable)."""
        list(self._client().do_action(flight.Action("ping", b"")))

    def promote(self, body: dict) -> dict:
        """Failover re-hosting: move this server's replica-shadow rows of
        body['buckets'] into its primary table (body['table'])."""
        raw = json.dumps(self._with_token(dict(body))).encode("utf-8")
        results = list(self._client().do_action(
            flight.Action("promote", raw)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def replicate(self, body: dict) -> dict:
        """Redundancy restoration: this server copies its CURRENT rows of
        body['buckets'] (table body['table']) into body['target']'s
        replica shadow."""
        raw = json.dumps(self._with_token(dict(body))).encode("utf-8")
        results = list(self._client().do_action(
            flight.Action("replicate", raw)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def purge_replica(self, body: dict) -> dict:
        """Drop body['buckets'] rows from this server's replica shadow of
        body['table'] (pre-copy cleanup for idempotent re-replication)."""
        raw = json.dumps(self._with_token(dict(body))).encode("utf-8")
        results = list(self._client().do_action(
            flight.Action("purge_replica", raw)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def _with_token(self, body: dict) -> dict:
        if self._token is not None:
            body["token"] = self._token
        return body

    def stats(self) -> dict:
        body = json.dumps(self._with_token({})).encode("utf-8")
        results = list(self._client().do_action(
            flight.Action("stats", body)))
        return json.loads(results[0].body.to_pybytes().decode("utf-8"))

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None
