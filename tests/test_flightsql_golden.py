"""Independent Flight SQL protocol evidence (round-4 verdict Weak #3 /
task 4a): the hand-rolled protobuf codec in cluster/flightsql.py is
asserted against GOLDEN wire-format fixtures generated with the
OFFICIAL google.protobuf runtime from a vendored subset of the public
FlightSql.proto (tests/fixtures/flightsql_subset.proto — field numbers
copied from apache/arrow's spec, the contract a stock ADBC/JDBC
Flight SQL driver speaks; ref /root/reference/cluster/
README-thrift.md:20-35 "any JDBC/ODBC client connects").

Until now the codec was verified only against its own FlightSqlClient —
an encode/decode bug symmetric in both directions was invisible. Here:
(1) decode: official bytes -> the exact field values;
(2) encode: the codec re-produces the official bytes BYTE-IDENTICALLY
    (proto3 canonical form, defaults omitted);
(3) provenance: a live protoc + google.protobuf pass regenerates every
    fixture and must match the vendored hex, proving the fixtures are
    genuine official-runtime output and not tuned to the codec.
"""

import os
import shutil
import subprocess
import sys

import pytest

from snappydata_tpu.cluster.flightsql import (_b, _s, decode_fields,
                                              encode_fields, pack_any,
                                              unpack_any)

_FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures")

# hex(SerializeToString()) from the official google.protobuf runtime
# (6.x) over tests/fixtures/flightsql_subset.proto — regenerated and
# re-asserted by test_fixture_provenance_official_runtime below.
GOLDEN = {
    "CommandStatementQuery":
        "0a2c53454c4543542073756d287072696365292046524f4d206f7264657273"
        "20574845524520717479203c203435",
    "CommandStatementUpdate":
        "0a205550444154452074205345542076203d20312e35205748455245206b20"
        "3d2037",
    "CommandGetTables_full":
        "0a06736e6170707912034150501a044f52442522055441424c452204564945"
        "572801",
    "CommandGetTables_pattern_only": "1a0125",
    "CommandGetCatalogs": "",
    "CommandGetDbSchemas": "0a0263311203415025",
    "ActionCreatePreparedStatementRequest":
        "0a1b53454c454354202a2046524f4d2074205748455245206b203d203f",
    "ActionCreatePreparedStatementResult":
        "0a0c000168616e646c652d3432ff1203102030",
    "ActionClosePreparedStatementRequest": "0a03682d31",
    "CommandPreparedStatementQuery": "0a03070809",
    "TicketStatementQuery": "0a137b2273716c223a202253454c4543542031227d",
    "DoPutUpdateResult": "08b5b8f0fe2d",
    "DoPutUpdateResult_zero": "",
    # record_count = -1 ('unknown' per the FlightSql spec): proto varints
    # are two's-complement over 64 bits -> 10-byte encoding
    "DoPutUpdateResult_unknown": "08ffffffffffffffffff01",
    # repeated table_types with an EMPTY-STRING element: a real element,
    # NOT a droppable proto3 default (that omission rule is for
    # singular fields only — advisor round 5)
    "CommandGetTables_empty_type": "2200220456494557",
    "Any_CommandStatementQuery":
        "0a43747970652e676f6f676c65617069732e636f6d2f6172726f772e666c69"
        "6768742e70726f746f636f6c2e73716c2e436f6d6d616e6453746174656d65"
        "6e745175657279120a0a0853454c4543542031",
}

# the logical content of every fixture: (message, {field: value})
CONTENT = {
    "CommandStatementQuery":
        [(1, "SELECT sum(price) FROM orders WHERE qty < 45")],
    "CommandStatementUpdate":
        [(1, "UPDATE t SET v = 1.5 WHERE k = 7")],
    "CommandGetTables_full":
        [(1, "snappy"), (2, "APP"), (3, "ORD%"), (4, "TABLE"),
         (4, "VIEW"), (5, True)],
    "CommandGetTables_pattern_only": [(3, "%"), (5, False)],
    "CommandGetCatalogs": [],
    "CommandGetDbSchemas": [(1, "c1"), (2, "AP%")],
    "ActionCreatePreparedStatementRequest":
        [(1, "SELECT * FROM t WHERE k = ?")],
    "ActionCreatePreparedStatementResult":
        [(1, b"\x00\x01handle-42\xff"), (2, b"\x10\x20\x30")],
    "ActionClosePreparedStatementRequest": [(1, b"h-1")],
    "CommandPreparedStatementQuery": [(1, b"\x07\x08\x09")],
    "TicketStatementQuery": [(1, b'{"sql": "SELECT 1"}')],
    "DoPutUpdateResult": [(1, 12345678901)],
    "DoPutUpdateResult_zero": [(1, 0)],
    "DoPutUpdateResult_unknown": [(1, -1)],
    "CommandGetTables_empty_type": [(4, ["", "VIEW"])],
}


def test_codec_decodes_official_bytes():
    f = decode_fields(bytes.fromhex(GOLDEN["CommandStatementQuery"]))
    assert _s(f, 1) == "SELECT sum(price) FROM orders WHERE qty < 45"

    f = decode_fields(bytes.fromhex(GOLDEN["CommandGetTables_full"]))
    assert _s(f, 1) == "snappy"
    assert _s(f, 2) == "APP"
    assert _s(f, 3) == "ORD%"
    assert [v.decode() for v in f[4]] == ["TABLE", "VIEW"]
    assert f[5] == [1]                       # include_schema=True

    f = decode_fields(
        bytes.fromhex(GOLDEN["CommandGetTables_pattern_only"]))
    assert _s(f, 3) == "%"
    assert 5 not in f                        # proto3 default omitted

    f = decode_fields(
        bytes.fromhex(GOLDEN["ActionCreatePreparedStatementResult"]))
    assert _b(f, 1) == b"\x00\x01handle-42\xff"
    assert _b(f, 2) == b"\x10\x20\x30"

    f = decode_fields(bytes.fromhex(GOLDEN["DoPutUpdateResult"]))
    assert f[1] == [12345678901]
    assert decode_fields(
        bytes.fromhex(GOLDEN["DoPutUpdateResult_zero"])) == {}

    # negative record_count: raw varint is unsigned; the signed helper
    # recovers -1 (and the codec's encoder terminates — it used to loop
    # forever on negatives)
    from snappydata_tpu.cluster.flightsql import varint_to_int64

    f = decode_fields(bytes.fromhex(GOLDEN["DoPutUpdateResult_unknown"]))
    assert varint_to_int64(f[1][0]) == -1
    assert varint_to_int64(12345678901) == 12345678901

    # repeated-field elements survive even when they are default values
    f = decode_fields(bytes.fromhex(GOLDEN["CommandGetTables_empty_type"]))
    assert f[4] == [b"", b"VIEW"]


def test_codec_encodes_byte_identical():
    for name, fields in CONTENT.items():
        got = encode_fields(fields).hex()
        assert got == GOLDEN[name], name


def test_any_pack_unpack_matches_official():
    raw = bytes.fromhex(GOLDEN["Any_CommandStatementQuery"])
    name, payload = unpack_any(raw)
    assert name == "CommandStatementQuery"
    assert _s(decode_fields(payload), 1) == "SELECT 1"
    assert pack_any("CommandStatementQuery",
                    encode_fields([(1, "SELECT 1")])).hex() \
        == GOLDEN["Any_CommandStatementQuery"]


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not available")
def test_fixture_provenance_official_runtime(tmp_path):
    """Regenerate every fixture with protoc + google.protobuf and
    assert equality with the vendored hex — the fixtures stay honest
    official-runtime output, not bytes tuned to the codec."""
    pytest.importorskip("google.protobuf")
    proto = os.path.join(_FIXDIR, "flightsql_subset.proto")
    subprocess.run(["protoc", f"--proto_path={_FIXDIR}",
                    f"--python_out={tmp_path}", proto], check=True)
    sys.path.insert(0, str(tmp_path))
    try:
        import flightsql_subset_pb2 as pb
        from google.protobuf import any_pb2
    finally:
        sys.path.remove(str(tmp_path))

    regen = {
        "CommandStatementQuery": pb.CommandStatementQuery(
            query="SELECT sum(price) FROM orders WHERE qty < 45"),
        "CommandStatementUpdate": pb.CommandStatementUpdate(
            query="UPDATE t SET v = 1.5 WHERE k = 7"),
        "CommandGetTables_full": pb.CommandGetTables(
            catalog="snappy", db_schema_filter_pattern="APP",
            table_name_filter_pattern="ORD%",
            table_types=["TABLE", "VIEW"], include_schema=True),
        "CommandGetTables_pattern_only": pb.CommandGetTables(
            table_name_filter_pattern="%", include_schema=False),
        "CommandGetCatalogs": pb.CommandGetCatalogs(),
        "CommandGetDbSchemas": pb.CommandGetDbSchemas(
            catalog="c1", db_schema_filter_pattern="AP%"),
        "ActionCreatePreparedStatementRequest":
            pb.ActionCreatePreparedStatementRequest(
                query="SELECT * FROM t WHERE k = ?"),
        "ActionCreatePreparedStatementResult":
            pb.ActionCreatePreparedStatementResult(
                prepared_statement_handle=b"\x00\x01handle-42\xff",
                dataset_schema=b"\x10\x20\x30"),
        "ActionClosePreparedStatementRequest":
            pb.ActionClosePreparedStatementRequest(
                prepared_statement_handle=b"h-1"),
        "CommandPreparedStatementQuery":
            pb.CommandPreparedStatementQuery(
                prepared_statement_handle=b"\x07\x08\x09"),
        "TicketStatementQuery": pb.TicketStatementQuery(
            statement_handle=b'{"sql": "SELECT 1"}'),
        "DoPutUpdateResult": pb.DoPutUpdateResult(
            record_count=12345678901),
        "DoPutUpdateResult_zero": pb.DoPutUpdateResult(record_count=0),
        "DoPutUpdateResult_unknown": pb.DoPutUpdateResult(
            record_count=-1),
        "CommandGetTables_empty_type": pb.CommandGetTables(
            table_types=["", "VIEW"]),
    }
    any_msg = any_pb2.Any()
    any_msg.Pack(pb.CommandStatementQuery(query="SELECT 1"),
                 type_url_prefix="type.googleapis.com/")
    regen["Any_CommandStatementQuery"] = any_msg

    for name, msg in regen.items():
        assert msg.SerializeToString().hex() == GOLDEN[name], name

    # and the official runtime PARSES what the codec emits
    parsed = pb.CommandGetTables()
    parsed.ParseFromString(encode_fields(CONTENT["CommandGetTables_full"]))
    assert parsed.catalog == "snappy" and parsed.include_schema is True
    assert list(parsed.table_types) == ["TABLE", "VIEW"]
