"""Approximate Query Processing (AQP/SDE).

The reference ships this as a closed-source plug-in behind
SnappyContextFunctions hooks (core/.../SnappyContextFunctions.scala:29-94;
docs/aqp.md): stratified samples (CREATE SAMPLE TABLE ... OPTIONS (qcs,
fraction)), error-bounded SUM/AVG/COUNT rewrites, and TopK structures
backed by CountMinSketch + StreamSummary (the clearspring utilities
vendored in core). Same shape here: a plug-in package the session calls
into, nothing in the core engine depends on it.
"""

from snappydata_tpu.aqp.sampling import StratifiedReservoir  # noqa: F401
from snappydata_tpu.aqp.sketches import CountMinSketch, TopKSummary  # noqa: F401
from snappydata_tpu.aqp.rewrite import approx_rewrite  # noqa: F401
