"""Micro-batch streaming queries over pluggable sources.

The reference runs Spark structured streaming (micro-batches from Kafka/
file/socket sources) into the snappy sink (SURVEY.md §3.5) plus a legacy
DStream layer (SchemaDStream). Here: a thread-driven micro-batch loop with
the same progress/exactly-once contract, and sources for in-memory queues,
growing files, and Kafka (streaming/kafka.py — durable per-partition
offset ranges behind the same Source interface; network brokers need a
client library, in-process brokers work out of the box)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.streaming.sink import SnappySink
from snappydata_tpu.utils import locks


class Source:
    """One micro-batch source: next_batch(from_offset) → (columns, new
    offset) or None when no data is pending."""

    def next_batch(self, offset):
        raise NotImplementedError


class MemorySource(Source):
    """In-memory list of pending batches (tests / programmatic feeds)."""

    def __init__(self):
        self._batches: List[Dict[str, np.ndarray]] = []
        self._lock = locks.named_lock("streaming.query")

    def add_batch(self, columns: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._batches.append(columns)

    def next_batch(self, offset):
        with self._lock:
            if offset < len(self._batches):
                return self._batches[offset], offset + 1
        return None


class FileSource(Source):
    """Tails a directory of JSON-lines files (ref: file stream source).
    Each new file is one micro-batch; offset = count of consumed files."""

    def __init__(self, directory: str, schema_names: List[str]):
        self.directory = directory
        self.names = schema_names

    def next_batch(self, offset):
        files = sorted(f for f in os.listdir(self.directory)
                       if not f.startswith("."))
        if offset >= len(files):
            return None
        path = os.path.join(self.directory, files[offset])
        rows = []
        skipped = 0
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    # poison line: skip rather than wedging the stream on
                    # the same offset forever (log-tailing semantics)
                    skipped += 1
        if skipped:
            import sys

            print(f"warning: {path}: skipped {skipped} malformed line(s)",
                  file=sys.stderr)
        cols = {n: np.array([r.get(n) for r in rows]) for n in self.names}
        for extra in ("_eventType",):
            if rows and extra in rows[0]:
                cols[extra] = np.array([r[extra] for r in rows])
        return cols, offset + 1


class SocketSource(Source):
    """TCP line source (ref: socketTextStream demos — the reference's
    socket stream source is likewise at-most-once: a socket has no
    offsets to replay, so unconsumed lines buffered at crash time are
    lost; durable pipelines use kafka_stream)."""

    def __init__(self, host: str, port: int, schema_names):
        import socket
        import threading as _t

        self.names = list(schema_names)
        self._buf: List[dict] = []
        self._lock = locks.named_lock("streaming.socket_source")
        self._sock = socket.create_connection((host, port), timeout=10)
        # the 10s timeout covers CONNECT only: a blocking read timeout
        # would poison the pump on any >10s producer idle gap
        self._sock.settimeout(None)
        self._closed = False
        _t.Thread(target=self._pump, daemon=True).start()

    def _pump(self) -> None:
        import json as _json

        fh = self._sock.makefile("r")
        try:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = _json.loads(line)
                except ValueError:
                    continue  # poison line: skip, like FileSource
                with self._lock:
                    self._buf.append(rec)
        except OSError:
            pass
        finally:
            self._closed = True

    def next_batch(self, offset):
        with self._lock:
            if not self._buf:
                return None
            rows, self._buf = self._buf, []
        cols = {n: np.array([r.get(n) for r in rows])
                for n in self.names}
        return cols, offset + 1

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _batch_empty(columns) -> bool:
    return not columns or all(len(np.asarray(v)) == 0
                              for v in columns.values())


class StreamingQuery:
    """One running micro-batch pipeline: source → optional transform →
    exactly-once sink. Progress (batch id) restarts from the sink state
    table, so a restarted query resumes where it left off."""

    def __init__(self, session, name: str, source: Source, table: str,
                 transform: Optional[Callable] = None,
                 conflation: bool = False, interval_s: float = 0.05,
                 stamp_arrivals: bool = False):
        self.session = session
        self.name = name
        self.source = source
        self.sink = SnappySink(session, name, table, conflation=conflation)
        self.transform = transform
        self.interval_s = interval_s
        self.stamp_arrivals = stamp_arrivals
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches_processed = 0
        self.rows_processed = 0
        self.started_at = time.time()
        self.last_batch_ts: Optional[float] = None
        self.last_error: Optional[BaseException] = None

    # offset == batch id: deterministic replay after restart
    def start(self) -> "StreamingQuery":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        offset = self.sink.last_batch_id() + 1
        while not self._stop.is_set():
            try:
                got = self.source.next_batch(offset)
            except Exception as e:  # source hiccup: retry next tick
                logging.getLogger(__name__).warning(
                    "stream source fetch failed: %s", e)
                global_registry().inc("stream_source_errors")
                self.last_error = e
                got = None
            if got is None:
                time.sleep(self.interval_s)
                continue
            columns, new_offset = got
            if self.transform is not None:
                columns = self.transform(columns)
            if _batch_empty(columns):
                offset = new_offset  # nothing to apply; just advance
                continue
            columns = self._stamp(columns)
            try:
                applied = self.sink.process_batch(offset, columns)
                self._note_batch(columns if applied else None)
                self._prune_source_log(offset)
                offset = new_offset
            except Exception as e:
                # retried next tick at the same offset (exactly-once
                # sinks dedup) — but the stall must be visible
                logging.getLogger(__name__).warning(
                    "stream batch apply failed: %s", e)
                global_registry().inc("stream_apply_errors")
                self.last_error = e
                time.sleep(self.interval_s)

    def _stamp(self, columns):
        """Arrival timestamps for WINDOW (DURATION ...) queries."""
        if not self.stamp_arrivals or not columns:
            return columns
        n = len(np.asarray(next(iter(columns.values()))))
        out = dict(columns)
        out["__arrival_ts"] = np.full(n, int(time.time() * 1e6),
                                      dtype=np.int64)
        return out

    def process_available(self) -> int:
        """Synchronous drain (tests / backfills): consume until the source
        is empty. Returns number of batches applied."""
        offset = self.sink.last_batch_id() + 1
        applied = 0
        while True:
            got = self.source.next_batch(offset)
            if got is None:
                return applied
            columns, new_offset = got
            if self.transform is not None:
                columns = self.transform(columns)
            columns = self._stamp(columns)
            did_apply = not _batch_empty(columns) and \
                self.sink.process_batch(offset, columns)
            if did_apply:
                applied += 1
                self._prune_source_log(offset)
            # rows count only when APPLIED: a replayed batch the exactly-
            # once sink deduplicated must not inflate progress metrics
            self._note_batch(columns if did_apply else None)
            offset = new_offset

    def _prune_source_log(self, applied_batch_id: int) -> None:
        """Sources with a durable offset log (Kafka) drop entries the
        sink has durably recorded — everything strictly below the
        applied batch stays replayable until then."""
        prune = getattr(self.source, "prune_log", None)
        if prune is not None:
            try:
                prune(applied_batch_id)
            except Exception:
                pass  # pruning is advisory; replay handles leftovers

    def _note_batch(self, columns) -> None:
        """columns=None → the batch was seen but deduplicated (replay)."""
        self.batches_processed += 1
        if columns:
            self.rows_processed += int(
                len(np.asarray(next(iter(columns.values())))))
        self.last_batch_ts = time.time()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        close = getattr(self.source, "close", None)
        if close is not None:   # socket sources hold a live connection
            try:
                close()
            except Exception:
                pass

    @property
    def is_active(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def progress(self) -> dict:
        """Status snapshot (ref: StreamingQueryManager's query progress —
        the reference's structured-streaming UI tab reads the same
        fields: batches, input rows, processing rate, last error)."""
        elapsed = max(time.time() - self.started_at, 1e-9)
        return {
            "name": self.name,
            "table": self.sink.table,
            "active": self.is_active,
            "batches_processed": self.batches_processed,
            "rows_processed": self.rows_processed,
            "rows_per_s": round(self.rows_processed / elapsed, 1),
            "last_batch_id": self.sink.last_batch_id(),
            "last_batch_ts": self.last_batch_ts,
            "interval_s": self.interval_s,
            "last_error": str(self.last_error) if self.last_error else None,
        } | (self.source.extra_progress()
             if hasattr(self.source, "extra_progress") else {})
