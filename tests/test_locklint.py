"""Concurrency static-analysis + runtime lockdep witness suite.

Covers the tools/locklint passes (lock-order manifest gate over the
real tree, the PR 6 ABBA and PR 10 gauge-under-lock fixture shapes,
metrics hygiene, background-exception hygiene), the manifest model, and
the runtime witness (cycle reported with both stacks BEFORE the threads
deadlock, RLock reentrancy, subgraph check, zero overhead when off)."""

import os
import subprocess
import sys
import threading

import pytest

pytestmark = pytest.mark.lockdep

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "locklint_fixtures")
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.locklint", *args],
        cwd=ROOT, capture_output=True, text=True, timeout=300)


# ---------------------------------------------------------------- CI gate

def test_locklint_clean_on_real_tree():
    """THE gate: `python -m tools.locklint snappydata_tpu/` exits 0 —
    zero undeclared lock-order edges, zero unwaived blocking-call /
    callback / metric / exception findings on the shipped tree."""
    res = _cli("snappydata_tpu")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "clean" in res.stdout


def test_locklint_flags_historical_bug_fixtures():
    """The reduced PR 6 ABBA shape and PR 10 gauge shape must be
    flagged — the analyzer that misses them can't guard the real tree."""
    res = _cli(os.path.relpath(FIXTURES, ROOT))
    assert res.returncode == 1, res.stdout + res.stderr
    out = res.stdout
    # ABBA: cycle + both edges with sites
    assert "lock-order-cycle" in out
    assert "abba_fixture.py" in out
    assert "fixture.mutation" in out
    assert out.count("lock-order-undeclared") >= 2
    # gauge-under-registry-lock
    assert "callback-under-lock" in out
    assert "gauge_fixture.py" in out
    # sibling lints on the same fixtures
    assert "swallowed-exception" in out
    assert "metric-collision" in out
    assert "unnamed-lock" in out


# ------------------------------------------------------- analyzer details

def _analyze_fixtures():
    from tools.locklint import analyzer

    return analyzer.analyze([FIXTURES])


def test_static_edges_carry_sites():
    an = _analyze_fixtures()
    edges = {k: v for k, v in an.edges.items()}
    fwd = [(a, b) for (a, b) in edges
           if a == "fixture.mutation" and "View._lock" in b]
    rev = [(a, b) for (a, b) in edges
           if "View._lock" in a and b == "fixture.mutation"]
    assert fwd and rev, sorted(edges)
    for key in fwd + rev:
        path, line, _via = edges[key]
        assert path.endswith("abba_fixture.py") and line > 0


def test_inter_procedural_edge_via_method_call():
    """commit() holds the mutation lock and calls view.fold(), which
    takes the view lock — the edge must come from the CALL chain, not a
    direct with-nesting."""
    an = _analyze_fixtures()
    hit = [(k, v) for k, v in an.edges.items()
           if k[0] == "fixture.mutation"]
    assert hit
    assert any("via" in v[2] for _k, v in hit)


# ----------------------------------------------------------- manifest

def test_manifest_rejects_declared_cycle():
    from tools.locklint.manifest import Manifest, ManifestError

    m = Manifest({"order": [{"chain": ["a", "b"]}, {"chain": ["b", "a"]}]})
    with pytest.raises(ManifestError):
        m.validate()


def test_manifest_rejects_leaf_as_source():
    from tools.locklint.manifest import Manifest, ManifestError

    m = Manifest({"order": [{"chain": ["metrics", "x"]}],
                  "leaf": {"names": ["metrics"]}})
    with pytest.raises(ManifestError):
        m.validate()


def test_manifest_semantics():
    from tools.locklint.manifest import Manifest

    m = Manifest({
        "order": [{"chain": ["a", "b", "c"]}, {"chain": ["c", "d"]}],
        "edge": [{"from": "x", "to": "y"}],
        "leaf": {"names": ["leafy"]},
    })
    m.validate()
    assert m.allows("a", "b") and m.allows("a", "c")
    assert m.allows("a", "d"), "closure must compose chains through c"
    assert m.allows("x", "y") and not m.allows("y", "x")
    assert not m.allows("b", "a")
    assert m.allows("anything", "leafy")
    assert not m.allows("leafy", "a"), "leaves are terminal"
    assert m.allows("a", "a"), "same lock class: self-nesting policy"


def test_shipped_manifest_is_valid_dag():
    from tools.locklint import load_manifest

    man = load_manifest()
    # validate() ran inside load(); spot-check the codified orderings
    assert man.allows("storage.mutation_lock", "views.matview"), \
        "PR 6 ordering must be declared"
    assert not man.allows("views.matview", "storage.mutation_lock")
    assert man.allows("mvcc.pin", "mvcc.clock"), "PR 11 ordering"
    assert not man.allows("mvcc.clock", "mvcc.pin")
    assert man.allows("storage.mutation_lock",
                      "observability.metrics_registry")


def test_toml_lite_parses_manifest_shapes():
    from tools.locklint import toml_lite

    doc = toml_lite.loads(
        'version = 1\n'
        '# comment\n'
        '[[order]]\n'
        'name = "x"      # trailing comment\n'
        'chain = ["a", "b",\n'
        '         "c"]\n'
        '[[order]]\n'
        'chain = ["d", "e"]\n'
        '[leaf]\n'
        'names = ["m"]\n'
        'flag = true\n')
    assert doc["version"] == 1
    assert doc["order"][0]["chain"] == ["a", "b", "c"]
    assert doc["order"][1]["chain"] == ["d", "e"]
    assert doc["leaf"]["names"] == ["m"] and doc["leaf"]["flag"] is True


# ------------------------------------------------------ metrics hygiene

def test_metric_registry_in_sync_with_tree():
    """Every literal metric name used in the package is declared (the
    lint enforces it in CI; this is the in-process mirror with a useful
    diff on failure)."""
    from tools.locklint import metrics_lint

    decl = metrics_lint.load_declared(os.path.join(
        ROOT, "snappydata_tpu", "observability", "metric_names.py"))
    used = metrics_lint.collect_used([os.path.join(ROOT, "snappydata_tpu")])
    declared_all = decl["counter"] | decl["timer"] | decl["gauge"]
    missing = {k: sorted(v - declared_all) for k, v in used.items()
               if v - declared_all}
    assert not missing, missing


def test_metric_collision_detected():
    from tools.locklint import metrics_lint

    assert metrics_lint._sanitize("a.b") == metrics_lint._sanitize("a_b")
    res = _cli(os.path.relpath(FIXTURES, ROOT))
    assert "metric-collision" in res.stdout


# ------------------------------------------------------ runtime witness

@pytest.fixture()
def witness():
    from snappydata_tpu.utils import locks

    was = locks.enabled()
    # save/RESTORE the global witness state: this fixture's tests create
    # deliberate violations and fixture.* edges, which must not leak
    # into a lockdep-enabled outer session's end-of-run check — but a
    # blanket reset() would also erase the REAL edges/violations that
    # session accumulated before this test file ran
    snap = locks.snapshot_state()
    locks.enable()
    try:
        yield locks
    finally:
        locks.restore_state(snap)
        if not was:
            locks.disable()


def test_witness_reports_cycle_with_both_stacks_before_deadlock(witness):
    """Two seeded threads: T1 establishes A->B; T2 takes B then tries A.
    The witness must raise IN T2, BEFORE it blocks on A — with both
    acquisition stacks — and both threads must finish (no deadlock)."""
    locks = witness
    A = locks.named_lock("fixture.thread_a")
    B = locks.named_lock("fixture.thread_b")
    e1, e2 = threading.Event(), threading.Event()
    caught = []

    def t1():
        with A:
            with B:            # establishes A -> B
                pass
        e1.set()
        e2.wait(10)
        with A:                # still fine afterwards
            pass

    def t2():
        e1.wait(10)
        with B:
            try:
                with A:        # closes the cycle: witness must raise
                    pass
            except locks.LockdepViolation as e:
                caught.append(str(e))
        e2.set()

    th1 = threading.Thread(target=t1)
    th2 = threading.Thread(target=t2)
    th1.start(); th2.start()
    th1.join(15); th2.join(15)
    assert not th1.is_alive() and not th2.is_alive(), "threads deadlocked"
    assert caught, "witness did not fire"
    msg = caught[0]
    assert "fixture.thread_a" in msg and "fixture.thread_b" in msg
    assert "closes the cycle" in msg
    # both stacks: the current thread's and the first-observed reverse edge's
    assert "--- this thread" in msg and "--- reverse edge" in msg
    assert msg.count("test_locklint.py") >= 2, msg
    assert locks.violations(), "violation must be recorded globally too"


def test_witness_detects_self_deadlock_on_plain_lock(witness):
    """Same-thread re-acquisition of a non-reentrant named Lock is a
    guaranteed self-deadlock (the PR 10 gauge shape): the witness must
    RAISE instead of hanging, and record the violation globally."""
    locks = witness
    locks.reset()
    L = locks.named_lock("fixture.selfdead")
    with L:
        with pytest.raises(locks.LockdepViolation, match="self-deadlock"):
            L.acquire()
    assert any("fixture.selfdead" in v for v in locks.violations())
    # the lock is released and reusable afterwards
    with L:
        pass


def test_witness_observes_edges_and_subgraph_check(witness):
    locks = witness
    locks.reset()
    A = locks.named_lock("fixture.sub_a")
    B = locks.named_lock("fixture.sub_b")
    with A:
        with B:
            pass
    assert ("fixture.sub_a", "fixture.sub_b") in locks.observed_edges()
    bad = locks.assert_subgraph(lambda a, b: False)
    assert any("fixture.sub_a -> fixture.sub_b" in m for m in bad)
    ok = locks.assert_subgraph(lambda a, b: True)
    assert ok == []


def test_witness_rlock_reentrancy_no_self_edge(witness):
    locks = witness
    locks.reset()
    R = locks.named_rlock("fixture.reentrant")
    with R:
        with R:                 # reentrant: no edge, no violation
            pass
    assert ("fixture.reentrant", "fixture.reentrant") \
        not in locks.observed_edges()
    assert not locks.violations()


def test_witness_same_name_instances_nest(witness):
    """Two instances of one lock CLASS may nest (per-table locks) —
    self-nesting is the class's own business, not a cycle."""
    locks = witness
    locks.reset()
    t1 = locks.named_lock("fixture.table")
    t2 = locks.named_lock("fixture.table")
    with t1:
        with t2:
            pass
    assert not locks.violations()
    assert ("fixture.table", "fixture.table") not in locks.observed_edges()


def test_witness_condition_wait_releases_held_entry(witness):
    locks = witness
    locks.reset()
    cond = locks.named_condition("fixture.cond")
    hit = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hit.append(True)

    th = threading.Thread(target=waiter)
    th.start()
    import time

    deadline = time.time() + 5
    while time.time() < deadline:
        with cond:
            cond.notify_all()
        if hit:
            break
        time.sleep(0.01)
    th.join(5)
    assert hit and not th.is_alive()
    assert not locks.violations()


def test_named_lock_plain_when_disabled():
    from snappydata_tpu.utils import locks

    if locks.enabled():
        pytest.skip("outer session runs under SNAPPY_TPU_LOCKDEP")
    lk = locks.named_lock("fixture.off")
    assert type(lk) is type(threading.Lock()), \
        "disabled witness must hand back the raw primitive (hot paths)"
    rl = locks.named_rlock("fixture.off_r")
    assert type(rl) is type(threading.RLock())


# ------------------------------------------- witness over the real engine

def test_representative_htap_chaos_under_lockdep():
    """One representative seeded HTAP chaos test runs under
    SNAPPY_TPU_LOCKDEP=1: zero cycle reports, and the conftest
    session-end check proves the observed graph is a subgraph of the
    declared manifest (a witness failure raises out of sessionfinish →
    nonzero exit)."""
    env = dict(os.environ)
    env["SNAPPY_TPU_LOCKDEP"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTEST_CURRENT_TEST", None)
    res = subprocess.run(
        [sys.executable, "-m", "pytest",
         "tests/test_mvcc.py::test_htap_chaos_schedule",
         "-q", "-p", "no:cacheprovider"],
        cwd=ROOT, capture_output=True, text=True, timeout=420, env=env)
    assert res.returncode == 0, res.stdout[-4000:] + res.stderr[-4000:]
    assert "1 passed" in res.stdout
    assert "lockdep witness" not in res.stderr
