"""Network-surface security + durability regressions (advisor findings,
round 1): do_put WAL ordering/null fidelity, EXEC PYTHON gating on
network surfaces, token auth on Flight and REST.

Reference behavior: network servers authenticate principals (SecurityUtils
LDAP hooks) and query routing runs per-connection sessions
(SparkSQLExecuteImpl.scala:99)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pyarrow.flight as pafl
import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.cluster import SnappyClient
from snappydata_tpu.cluster.flight_server import SnappyFlightServer


def _serve(session, auth_tokens=None):
    server = SnappyFlightServer(session, "127.0.0.1", 0,
                                auth_tokens=auth_tokens)
    th = threading.Thread(target=server.serve, daemon=True)
    th.start()
    server.wait_ready(timeout=10)
    return server


def test_do_put_nulls_survive_recovery(tmp_path):
    """Advisor (high): do_put's WAL record used to omit null masks —
    bulk-ingested NULLs silently became 0 after recovery."""
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE m (id BIGINT, v DOUBLE) USING column")
    server = _serve(s)
    try:
        client = SnappyClient(address=f"127.0.0.1:{server.port}")
        import pyarrow as pa

        arrow = pa.table({
            "id": pa.array([1, 2, 3, 4], type=pa.int64()),
            "v": pa.array([1.5, None, 3.5, None], type=pa.float64())})
        descriptor = pafl.FlightDescriptor.for_path("m")
        writer, _ = client._client().do_put(descriptor, arrow.schema)
        writer.write_table(arrow)
        writer.close()
        client.close()
    finally:
        server.shutdown()
    s.disk_store.close()

    # recover WITHOUT a checkpoint: rows must come from the WAL, nulls intact
    s2 = SnappySession(data_dir=d)
    rows = s2.sql("SELECT id, v FROM m ORDER BY id").rows()
    assert [r[0] for r in rows] == [1, 2, 3, 4]
    assert rows[1][1] is None and rows[3][1] is None
    assert rows[0][1] == pytest.approx(1.5)
    # count of NULLs must not be zero-filled
    assert s2.sql("SELECT count(*) FROM m WHERE v IS NULL").rows()[0][0] == 2
    s2.disk_store.close()


def test_do_put_then_checkpoint_no_duplicates(tmp_path):
    """Advisor (high): do_put journaled AFTER applying, outside the
    mutation lock — a checkpoint folding the rows then replaying the
    record duplicated them."""
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE m (id BIGINT) USING column")
    server = _serve(s)
    try:
        client = SnappyClient(address=f"127.0.0.1:{server.port}")
        client.insert("m", {"id": np.arange(100, dtype=np.int64)})
        client.close()
    finally:
        server.shutdown()
    s.checkpoint()
    s.disk_store.close()
    s2 = SnappySession(data_dir=d)
    assert s2.sql("SELECT count(*) FROM m").rows()[0][0] == 100
    s2.disk_store.close()


def test_exec_python_refused_over_network_without_auth():
    s = SnappySession()
    server = _serve(s)
    try:
        client = SnappyClient(address=f"127.0.0.1:{server.port}")
        with pytest.raises(Exception, match="EXEC PYTHON"):
            client.execute("EXEC PYTHON 'result = [1]'")
        client.close()
    finally:
        server.shutdown()
    # local (non-remote) sessions still allow it
    assert s.sql("EXEC PYTHON 'result = [42]'").rows()[0][0] == 42


def test_flight_token_auth_and_principals():
    s = SnappySession()  # node session is the admin superuser
    s.sql("CREATE TABLE t (a INT) USING column")
    s.sql("INSERT INTO t VALUES (1), (2)")
    tokens = {"admintok": "admin", "bobtok": "bob"}
    server = _serve(s, auth_tokens=tokens)
    try:
        # no token → refused
        noauth = SnappyClient(address=f"127.0.0.1:{server.port}")
        with pytest.raises(Exception, match="(?i)token|unauthenticated"):
            noauth.sql("SELECT * FROM t")
        noauth.close()
        # bob authenticates but lacks SELECT until granted
        bob = SnappyClient(address=f"127.0.0.1:{server.port}",
                           token="bobtok")
        with pytest.raises(Exception, match="(?i)lacks"):
            bob.sql("SELECT * FROM t")
        s.sql("GRANT SELECT ON t TO bob")
        assert bob.sql("SELECT count(*) FROM t").column(0).to_pylist() == [2]
        # bob is authenticated but NOT admin → EXEC PYTHON refused
        with pytest.raises(Exception, match="EXEC PYTHON|may not run"):
            bob.execute("EXEC PYTHON 'result = [1]'")
        bob.close()
        # authenticated admin gets the interpreter
        admin = SnappyClient(address=f"127.0.0.1:{server.port}",
                             token="admintok")
        out = admin.execute("EXEC PYTHON 'result = [7]'")
        assert out["rows"] == [[7]]
        # token also authorizes do_put, and privileges apply
        with pytest.raises(Exception, match="(?i)lacks"):
            bob2 = SnappyClient(address=f"127.0.0.1:{server.port}",
                                token="bobtok")
            bob2.insert("t", {"a": np.array([3], dtype=np.int64)})
        admin.insert("t", {"a": np.array([3], dtype=np.int64)})
        assert admin.sql("SELECT count(*) FROM t").column(0).to_pylist() \
            == [3]
        admin.close()
    finally:
        server.shutdown()


def test_rest_jobs_require_token_when_configured():
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability import TableStatsService

    s = SnappySession()
    s.sql("CREATE TABLE rj (a INT) USING column")
    svc = RestService(s, TableStatsService(s.catalog),
                      auth_tokens={"tok1": "admin"}).start()
    try:
        base = f"http://{svc.host}:{svc.port}"
        body = json.dumps({"sql": "SELECT 1"}).encode()

        req = urllib.request.Request(base + "/jobs", data=body,
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 401

        req = urllib.request.Request(
            base + "/jobs", data=body, method="POST",
            headers={"Authorization": "Bearer tok1"})
        out = json.loads(urllib.request.urlopen(req).read())
        assert out["status"] == "STARTED"
    finally:
        svc.stop()


def test_recovery_replays_statement_reading_a_view(tmp_path):
    """Advisor (medium): WAL replay ran before views were restored, and
    replay swallows errors — INSERT INTO t SELECT ... FROM v silently
    dropped its rows on recovery."""
    d = str(tmp_path / "store")
    s = SnappySession(data_dir=d)
    s.sql("CREATE TABLE src (a INT) USING column")
    s.sql("INSERT INTO src VALUES (10), (20)")
    s.sql("CREATE VIEW v AS SELECT a * 2 AS b FROM src")
    s.checkpoint()  # view lands in catalog.json; WAL tail starts empty
    s.sql("CREATE TABLE dst (b INT) USING column")
    s.sql("INSERT INTO dst SELECT b FROM v")   # journaled, reads the view
    assert s.sql("SELECT sum(b) FROM dst").rows()[0][0] == 60
    s.disk_store.close()

    s2 = SnappySession(data_dir=d)
    assert s2.sql("SELECT sum(b) FROM dst").rows()[0][0] == 60
    s2.disk_store.close()
