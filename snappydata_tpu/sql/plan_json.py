"""Logical-plan wire format: AST ⇄ JSON.

Plan-fragment shipping for distributed execution (ref: the lead ships
Catalyst plans to real executors, SparkSQLExecuteImpl.scala:75-109):
instead of re-rendering a rewritten plan to SQL text — which leaks
shapes the single-block renderer can't express (GROUPING SETS, window
partials, decorrelated semi/anti FROM trees) — the lead serializes the
UNRESOLVED logical plan and each server deserializes and executes it
through its normal session pipeline (analyze → optimize → compile).

Serialization is generic over the ast/types dataclasses: a node encodes
as {"_t": "ClassName", ...fields...}; sequences round-trip as tuples
(every ast child container is a tuple), dates/np-scalars get tagged
encodings. Only classes registered in `snappydata_tpu.sql.ast` /
`snappydata_tpu.types` deserialize — arbitrary type names are rejected
(the Flight surface is authenticated, but the decoder still refuses to
instantiate anything outside the AST namespace).
"""

from __future__ import annotations

import dataclasses
import datetime
from typing import Any

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.sql import ast


class PlanCodecError(ValueError):
    pass


def to_json(obj: Any):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, datetime.datetime):
        return {"_ts": obj.isoformat()}
    if isinstance(obj, datetime.date):
        return {"_d": obj.isoformat()}
    if isinstance(obj, (list, tuple)):
        return {"_seq": [to_json(v) for v in obj]}
    if dataclasses.is_dataclass(obj):
        cls = type(obj).__name__
        out = {"_t": cls}
        for f in dataclasses.fields(obj):
            out[f.name] = to_json(getattr(obj, f.name))
        return out
    raise PlanCodecError(f"cannot serialize {type(obj).__name__}")


def _resolve_class(name: str):
    cls = getattr(ast, name, None)
    if cls is None:
        cls = getattr(T, name, None)
    if cls is None or not (dataclasses.is_dataclass(cls)
                           or cls is T.Schema):
        raise PlanCodecError(f"unknown plan node type {name!r}")
    return cls


def from_json(obj: Any):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):  # bare list (shouldn't occur, but accept)
        return tuple(from_json(v) for v in obj)
    if isinstance(obj, dict):
        if "_seq" in obj:
            return tuple(from_json(v) for v in obj["_seq"])
        if "_d" in obj:
            return datetime.date.fromisoformat(obj["_d"])
        if "_ts" in obj:
            return datetime.datetime.fromisoformat(obj["_ts"])
        if "_t" in obj:
            cls = _resolve_class(obj["_t"])
            if cls is T.Schema:
                return T.Schema(from_json(obj["fields"]))
            kwargs = {k: from_json(v) for k, v in obj.items()
                      if k != "_t"}
            return cls(**kwargs)
    raise PlanCodecError(f"cannot deserialize {obj!r}")
