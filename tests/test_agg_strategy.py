"""Single-pass grouped aggregation: packed slot fusion, the backend-aware
reduction strategy table (ops/reduction.py), the group-index cache, and
the tiled scan's on-device partial merge.

Covers the perf-guard contracts the CI must hold:
- reduction dispatches per grouped query are O(1) in slot count (the
  old path issued one masked reduction per group per slot);
- tile partials merge on device (scan_tile_device_merges) and never take
  the per-tile host round trip when the group space is tile-aligned;
- unroll / scatter / matmul / pallas-interpret agree bit-for-bit on
  exactly-summable inputs across dtypes, null patterns, empty groups,
  and G around the 64-group unroll boundary;
- the count accumulator widens past the int32 row bound.
"""

import json
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.ops import reduction


@pytest.fixture
def props():
    p = config.global_properties()
    saved = (p.agg_reduce_strategy, p.gidx_cache_bytes,
             p.column_batch_rows, p.scan_tile_bytes)
    yield p
    (p.agg_reduce_strategy, p.gidx_cache_bytes,
     p.column_batch_rows, p.scan_tile_bytes) = saved


def _counter(name: str) -> int:
    return global_registry().counter(name)


# ---------------------------------------------------------------------
# strategy table + packed kernels (ops/reduction.py)
# ---------------------------------------------------------------------

def test_resolve_strategy_degrades_invalid_requests():
    # matmul refused for exact int sums and min/max, and past the
    # one-hot byte budget; unroll degrades to scatter past the boundary
    assert reduction.resolve_strategy(
        "matmul", "cpu", 8, 1000, "isum", jnp.int64) != "matmul"
    assert reduction.resolve_strategy(
        "matmul", "cpu", 8, 1000, "minmax", jnp.float64) != "matmul"
    huge_n = reduction.MATMUL_ONEHOT_MAX_BYTES  # n*G*8 >> budget
    assert reduction.resolve_strategy(
        "matmul", "cpu", 8, huge_n, "fsum", jnp.float64) == "scatter"
    assert reduction.resolve_strategy(
        "unroll", "cpu", reduction.UNROLL_MAX_SEGMENTS + 1, 1000,
        "fsum", jnp.float64) == "scatter"
    # auto: cpu float sums take the matmul (gemm) when the one-hot fits
    assert reduction.resolve_strategy(
        "auto", "cpu", 9, 100_000, "fsum", jnp.float64) == "matmul"
    # auto: tpu keeps the measured unroll in the dictionary regime
    assert reduction.resolve_strategy(
        "auto", "tpu", 9, 100_000, "fsum", jnp.float64) == "unroll"
    assert reduction.resolve_strategy(
        "auto", "tpu", 1000, 100_000, "fsum", jnp.float64) == "scatter"


@pytest.mark.parametrize("nseg", [1, 2, 63, 64, 65, 200])
@pytest.mark.parametrize("dtype", [np.float64, np.float32, np.int64])
def test_packed_strategies_bit_identical(nseg, dtype):
    """unroll / scatter / matmul produce bit-identical group results on
    exactly-summable values (integer-valued, so summation order cannot
    matter) across dtypes, null patterns, empty groups, and G around
    the 64-group unroll boundary."""
    rng = np.random.default_rng(nseg)
    n = 4096
    # leave the last segment (and, for nseg>2, segment 0) empty
    lo = 1 if nseg > 2 else 0
    gidx = jnp.asarray(rng.integers(lo, max(1, nseg - 1), n))
    vals = rng.integers(-50, 50, (n, 3)).astype(dtype)
    mask = rng.random(n) < 0.8  # null pattern
    masked = np.where(mask[:, None], vals, 0).astype(dtype)
    cols = [jnp.asarray(masked[:, j]) for j in range(3)]
    outs = {}
    for strat in ("unroll", "scatter", "matmul"):
        eff = reduction.resolve_strategy(
            strat, "cpu", nseg, n, "isum" if dtype == np.int64 else "fsum",
            jnp.dtype(dtype))
        outs[strat] = np.asarray(
            reduction.packed_sum(cols, gidx, nseg, eff))
    assert (outs["unroll"] == outs["scatter"]).all()
    assert (outs["unroll"] == outs["matmul"]).all()
    # oracle
    for g in range(nseg):
        sel = (np.asarray(gidx) == g) & mask
        np.testing.assert_array_equal(
            outs["scatter"][g], vals[sel].sum(axis=0).astype(dtype)
            if sel.any() else np.zeros(3, dtype))
    # min/max: unroll vs scatter, empty groups keep the identity filler
    mn_fill = np.where(mask[:, None], vals,
                       reduction._extreme_of(jnp.dtype(dtype), True))
    mm_cols = [jnp.asarray(mn_fill.astype(dtype)[:, j])
               for j in range(3)]
    for kind in ("min", "max"):
        a = np.asarray(reduction.packed_minmax(
            kind, mm_cols, gidx, nseg,
            "unroll" if nseg <= 64 else "scatter"))
        b = np.asarray(reduction.packed_minmax(
            kind, mm_cols, gidx, nseg, "scatter"))
        assert (a == b).all()


def test_pallas_interpret_matches_packed_sums():
    """The pallas-interpret kernel's f64-combined Kahan sums agree
    bit-for-bit with the packed families on exactly-summable f32 data."""
    from snappydata_tpu.ops.pallas_group import grouped_reduce

    rng = np.random.default_rng(3)
    n, G = 30_000, 7
    gidx = rng.integers(0, G - 1, n)  # group G-1 empty
    v = rng.integers(0, 1000, n).astype(np.float32)
    m = rng.random(n) < 0.9
    pal = grouped_reduce(
        [("sum", jnp.asarray(v), jnp.asarray(m)),
         ("count", None, jnp.asarray(m))], jnp.asarray(gidx), G)
    col = jnp.asarray(np.where(m, v, 0).astype(np.float64))
    for strat in ("unroll", "scatter", "matmul"):
        res = np.asarray(reduction.packed_sum(
            [col], jnp.asarray(gidx), G, strat))[:, 0]
        assert (np.asarray(pal[0]) == res).all(), strat
    cnt = np.asarray(reduction.packed_sum(
        [jnp.asarray(m.astype(np.int32))], jnp.asarray(gidx), G,
        "scatter")).astype(np.int64)[:, 0]
    assert (np.asarray(pal[1]) == cnt).all()


def test_matmul_nonfinite_values_stay_group_isolated(props):
    """A NaN/Inf value must poison ONLY its own group: the matmul
    strategy's finite-guard falls back to the isolating scatter."""
    props.agg_reduce_strategy = "matmul"
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE nf (k STRING, v DOUBLE) USING column")
    s.insert_arrays("nf", [
        np.array(["a", "a", "b", "b"], dtype=object),
        np.array([1.0, np.nan, 2.0, 3.0])])
    rows = s.sql("SELECT k, sum(v) FROM nf GROUP BY k ORDER BY k").rows()
    assert rows[0][0] == "a" and np.isnan(rows[0][1])
    assert rows[1] == ("b", 5.0)
    s.stop()


# ---------------------------------------------------------------------
# engine-level equivalence + knob behavior
# ---------------------------------------------------------------------

def _mk_session():
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE t (k STRING, b BOOLEAN, v DOUBLE, i BIGINT) "
          "USING column")
    rng = np.random.default_rng(11)
    n = 20_000
    k = rng.choice(np.array(["a", "b", "c", "d", "e"], dtype=object), n)
    b = rng.random(n) < 0.5
    v = rng.integers(0, 10_000, n).astype(np.float64)  # exactly summable
    i = rng.integers(-100, 100, n, dtype=np.int64)
    nulls = rng.random(n) < 0.2
    s.catalog.describe("t").data.insert_arrays(
        [k, b, v, i], nulls=[None, None, nulls, None])
    return s


ENGINE_Q = ("SELECT k, b, count(*), count(v), sum(v), avg(v), min(v), "
            "max(v), sum(i), stddev(v) FROM t GROUP BY k, b "
            "ORDER BY k, b")


def test_engine_strategies_identical_and_respecialize(props):
    """All strategies return identical rows through the engine, and the
    knob re-specializes via the static key — no plan-cache clear."""
    s = _mk_session()
    props.agg_reduce_strategy = "auto"
    base = s.sql(ENGINE_Q).rows()
    assert len(base) == 10
    for strat in ("unroll", "scatter", "matmul"):
        props.agg_reduce_strategy = strat
        before = _counter(f"agg_strategy_{strat}")
        got = s.sql(ENGINE_Q).rows()
        for a, b in zip(got, base):
            # integer-valued doubles: sums are exact under any order, so
            # equality is exact (stddev divides — compare approx)
            assert a[:9] == b[:9], (strat, a, b)
            assert a[9] == pytest.approx(b[9], rel=1e-12)
        assert _counter(f"agg_strategy_{strat}") > before, \
            f"{strat} was not picked despite the knob"
    s.stop()


def test_reduce_passes_constant_in_slot_count(props):
    """CI perf guard: fused reduction dispatches are O(1) in the number
    of aggregate slots — a wide aggregate packs into the same per-family
    passes as a narrow one."""
    props.agg_reduce_strategy = "auto"
    s = SnappySession(catalog=Catalog())
    decls = ", ".join(f"c{j} DOUBLE" for j in range(8))
    s.sql(f"CREATE TABLE w (k STRING, {decls}) USING column")
    rng = np.random.default_rng(5)
    n = 5000
    s.insert_arrays("w", [
        rng.choice(np.array(["x", "y", "z"], dtype=object), n)]
        + [np.round(rng.random(n) * 100, 2) for _ in range(8)])

    def passes_of(q):
        s.sql(q)  # warm/compile
        c0 = _counter("agg_reduce_passes")
        s.sql(q)
        return _counter("agg_reduce_passes") - c0

    narrow = passes_of(
        "SELECT k, sum(c0), min(c0), count(*) FROM w GROUP BY k")
    sums = ", ".join(f"sum(c{j})" for j in range(8))
    avgs = ", ".join(f"avg(c{j})" for j in range(8))
    mins = ", ".join(f"min(c{j})" for j in range(4))
    wide = passes_of(
        f"SELECT k, {sums}, {avgs}, {mins}, count(*) FROM w GROUP BY k")
    assert narrow > 0
    assert wide == narrow, (wide, narrow)
    s.stop()


def test_count_accumulator_widens_past_int32(monkeypatch):
    """Regression for the int32 count accumulator: jnp.sum of int32 ones
    keeps int32 and could wrap past 2**31 rows.  The packed count dtype
    now widens by an explicit row-count bound (N is a static shape), and
    counts riding the f64 matmul pack are exact below 2**53."""
    assert reduction.count_pack_dtype(2 ** 31 - 1) == jnp.int32
    assert reduction.count_pack_dtype(2 ** 31) == jnp.int64
    assert reduction.count_pack_dtype(2 ** 40) == jnp.int64
    # behavioral check at a shrunken bound: with the threshold forced
    # tiny, the engine must pick int64 and still count exactly
    monkeypatch.setattr(reduction, "COUNT_I32_MAX_ROWS", 100)
    assert reduction.count_pack_dtype(101) == jnp.int64
    gidx = jnp.asarray(np.zeros(500, dtype=np.int64))
    ones = jnp.asarray(np.ones(500, dtype=np.int32)).astype(
        reduction.count_pack_dtype(500))
    out = reduction.packed_sum([ones], gidx, 2, "scatter")
    assert out.dtype == jnp.int64
    assert int(out[0, 0]) == 500


def test_gidx_cache_hits_and_invalidation(props):
    """Repeated dashboard queries skip group-index recomputation; a
    mutation rotates the bind identity and invalidates the entry."""
    props.agg_reduce_strategy = "auto"
    s = _mk_session()
    q = "SELECT k, count(*) FROM t GROUP BY k ORDER BY k"
    s.sql(q)  # compile + first run (miss)
    h0, m0 = _counter("gidx_cache_hits"), _counter("gidx_cache_misses")
    s.sql(q)
    s.sql(q)
    assert _counter("gidx_cache_hits") == h0 + 2
    assert _counter("gidx_cache_misses") == m0
    s.sql("INSERT INTO t VALUES ('a', true, 1.0, 1)")
    rows = s.sql(q).rows()
    assert _counter("gidx_cache_misses") == m0 + 1
    assert sum(r[1] for r in rows) == 20_001
    # disabling the cache budget bypasses the two-phase split entirely
    props.gidx_cache_bytes = 0
    h1 = _counter("gidx_cache_hits")
    m1 = _counter("gidx_cache_misses")
    assert s.sql(q).rows() == rows
    assert _counter("gidx_cache_hits") == h1
    assert _counter("gidx_cache_misses") == m1
    s.stop()


# ---------------------------------------------------------------------
# tiled scan: on-device merge + REST surface
# ---------------------------------------------------------------------

def test_tile_merges_stay_on_device(props):
    """CI perf guard: a tile-aligned grouped aggregate merges its [G]
    partials on device — no per-tile host round trip (the host-merge
    counter must not move)."""
    props.column_batch_rows = 256
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE big (k STRING, v DOUBLE) USING column")
    rng = np.random.default_rng(9)
    n = 4096
    k = rng.choice(np.array(["a", "b", "c"], dtype=object), n)
    v = rng.integers(0, 1000, n).astype(np.float64)
    s.catalog.describe("big").data.insert_arrays([k, v])
    q = "SELECT k, count(*), sum(v), min(v) FROM big GROUP BY k ORDER BY k"
    untiled = s.sql(q).rows()
    props.scan_tile_bytes = 4 * 256 * 16
    t0, d0, h0 = (_counter("scan_tiles"),
                  _counter("scan_tile_device_merges"),
                  _counter("scan_tile_host_merges"))
    got = s.sql(q).rows()
    tiles = _counter("scan_tiles") - t0
    assert tiles > 1, "expected a multi-tile pass"
    assert _counter("scan_tile_device_merges") - d0 == tiles - 1
    assert _counter("scan_tile_host_merges") == h0
    assert got == untiled
    # a direct numeric key now groups through its table-global value
    # domain (vdict): the group-index space is data-independent across
    # tiles, so the merge stays on device too — with identical values
    q2 = "SELECT v, count(*) FROM big GROUP BY v ORDER BY v LIMIT 3"
    props.scan_tile_bytes = 0
    flat2 = s.sql(q2).rows()
    props.scan_tile_bytes = 4 * 256 * 16
    h1 = _counter("scan_tile_host_merges")
    assert s.sql(q2).rows() == flat2
    assert _counter("scan_tile_host_merges") == h1
    # an EXPRESSION key has no table-global domain: generic hash path,
    # host merge, exactly once
    q3 = "SELECT v + 0.5, count(*) FROM big GROUP BY v + 0.5 LIMIT 3"
    h2 = _counter("scan_tile_host_merges")
    d1 = _counter("scan_tile_device_merges")
    s.sql(q3)
    assert _counter("scan_tile_host_merges") == h2 + 1
    assert _counter("scan_tile_device_merges") == d1
    s.stop()


def test_rest_scan_endpoint(props):
    from snappydata_tpu.cluster.rest import RestService
    from snappydata_tpu.observability.stats_service import \
        TableStatsService

    s = _mk_session()
    s.sql("SELECT k, count(*) FROM t GROUP BY k")
    svc = RestService(s, TableStatsService(s.catalog), port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/status/api/v1/scan",
                timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["agg_reduce_strategy"] == \
            props.get("agg_reduce_strategy")
        assert body["agg_reduce_passes"] > 0
        assert isinstance(body["agg_strategies"], dict) \
            and body["agg_strategies"]
        assert {"gidx_cache_hits", "scan_tiles",
                "scan_tile_device_merges",
                "scan_tile_prefetch_overlap"} <= set(body)
        # dashboard renders the Aggregation section
        with urllib.request.urlopen(
                f"http://{svc.host}:{svc.port}/dashboard",
                timeout=5) as resp:
            html = resp.read().decode()
        assert "Aggregation engine" in html
    finally:
        svc.stop()
        s.stop()
