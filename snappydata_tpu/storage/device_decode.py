"""In-trace device decode: encoded bytes cross the PCIe/DMA link, the
decode to capacity-row plates happens on the accelerator.

Reference parity: the reference decodes dictionary/RLE/delta INSIDE the
generated scan code at batch-read time (ColumnTableScan.scala:684
genCodeColumnBuffer), so encodings save memory end to end. Here the
equivalents are vectorized XLA programs applied at cold bind:

* RUN_LENGTH: upload (run_values [R], run_end_offsets [R]) and expand to
  the plate with a vmapped searchsorted-gather — the batched form of
  `jnp.repeat(values, runs, total_repeat_length=cap)`. Transfer shrinks
  from cap×itemsize to 2×R×itemsize (R = #runs).
* BOOLEAN_BITSET: upload the packed bits (uint8 [cap/8]) and unpack with
  shift/mask ops — an 8× transfer reduction.
* VALUE_DICT: low-cardinality numeric columns upload uint8/uint16 codes
  [cap] plus the tiny value dictionary [D] and gather on device — an
  itemsize× (≥4×) transfer reduction. This is the encoding the default
  TPC-H scan engages (l_quantity/l_discount/l_tax are 50/11/9 distinct
  f64 values), so the bench's device_decode counters are nonzero on the
  stock workload.

Compressed-domain execution (r06) goes one step further: under
`scan_compressed_domain` the plates THEMSELVES stay encoded in HBM
(CodePlate/RlePlate/BitPlate below), predicates run on codes/runs, and
values decode lazily in-trace only where consumed — see the builders
and in-trace consumers at the bottom of this module.

Dictionary string columns need no device decode: their int32 codes ARE
the on-device representation (group-by/join run on codes). Batches with
update deltas take the host decode path — the delta merge is host-side
state.

Lanes past a batch's last run decode to the final run's value rather
than zero; every consumer masks by the table validity plate, so padding
content is unobservable (same contract as the zero padding of host
decode).
"""

from __future__ import annotations

import functools
import weakref
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from snappydata_tpu.utils import locks

# bind-transfer accounting (powers the bench/device-decode metric and the
# tests' "compressed bytes actually crossed the link" assertion).
# batches_code_bound counts batches whose column stayed RESIDENT in the
# compressed domain (no decoded plate in HBM at all — the r06
# compressed-domain execution path), a subset of batches_device_decoded.
_counters: Dict[str, int] = {"bytes_encoded": 0, "bytes_decoded_equiv": 0,
                             "batches_device_decoded": 0,
                             "batches_code_bound": 0}


# --- compressed-domain column plates --------------------------------------
# A code-domain bind stores one of these in DeviceTable.columns[ci]
# instead of a decoded [B, cap] plate.  They are NamedTuples, so they ride
# the jit boundary as pytrees, survive the bind-time batch-skip gather
# (field-wise jnp.take along axis 0), and make_ctx recognizes them
# structurally at trace time — no side-channel metadata needed.

class CodePlate(NamedTuple):
    """VALUE_DICT column resident in the code domain.
    codes: [B, cap] uint8/uint16 device array;
    dicts: [B, D] device array, each row SORTED ascending and padded by
    repeating its last value (keeps searchsorted semantics exact)."""

    codes: object
    dicts: object


class RlePlate(NamedTuple):
    """RUN_LENGTH column resident as runs.
    values: [B, R] run values; ends: [B, R] int32 cumulative run end
    offsets (padded runs repeat the last end)."""

    values: object
    ends: object


class BitPlate(NamedTuple):
    """BOOLEAN_BITSET column resident as packed bits [B, ceil(cap/8)]."""

    packed: object


def counters() -> Dict[str, int]:
    return dict(_counters)


def reset_counters() -> None:
    for k in _counters:
        _counters[k] = 0


@functools.partial(jax.jit, static_argnames=("cap",))
def _rle_expand(values: jnp.ndarray, ends: jnp.ndarray, cap: int):
    """values/ends: [N, R] (R padded; unused runs carry end=last_end).
    Returns [N, cap] plates: lane j takes values[searchsorted(ends, j,
    'right')] — the run whose half-open [prev_end, end) interval holds j.
    """
    pos = jnp.arange(cap, dtype=ends.dtype)

    def one(vals, end):
        seg = jnp.searchsorted(end, pos, side="right")
        seg = jnp.minimum(seg, vals.shape[0] - 1)
        return vals[seg]

    return jax.vmap(one)(values, ends)


@functools.partial(jax.jit, static_argnames=("cap",))
def _bitset_expand(packed: jnp.ndarray, cap: int):
    """packed: [N, ceil(cap/8)] uint8 (LSB-first, numpy packbits
    bitorder='little') → bool [N, cap]."""
    idx = jnp.arange(cap)
    byte = packed[:, idx // 8]
    return ((byte >> (idx % 8).astype(jnp.uint8)) & 1).astype(jnp.bool_)


def rle_views_to_plate(rle_cols, cap: int, dt) -> jnp.ndarray:
    """Stack N encoded RLE columns into device plates [N, cap].

    `rle_cols`: list of EncodedColumn with .data (run values) and .runs
    (run lengths). Returns the decoded [N, cap] device array."""
    r_max = max(1, max(len(c.data) for c in rle_cols))
    n = len(rle_cols)
    vals = np.zeros((n, r_max), dtype=dt)
    ends = np.zeros((n, r_max), dtype=np.int64)
    for i, c in enumerate(rle_cols):
        r = len(c.data)
        vals[i, :r] = c.data
        e = np.cumsum(c.runs, dtype=np.int64)
        ends[i, :r] = e
        if r < r_max:
            vals[i, r:] = vals[i, r - 1] if r else 0
            ends[i, r:] = e[-1] if r else 0
        _counters["bytes_encoded"] += int(vals[i].nbytes + ends[i].nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * vals.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
    return _rle_expand(jnp.asarray(vals), jnp.asarray(ends), cap)


@jax.jit
def _valdict_expand(codes: jnp.ndarray, dicts: jnp.ndarray):
    """codes: [N, cap] uint8; dicts: [N, D] (D padded per call).  Lane j
    of row i takes dicts[i, codes[i, j]] — a per-batch device gather."""
    return jnp.take_along_axis(dicts, codes.astype(jnp.int32), axis=1)


def _valdict_code_dtype(vd_cols) -> np.dtype:
    """Narrowest common code dtype across the stacked batches (uint16
    VALUE_DICT widening: per-batch code dtypes can mix u8/u16)."""
    return np.dtype(np.uint16) if any(
        c.data.dtype.itemsize > 1 for c in vd_cols) else np.dtype(np.uint8)


def valdict_views_to_plate(vd_cols, cap: int, dt) -> jnp.ndarray:
    """Stack N value-dict columns into decoded plates [N, cap]: the
    uint8/uint16 codes and the (padded) dictionaries cross the link, the
    values-gather runs in-trace."""
    d_max = max(1, max(len(c.dictionary) for c in vd_cols))
    n = len(vd_cols)
    codes = np.zeros((n, cap), dtype=_valdict_code_dtype(vd_cols))
    dicts = np.zeros((n, d_max), dtype=dt)
    for i, c in enumerate(vd_cols):
        codes[i, :c.data.shape[0]] = c.data
        d = np.asarray(c.dictionary, dtype=dt)
        dicts[i, :d.shape[0]] = d
        _counters["bytes_encoded"] += int(c.data.nbytes + d.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * dicts.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
    return _valdict_expand(jnp.asarray(codes), jnp.asarray(dicts))


def bitset_views_to_plate(bit_cols, cap: int) -> jnp.ndarray:
    """Stack N boolean-bitset columns into decoded bool plates [N, cap]."""
    nbytes = (cap + 7) // 8
    n = len(bit_cols)
    packed = np.zeros((n, nbytes), dtype=np.uint8)
    for i, c in enumerate(bit_cols):
        raw = np.asarray(c.data, dtype=np.uint8)
        packed[i, :raw.shape[0]] = raw
        _counters["bytes_encoded"] += int(raw.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap)
        _counters["batches_device_decoded"] += 1
    return _bitset_expand(jnp.asarray(packed), cap)


# ==========================================================================
# Compressed-domain binds: the column STAYS encoded in HBM (CodePlate /
# RlePlate / BitPlate in DeviceTable.columns) and every consumer either
# works on the encoded form directly (code-threshold predicates, per-run
# predicates) or decodes lazily IN-TRACE, where XLA fuses the expansion
# into the consuming kernel — a decoded capacity-row plate never exists.
# ==========================================================================

def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def compressed_fallback(reason: str, n: int = 1, table=None) -> None:
    """Count a decode-first reroute (a column that did NOT bind in the
    compressed domain), itemized by reason so every reroute is visible
    on the scan dashboard: compressed_fallback_<reason> + total.

    With `table` (the ColumnTableData the reroute happened on) the count
    also lands in a per-table registry — the background compactor's
    trigger signal (storage/compact.py picks tables whose FOLDABLE
    reasons keep firing) and the per-table triage view that
    stats_service.encoding_mix surfaces."""
    from snappydata_tpu.observability.metrics import global_registry

    reg = global_registry()
    reg.inc("compressed_fallbacks", n)
    reg.inc("compressed_fallback_" + reason, n)
    if table is not None:
        with _table_fb_lock:
            d = _table_fallbacks.setdefault(table, {})
            d[reason] = d.get(reason, 0) + n


# per-table fallback tallies: weak keys so a dropped table takes its
# tally with it.  Guarded by a declared LEAF lock (nothing is acquired
# under it), read by the compactor and the stats service.
_table_fallbacks: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_table_fb_lock = locks.named_lock("storage.table_fallbacks")


def table_fallbacks(table) -> Dict[str, int]:
    """Per-table compressed-fallback counts since the last reset."""
    with _table_fb_lock:
        return dict(_table_fallbacks.get(table, ()))


def reset_table_fallbacks(table) -> None:
    """Zero a table's tally — the compactor calls this after a rewrite
    pass so the next window measures only post-compaction reroutes."""
    with _table_fb_lock:
        _table_fallbacks.pop(table, None)


def code_plates(vd_cols, b: int, cap: int, dt, place=jnp.asarray):
    """VALUE_DICT views → a resident CodePlate plus the HOST-side sorted
    dictionary stack the bind-time sarg skipper reads.

    Returns (CodePlate, host_dicts [b, Dp] float64, sizes [b] int64).
    Dictionary rows pad by REPEATING the last value so each row stays
    sorted — the property the in-trace searchsorted threshold
    translation and the host membership probe both rely on.
    `place` is the bind's device-placement hook: under a mesh the plate
    leaves shard on the batch axis like decoded plates (codes AND
    per-batch dictionaries are [b, ...]-leading)."""
    d_pad = _next_pow2(max(1, max(len(c.dictionary) for c in vd_cols)))
    codes = np.zeros((b, cap), dtype=_valdict_code_dtype(vd_cols))
    dicts = np.zeros((b, d_pad), dtype=dt)
    host = np.zeros((b, d_pad), dtype=np.float64)
    sizes = np.zeros(b, dtype=np.int64)
    for i, c in enumerate(vd_cols):
        codes[i, :c.data.shape[0]] = c.data
        d = np.asarray(c.dictionary, dtype=dt)
        dicts[i, :d.shape[0]] = d
        if d.shape[0] and d.shape[0] < d_pad:
            dicts[i, d.shape[0]:] = d[-1]
        host[i, :d.shape[0]] = np.asarray(c.dictionary, dtype=np.float64)
        if d.shape[0] and d.shape[0] < d_pad:
            host[i, d.shape[0]:] = host[i, d.shape[0] - 1]
        sizes[i] = d.shape[0]
        _counters["bytes_encoded"] += int(c.data.nbytes + d.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * d.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
        _counters["batches_code_bound"] += 1
    return (CodePlate(place(codes), place(dicts)),
            host, sizes)


def rle_plates(rle_cols, b: int, cap: int, dt,
               place=jnp.asarray) -> RlePlate:
    """RUN_LENGTH views → a resident RlePlate (run values + cumulative
    end offsets, O(runs) bytes in HBM instead of O(cap))."""
    r_pad = _next_pow2(max(1, max(len(c.data) for c in rle_cols)))
    vals = np.zeros((b, r_pad), dtype=dt)
    ends = np.zeros((b, r_pad), dtype=np.int64)
    for i, c in enumerate(rle_cols):
        r = len(c.data)
        vals[i, :r] = c.data
        e = np.cumsum(c.runs, dtype=np.int64)
        ends[i, :r] = e
        if r and r < r_pad:
            vals[i, r:] = vals[i, r - 1]
            ends[i, r:] = e[-1]
        _counters["bytes_encoded"] += int(
            c.data.nbytes + np.asarray(c.runs).nbytes)
        _counters["bytes_decoded_equiv"] += int(cap * vals.dtype.itemsize)
        _counters["batches_device_decoded"] += 1
        _counters["batches_code_bound"] += 1
    return RlePlate(place(vals), place(ends))


def bit_plates(bit_cols, b: int, cap: int, place=jnp.asarray) -> BitPlate:
    """BOOLEAN_BITSET views → a resident BitPlate (8x fewer HBM bytes)."""
    nbytes = (cap + 7) // 8
    packed = np.zeros((b, nbytes), dtype=np.uint8)
    for i, c in enumerate(bit_cols):
        raw = np.asarray(c.data, dtype=np.uint8)
        packed[i, :raw.shape[0]] = raw
        _counters["bytes_encoded"] += int(raw.nbytes)
        _counters["bytes_decoded_equiv"] += int(cap)
        _counters["batches_device_decoded"] += 1
        _counters["batches_code_bound"] += 1
    return BitPlate(place(packed))


# --- in-trace consumers ---------------------------------------------------

def code_values(plate: CodePlate) -> jnp.ndarray:
    """Lazy decode of a CodePlate: a per-batch dictionary gather that XLA
    fuses into whatever consumes the values (the fused
    decode+filter+aggregate form of the default scan)."""
    return jnp.take_along_axis(plate.dicts,
                               plate.codes.astype(jnp.int32), axis=1)


def rle_values(plate: RlePlate, cap: int) -> jnp.ndarray:
    """Lazy in-trace expansion of an RlePlate to [B, cap] values."""
    return _rle_expand(plate.values, plate.ends, cap)


def bit_values(plate: BitPlate, cap: int) -> jnp.ndarray:
    """Lazy in-trace unpack of a BitPlate to [B, cap] bools."""
    return _bitset_expand(plate.packed, cap)


def code_cmp_mask(op: str, plate: CodePlate, lit) -> jnp.ndarray:
    """Code-domain lowering of `column OP literal` over a CodePlate:
    the literal translates to per-batch code thresholds through the
    SORTED dictionaries (one searchsorted per batch, O(B log D)) and the
    comparison runs on the small integer codes — the decoded plate never
    materializes and per-row work touches 1-2 bytes, not 8.

    Exactness: the dictionary and the literal are both promoted to
    their common compare dtype first, so boundary behavior is
    bit-identical to comparing the decoded values (f32 dictionaries vs
    f64 literals compare in f64, exactly like the decoded plate would).
    Out-of-dictionary equality literals yield a constant-false mask
    (code -1 matches nothing); NaN literals follow IEEE semantics
    (every comparison false except !=)."""
    codes = plate.codes.astype(jnp.int32)
    cd = jnp.result_type(plate.dicts.dtype, jnp.asarray(lit).dtype)
    d = plate.dicts.astype(cd)
    v = jnp.asarray(lit).astype(cd)
    if op in ("=", "!="):
        pos = jax.vmap(
            lambda row: jnp.searchsorted(row, v, side="left"))(d)
        posc = jnp.clip(pos, 0, d.shape[1] - 1).astype(jnp.int32)
        hit = jnp.take_along_axis(d, posc[:, None], axis=1)[:, 0] == v
        code_eq = jnp.where(hit, posc, -1)
        return codes == code_eq[:, None] if op == "=" \
            else codes != code_eq[:, None]
    # values >= lit  <=>  code >= searchsorted(dict, lit, left); the
    # right-side variants shift the threshold past equal values
    side = "left" if op in (">=", "<") else "right"
    pos = jax.vmap(
        lambda row: jnp.searchsorted(row, v, side=side))(d)
    pos = pos.astype(jnp.int32)
    m = codes >= pos[:, None] if op in (">=", ">") \
        else codes < pos[:, None]
    if op in ("<", "<=") and jnp.issubdtype(cd, jnp.floating):
        # x < NaN is False, but NaN sorts past every dictionary entry
        # (threshold = D → all codes pass) — guard explicitly
        m = m & ~jnp.isnan(v)
    return m


def rle_cmp_mask(fn, plate: RlePlate, lit, cap: int) -> jnp.ndarray:
    """Run-arithmetic filter over an RlePlate: evaluate the predicate
    per RUN (O(runs) compares) and expand the boolean run mask — the
    full-width value plate is never produced."""
    run_mask = fn(plate.values, lit)
    return _rle_expand(run_mask, plate.ends, cap)


def rle_expand_runs(run_array: jnp.ndarray, ends: jnp.ndarray,
                    cap: int) -> jnp.ndarray:
    """Expand any per-run [B, R] array (values, boolean run masks) to
    row space [B, cap] over the given cumulative end offsets."""
    return _rle_expand(run_array, ends, cap)


def rle_run_lengths(ends: jnp.ndarray) -> jnp.ndarray:
    """Per-run lengths from cumulative end offsets (padded runs repeat
    the last end, so their length is exactly 0)."""
    prev = jnp.concatenate(
        [jnp.zeros_like(ends[:, :1]), ends[:, :-1]], axis=1)
    return ends - prev


def rle_masked_sum_count(plate: RlePlate, run_mask: jnp.ndarray):
    """O(runs) filter+aggregate arithmetic: with a per-run boolean mask,
    count = Σ len·mask and sum = Σ value·len·mask — multiply values by
    run lengths instead of touching O(rows) lanes.  Valid only when the
    surviving row set is run-aligned (no row-level deletes inside runs —
    the code-domain bind already excludes delta-bearing batches).

    Status: a TESTED building block (equivalence-asserted against the
    expanded path in tests/test_compressed_domain.py), not yet on the
    default aggregate path — the packed-family reduction consumes row
    plates with row-level validity, so wiring this in needs a
    run-alignment proof over the whole filter; the engine's WIRED run
    arithmetic today is the per-run predicate lane (rle_cmp_mask)."""
    lens = rle_run_lengths(plate.ends)
    lm = jnp.where(run_mask, lens, 0)
    count = jnp.sum(lm)
    total = jnp.sum(plate.values.astype(jnp.float64) * lm)
    return total, count
