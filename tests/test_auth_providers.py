"""Pluggable authentication: BUILTIN and LDAP providers end-to-end.

Reference behavior: `auth-provider=BUILTIN|LDAP` with `auth-ldap-server`
/ `auth-ldap-search-base` (ClusterManagerLDAPTestBase.scala:97-102);
network servers authenticate principals and statements run under the
principal's session so GRANT/REVOKE applies (SecurityUtils).

The LDAP tests run against an in-process mini LDAP server that speaks
genuine BER over TCP — binds and single-equality searches — so the
pure-python client in `security/auth.py` is exercised on real sockets.
"""

import socket
import threading

import pytest

from snappydata_tpu import SnappySession
from snappydata_tpu.config import Properties
from snappydata_tpu.security import (
    BuiltinAuthProvider,
    LdapAuthProvider,
    make_provider,
)
from snappydata_tpu.security.auth import (
    LDAP_AUTH_SIMPLE,
    LDAP_BIND_REQUEST,
    LDAP_BIND_RESPONSE,
    LDAP_SEARCH_DONE,
    LDAP_SEARCH_ENTRY,
    LDAP_SEARCH_REQUEST,
    LDAP_UNBIND_REQUEST,
    RESULT_INVALID_CREDENTIALS,
    RESULT_SUCCESS,
    ber,
    ber_children,
    ber_int,
    ber_read,
    escape_dn_value,
    read_ber_message,
)


# ---------------------------------------------------------------------------
# BER codec
# ---------------------------------------------------------------------------


def test_ber_roundtrip():
    for payload in (b"", b"x", b"a" * 127, b"b" * 128, b"c" * 70000):
        enc = ber(0x04, payload)
        tag, content, off = ber_read(enc)
        assert (tag, content, off) == (0x04, payload, len(enc))
    for v in (0, 1, 3, 127, 128, 255, 256, -1, 49):
        tag, content, _ = ber_read(ber_int(v))
        assert tag == 0x02
        assert int.from_bytes(content, "big", signed=True) == v


def test_escape_dn_value():
    assert escape_dn_value("alice") == "alice"
    assert escape_dn_value("a,b=c") == "a\\,b\\=c"
    assert escape_dn_value(" lead") == "\\ lead"


# ---------------------------------------------------------------------------
# BUILTIN
# ---------------------------------------------------------------------------


def test_builtin_plain_and_hashed():
    p = BuiltinAuthProvider({
        "alice": "secret",
        "bob": BuiltinAuthProvider.hash_password("hunter2")})
    assert p.authenticate("alice", "secret")
    assert p.authenticate("ALICE", "secret")   # user names fold case
    assert not p.authenticate("alice", "wrong")
    assert not p.authenticate("alice", "")
    assert p.authenticate("bob", "hunter2")
    assert not p.authenticate("bob", "hunter3")
    assert not p.authenticate("carol", "x")


def test_make_provider_from_conf():
    conf = Properties()
    assert make_provider(conf) is None
    # SET-style (dash) keys normalize to the same entry
    conf.set("auth-provider", "BUILTIN")
    conf.set("auth_builtin_users", "alice:pw1,bob:pw2")
    p = make_provider(conf)
    assert p.authenticate("alice", "pw1") and p.authenticate("bob", "pw2")
    assert not p.authenticate("alice", "pw2")
    conf.set("auth-provider", "nosuch")
    with pytest.raises(ValueError, match="unknown auth_provider"):
        make_provider(conf)


# ---------------------------------------------------------------------------
# Mini LDAP server
# ---------------------------------------------------------------------------


class MiniLdapServer:
    """Just enough LDAPv3 to test the client: simple bind against a
    dn→password table, single-equality subtree search over uid→dn."""

    def __init__(self, passwords, uids=None, allow_anonymous=True):
        self.passwords = passwords        # dn (lowercased) -> password
        self.uids = uids or {}            # uid -> dn
        self.allow_anonymous = allow_anonymous
        self.binds = []                   # observed (dn, password)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        authed = False
        try:
            while True:
                _, content = read_ber_message(conn)
                children = ber_children(content)
                msg_id = int.from_bytes(children[0][1], "big", signed=True)
                op_tag, op_body = children[1]
                if op_tag == LDAP_BIND_REQUEST:
                    parts = ber_children(op_body)
                    dn = parts[1][1].decode("utf-8")
                    assert parts[2][0] == LDAP_AUTH_SIMPLE
                    password = parts[2][1].decode("utf-8")
                    self.binds.append((dn, password))
                    if dn == "" and password == "":
                        code = RESULT_SUCCESS if self.allow_anonymous \
                            else RESULT_INVALID_CREDENTIALS
                        authed = self.allow_anonymous
                    elif self.passwords.get(dn.lower()) == password \
                            and password != "":
                        code, authed = RESULT_SUCCESS, True
                    else:
                        code, authed = RESULT_INVALID_CREDENTIALS, False
                    conn.sendall(ber(0x30, ber_int(msg_id) + ber(
                        LDAP_BIND_RESPONSE,
                        ber_int(code, 0x0A) + ber(0x04, b"") +
                        ber(0x04, b""))))
                elif op_tag == LDAP_SEARCH_REQUEST:
                    parts = ber_children(op_body)
                    filt_tag, filt = parts[6]
                    assert filt_tag == 0xA3, "equalityMatch expected"
                    attr, value = [b.decode("utf-8")
                                   for _, b in ber_children(filt)]
                    dn = self.uids.get(value) if authed and attr == "uid" \
                        else None
                    out = b""
                    if dn is not None:
                        out += ber(0x30, ber_int(msg_id) + ber(
                            LDAP_SEARCH_ENTRY,
                            ber(0x04, dn.encode()) + ber(0x30, b"")))
                    out += ber(0x30, ber_int(msg_id) + ber(
                        LDAP_SEARCH_DONE,
                        ber_int(RESULT_SUCCESS, 0x0A) + ber(0x04, b"") +
                        ber(0x04, b"")))
                    conn.sendall(out)
                elif op_tag == LDAP_UNBIND_REQUEST:
                    return
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            conn.close()


DIRECTORY = {
    "uid=alice,ou=people,dc=example,dc=com": "wonderland",
    "uid=bob,ou=people,dc=example,dc=com": "builder",
    "cn=admin,dc=example,dc=com": "adminpw",
}
UIDS = {
    "alice": "uid=alice,ou=people,dc=example,dc=com",
    "bob": "uid=bob,ou=people,dc=example,dc=com",
}


@pytest.fixture()
def ldap_server():
    server = MiniLdapServer(DIRECTORY, UIDS)
    yield server
    server.close()


def test_ldap_template_bind(ldap_server):
    p = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        user_dn_template="uid={user},ou=people,dc=example,dc=com")
    assert p.authenticate("alice", "wonderland")
    assert p.authenticate("bob", "builder")
    assert not p.authenticate("alice", "builder")
    assert not p.authenticate("mallory", "x")
    # RFC 4513: empty password must be refused client-side, no bind sent
    n_binds = len(ldap_server.binds)
    assert not p.authenticate("alice", "")
    assert len(ldap_server.binds) == n_binds


def test_ldap_template_escapes_dn_metacharacters(ldap_server):
    p = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        user_dn_template="uid={user},ou=people,dc=example,dc=com")
    assert not p.authenticate("alice,ou=people", "x")
    sent_dn = ldap_server.binds[-1][0]
    assert "\\," in sent_dn   # the comma travelled escaped


def test_ldap_search_then_bind_anonymous(ldap_server):
    p = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        search_base="dc=example,dc=com")
    assert p.authenticate("alice", "wonderland")
    assert not p.authenticate("alice", "nope")
    assert not p.authenticate("eve", "x")     # no entry found


def test_ldap_search_then_bind_with_admin(ldap_server):
    p = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        search_base="dc=example,dc=com",
        bind_dn="cn=admin,dc=example,dc=com",
        bind_password="adminpw")
    assert p.authenticate("bob", "builder")
    wrong = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        search_base="dc=example,dc=com",
        bind_dn="cn=admin,dc=example,dc=com",
        bind_password="wrongpw")
    assert not wrong.authenticate("bob", "builder")


def test_ldap_server_down_is_refusal_not_crash():
    p = LdapAuthProvider("ldap://127.0.0.1:1",   # nothing listens there
                         user_dn_template="uid={user},dc=x")
    assert not p.authenticate("alice", "pw")


# ---------------------------------------------------------------------------
# End-to-end on the network surfaces
# ---------------------------------------------------------------------------


def _serve_flight(session, provider):
    from snappydata_tpu.cluster.flight_server import SnappyFlightServer

    server = SnappyFlightServer(session, "127.0.0.1", 0,
                                auth_provider=provider)
    threading.Thread(target=server.serve, daemon=True).start()
    server.wait_ready(timeout=10)
    return server


def test_flight_login_with_builtin_provider():
    from snappydata_tpu.cluster import SnappyClient

    s = SnappySession()
    s.sql("CREATE TABLE auth_bt (a INT) USING column")
    s.sql("INSERT INTO auth_bt VALUES (1), (2), (3)")
    provider = BuiltinAuthProvider({"admin": "adminpw", "carol": "carolpw"})
    server = _serve_flight(s, provider)
    try:
        with pytest.raises(Exception, match="(?i)token|credential"):
            SnappyClient(address=f"127.0.0.1:{server.port}").sql(
                "SELECT * FROM auth_bt")
        with pytest.raises(Exception, match="(?i)invalid credentials"):
            SnappyClient(address=f"127.0.0.1:{server.port}",
                         user="carol", password="wrong").sql(
                "SELECT * FROM auth_bt")
        carol = SnappyClient(address=f"127.0.0.1:{server.port}",
                             user="carol", password="carolpw")
        with pytest.raises(Exception, match="(?i)lacks"):
            carol.sql("SELECT * FROM auth_bt")  # authed but not granted
        s.sql("GRANT SELECT ON auth_bt TO carol")
        assert carol.sql(
            "SELECT count(*) FROM auth_bt").column(0).to_pylist() == [3]
        with pytest.raises(Exception, match="EXEC PYTHON|may not run"):
            carol.execute("EXEC PYTHON 'result = [1]'")
        carol.close()
        admin = SnappyClient(address=f"127.0.0.1:{server.port}",
                             user="admin", password="adminpw")
        assert admin.execute("EXEC PYTHON 'result = [9]'")["rows"] == [[9]]
        admin.close()
    finally:
        server.shutdown()


def test_flight_login_with_ldap_provider(ldap_server):
    from snappydata_tpu.cluster import SnappyClient

    s = SnappySession()
    s.sql("CREATE TABLE auth_lt (a INT) USING column")
    s.sql("INSERT INTO auth_lt VALUES (7)")
    s.sql("GRANT SELECT ON auth_lt TO alice")
    provider = LdapAuthProvider(
        f"ldap://127.0.0.1:{ldap_server.port}",
        user_dn_template="uid={user},ou=people,dc=example,dc=com")
    server = _serve_flight(s, provider)
    try:
        alice = SnappyClient(address=f"127.0.0.1:{server.port}",
                             user="alice", password="wonderland")
        assert alice.sql("SELECT a FROM auth_lt").column(0).to_pylist() == [7]
        alice.close()
        with pytest.raises(Exception, match="(?i)invalid credentials"):
            SnappyClient(address=f"127.0.0.1:{server.port}",
                         user="alice", password="red-queen").sql(
                "SELECT a FROM auth_lt")
    finally:
        server.shutdown()


def test_expired_login_token_triggers_transparent_relogin():
    import time

    from snappydata_tpu.cluster import SnappyClient

    s = SnappySession()
    s.sql("CREATE TABLE auth_exp (a INT) USING column")
    s.sql("INSERT INTO auth_exp VALUES (1)")
    server = _serve_flight(s, BuiltinAuthProvider({"admin": "pw"}))
    server.TOKEN_TTL_S = 0.2   # instance override for the test
    try:
        c = SnappyClient(address=f"127.0.0.1:{server.port}",
                         user="admin", password="pw")
        assert c.sql("SELECT a FROM auth_exp").column(0).to_pylist() == [1]
        time.sleep(0.3)        # token expires server-side
        # the client re-logs-in transparently and the query still works
        assert c.sql("SELECT a FROM auth_exp").column(0).to_pylist() == [1]
        c.close()
    finally:
        server.shutdown()


def test_internal_cluster_token_accepted_as_node_principal():
    from snappydata_tpu.cluster import SnappyClient

    s = SnappySession()   # node session is admin
    s.sql("CREATE TABLE auth_int (a INT) USING column")
    s.sql("INSERT INTO auth_int VALUES (4)")
    from snappydata_tpu.cluster.flight_server import SnappyFlightServer

    server = SnappyFlightServer(s, "127.0.0.1", 0,
                                auth_provider=BuiltinAuthProvider({}),
                                internal_token="cluster-secret")
    threading.Thread(target=server.serve, daemon=True).start()
    server.wait_ready(timeout=10)
    try:
        peer = SnappyClient(address=f"127.0.0.1:{server.port}",
                            token="cluster-secret")
        assert peer.sql(
            "SELECT a FROM auth_int").column(0).to_pylist() == [4]
        peer.close()
        with pytest.raises(Exception, match="(?i)token|credential"):
            SnappyClient(address=f"127.0.0.1:{server.port}",
                         token="wrong").sql("SELECT a FROM auth_int")
    finally:
        server.shutdown()


def test_rest_malformed_basic_header_is_401():
    import urllib.error
    import urllib.request

    from snappydata_tpu.cluster.rest import RestService

    s = SnappySession()
    svc = RestService(s, None, host="127.0.0.1", port=0,
                      auth_provider=BuiltinAuthProvider({"x": "y"})).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{svc.port}/jobs", data=b"{}",
            headers={"Content-Type": "application/json",
                     "Authorization": "Basic %%%not-base64%%%"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 401
    finally:
        svc.stop()


def test_rest_basic_auth_with_provider():
    import base64
    import json
    import urllib.error
    import urllib.request

    from snappydata_tpu.cluster.rest import RestService

    s = SnappySession()
    s.sql("CREATE TABLE r (a INT) USING column")
    s.sql("INSERT INTO r VALUES (5)")
    s.sql("GRANT SELECT ON r TO dave")
    provider = BuiltinAuthProvider({"dave": "davepw"})
    svc = RestService(s, None, host="127.0.0.1", port=0,
                      auth_provider=provider).start()
    try:
        url = f"http://127.0.0.1:{svc.port}/jobs"
        payload = json.dumps({"sql": "SELECT a FROM r"}).encode()

        def submit(headers):
            req = urllib.request.Request(url, data=payload, headers={
                "Content-Type": "application/json", **headers})
            return json.loads(urllib.request.urlopen(req).read())

        with pytest.raises(urllib.error.HTTPError) as exc:
            submit({})
        assert exc.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as exc:
            bad = base64.b64encode(b"dave:wrongpw").decode()
            submit({"Authorization": f"Basic {bad}"})
        assert exc.value.code == 401
        cred = base64.b64encode(b"dave:davepw").decode()
        job = submit({"Authorization": f"Basic {cred}"})
        status = None
        import time
        for _ in range(100):
            status = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{svc.port}/jobs/{job['jobId']}",
                    headers={"Authorization": f"Basic {cred}"})).read())
            if status.get("status") in ("FINISHED", "ERROR"):
                break
            time.sleep(0.05)
        assert status["status"] == "FINISHED", status
        assert status["rows"] == [[5]]
    finally:
        svc.stop()
