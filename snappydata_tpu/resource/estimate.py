"""Pre-admission cost estimation: rows × decoded width from catalog stats.

The broker needs a memory estimate BEFORE a query runs (ref: the
reference sizes column batches from catalog stats before admitting work
against critical-heap-percentage; the decode-throughput law in
arXiv:2606.22423 likewise prices a scan by bytes decoded, not bytes
stored). The estimate is deliberately simple and conservative: for every
referenced table, row count times decoded row width (device dtype bytes
per numeric column, 4-byte dictionary codes per string, one validity
byte per column) summed over all referenced tables — i.e. the bytes a
full decoded bind of each scan would occupy.
"""

from __future__ import annotations

from typing import List, Set

import numpy as np

from snappydata_tpu import types as T
from snappydata_tpu.sql import ast


def _decoded_row_width(schema: T.Schema) -> int:
    width = 0
    for f in schema.fields:
        if isinstance(f.dtype, (T.ArrayType, T.MapType, T.StructType)):
            width += 64          # nested plates: coarse per-row charge
        elif f.dtype.name == "string":
            width += 4           # dictionary code (int32)
        else:
            try:
                width += np.dtype(f.dtype.device_dtype()).itemsize
            except Exception:
                width += 8
        width += 1               # validity byte
    return width


def _referenced_tables(plan: ast.Plan, out: Set[str]) -> None:
    if isinstance(plan, (ast.Relation, ast.UnresolvedRelation)):
        out.add(plan.name.lower())
    for e in ast.plan_exprs(plan):
        for x in ast.walk(e):
            if isinstance(x, (ast.ScalarSubquery, ast.InSubquery,
                              ast.ExistsSubquery)):
                _referenced_tables(x.plan, out)
    for k in plan.children():
        _referenced_tables(k, out)


def _table_rows(info) -> int:
    data = info.data
    m = getattr(data, "snapshot", None)
    if m is not None:
        snap = m()
        if hasattr(snap, "total_rows"):  # ColumnTableData manifest —
            return int(snap.total_rows())  # O(batches), no mask allocs
    live = getattr(data, "_live", None)  # RowTableData liveness list
    if live is not None:
        return int(live.count(True))
    return 0


def estimate_query_bytes(catalog, plan: ast.Plan) -> int:
    """Bytes a decoded full bind of every referenced table would take.
    Unknown tables (views resolve later, CTEs) contribute 0 — admission
    is a guard rail, not an oracle."""
    names: Set[str] = set()
    try:
        _referenced_tables(plan, names)
    except Exception:
        return 0
    total = 0
    for nm in names:
        info = catalog.lookup_table(nm)
        if info is None:
            continue
        try:
            total += _table_rows(info) * _decoded_row_width(info.schema)
        except Exception:
            continue
    return int(total)


def estimate_statement_bytes(catalog, stmt) -> int:
    plan = getattr(stmt, "plan", None)
    if plan is None:
        return 0
    return estimate_query_bytes(catalog, plan)


__all__: List[str] = ["estimate_query_bytes", "estimate_statement_bytes"]
