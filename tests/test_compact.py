"""Background compaction (PR 19): fold MVCC mutation debris (update
deltas, delete masks, mixed encodings, row-buffer tails) back into clean
encoded batches so the compressed-domain fast paths stay hot — and prove
the crash contract at the `storage.compaction` failpoint: a raise/kill
at the publish seam leaves the OLD manifest live and every value exact,
while pinned readers hold their pre-rewrite snapshot throughout."""

import dataclasses

import numpy as np
import pytest

from snappydata_tpu import SnappySession, config
from snappydata_tpu.catalog import Catalog
from snappydata_tpu.observability.metrics import global_registry
from snappydata_tpu.reliability import failpoints as rfail
from snappydata_tpu.storage import compact, mvcc
from snappydata_tpu.storage.device_decode import table_fallbacks

pytestmark = pytest.mark.faults


def _props():
    return config.global_properties()


@pytest.fixture(autouse=True)
def _clean():
    rfail.clear()
    saved = (_props().get("agg_on_codes"),
             _props().get("compaction_enabled"))
    yield
    rfail.clear()
    _props().set("agg_on_codes", saved[0])
    _props().set("compaction_enabled", saved[1])


def _counters():
    return dict(global_registry().snapshot()["counters"])


def _session(n=6000, seed=11):
    """Low-cardinality columns so every batch encodes compressibly; k is
    the self-verifying key (v == k * 0.5 always)."""
    s = SnappySession(catalog=Catalog())
    s.sql("CREATE TABLE ct (k BIGINT, q DOUBLE, v DOUBLE) USING column")
    rng = np.random.default_rng(seed)
    k = np.arange(n, dtype=np.int64)
    q = rng.choice(np.array([0.5, 1.25, 2.0, 3.75]), n)
    s.insert_arrays("ct", [k, q, k * 0.5])
    data = s.catalog.describe("ct").data
    data.force_rollover()
    return s, data


def _debris(s, data):
    """Manufacture every foldable residue: update deltas, a delete mask,
    and an undersized stub batch."""
    s.sql("UPDATE ct SET q = 2.0 WHERE k < 40")
    s.sql("DELETE FROM ct WHERE k >= 5900")
    s.sql("INSERT INTO ct VALUES (100000, 1.25, 50000.0)")
    data.force_rollover()
    man = data.snapshot()
    assert any(v.deltas or v.delete_mask is not None for v in man.views)
    return man


def _expected(s):
    return s.sql("SELECT count(*), sum(q), sum(v), sum(k) FROM ct").rows()


def _host_sums(man):
    """count/sum(q)/sum(v) recomputed host-side from a manifest's views
    + row buffer — how a pinned reader sees the table."""
    cnt, sq, sv = 0, 0.0, 0.0
    for view in man.views:
        live = view.live_mask()
        cnt += int(live.sum())
        sq += float(view.decoded_column(1)[live].sum())
        sv += float(view.decoded_column(2)[live].sum())
    if man.row_count:
        cnt += man.row_count
        sq += float(np.asarray(man.row_arrays[1]).sum())
        sv += float(np.asarray(man.row_arrays[2]).sum())
    return cnt, sq, sv


def test_pass_folds_debris_and_preserves_values():
    s, data = _session()
    _debris(s, data)
    before = _expected(s)
    c0 = _counters()
    out = compact.run_compaction_pass(data, force=True)
    assert out["rewritten"] > 0 and out["produced"] > 0
    man = data.snapshot()
    assert all(not v.deltas and v.delete_mask is None for v in man.views)
    # the stub merged away: every batch but the last is at capacity
    assert all(v.batch.num_rows == data.capacity for v in man.views[:-1])
    after = _expected(s)
    assert after == before
    c1 = _counters()
    assert c1.get("compaction_passes", 0) > c0.get("compaction_passes", 0)
    assert c1.get("compaction_batches_rewritten", 0) >= \
        c0.get("compaction_batches_rewritten", 0) + out["rewritten"]
    assert c1.get("compaction_bytes_reclaimed", 0) >= \
        c0.get("compaction_bytes_reclaimed", 0)
    # a second immediate pass declines itemized, never silently
    out2 = compact.run_compaction_pass(data, force=True)
    assert out2["rewritten"] == 0 and out2["skipped"]
    s.stop()


@pytest.mark.parametrize("action,param", [
    ("raise", 0), ("kill_worker", 0), ("return_errno", 0)],
    ids=["raise", "kill", "errno"])
def test_crash_at_publish_leaves_old_manifest_live(action, param):
    """The crash matrix cell for the compaction seam: the failpoint sits
    inside the table lock immediately before `_publish` — dying there
    must leave the old manifest (same version, same view objects, debris
    intact) serving exact values, and a retry must heal cleanly."""
    s, data = _session()
    _debris(s, data)
    before = _expected(s)
    man0 = data.snapshot()
    ids0 = [id(v) for v in man0.views]
    rfail.arm("storage.compaction", action, param=param, count=1)
    with pytest.raises(Exception) as ei:
        compact.run_compaction_pass(data, force=True)
    assert isinstance(ei.value, (OSError, rfail.WorkerKilled))
    assert rfail.fired_counts().get("storage.compaction") == 1
    man1 = data.snapshot()
    assert man1.version == man0.version, "a dead pass must not publish"
    assert [id(v) for v in man1.views] == ids0
    assert any(v.deltas or v.delete_mask is not None for v in man1.views)
    assert _expected(s) == before
    # disarmed retry folds everything the dead pass left behind
    rfail.clear()
    out = compact.run_compaction_pass(data, force=True)
    assert out["rewritten"] > 0
    assert all(not v.deltas and v.delete_mask is None
               for v in data.snapshot().views)
    assert _expected(s) == before
    s.stop()


def test_raced_pass_aborts_instead_of_resurrecting_rows():
    """If a concurrent update replaces a selected view (dataclasses.
    replace => new object identity) between selection and publish, the
    pass must abort COUNTED — publishing would resurrect pre-mutation
    rows.  The race is simulated deterministically at the failpoint
    seam, which runs under the table lock exactly where a real pass sits
    right before `_publish`."""
    s, data = _session()
    _debris(s, data)
    before = _expected(s)
    man0 = data.snapshot()

    def swap(name):
        if name != "storage.compaction":
            return
        cur = data._manifest
        views = (dataclasses.replace(cur.views[0]),) + cur.views[1:]
        data._manifest = dataclasses.replace(cur, views=views)

    orig = rfail.hit
    rfail.hit = swap
    try:
        c0 = _counters()
        out = compact.run_compaction_pass(data, force=True)
    finally:
        rfail.hit = orig
    assert out["rewritten"] == 0
    assert out["skipped"].get("raced", 0) > 0
    c1 = _counters()
    assert c1.get("compaction_skip_raced", 0) > \
        c0.get("compaction_skip_raced", 0)
    assert data.snapshot().version == man0.version
    assert _expected(s) == before
    s.stop()


def test_chaos_drain_fallbacks_reach_zero_with_pinned_reader():
    """Sustained mutations accumulate counted compressed-domain
    fallbacks; at most TWO compaction passes drain the table's foldable
    tally to zero, a re-run of the same queries counts NO new foldable
    fallbacks, and a reader pinned across the rewrite keeps its
    pre-compaction snapshot value-exact the whole way."""
    s, data = _session(n=8000)
    _props().set("agg_on_codes", "on")
    queries = ["SELECT count(*), sum(q), sum(v), sum(k) FROM ct",
               "SELECT q, count(*), sum(v) FROM ct GROUP BY q ORDER BY q"]
    rng = np.random.default_rng(3)
    for round_ in range(4):
        lo = int(rng.integers(0, 7000))
        s.sql(f"UPDATE ct SET q = 3.75 WHERE k >= {lo} AND k < {lo + 30}")
        s.sql(f"DELETE FROM ct WHERE k = {7200 + round_}")
        s.insert_arrays("ct", [
            np.arange(20, dtype=np.int64) + 50_000 + round_ * 100,
            np.full(20, 0.5),
            (np.arange(20) + 50_000 + round_ * 100) * 0.5])
        for qy in queries:
            s.sql(qy).rows()
    assert compact.foldable_fallbacks(data) > 0, \
        "sustained mutations must accumulate foldable fallbacks"
    before = [s.sql(qy).rows() for qy in queries]

    pin = mvcc.SnapshotPin()
    pin.pin_many([data])
    pinned_ver = pin.manifest_for(data).version
    pinned_sums = _host_sums(pin.manifest_for(data))

    passes = 0
    while compact.foldable_fallbacks(data) > 0 and passes < 2:
        compact.run_compaction_pass(data, force=True)
        passes += 1
    assert compact.foldable_fallbacks(data) == 0, \
        f"foldable fallbacks not drained after {passes} passes: " \
        f"{table_fallbacks(data)}"
    assert passes <= 2

    # the SAME queries now run without counting a single new foldable
    # fallback for this table, and with identical values
    after = [s.sql(qy).rows() for qy in queries]
    for a, b in zip(after, before):
        assert a == b
    fb = {r: n for r, n in table_fallbacks(data).items()
          if r in compact.FOLDABLE_REASONS}
    assert not fb, f"re-run still falls back: {fb}"

    # the pinned reader's world never moved
    assert pin.manifest_for(data).version == pinned_ver
    assert pin.manifest_for(data).version < data.snapshot().version
    assert _host_sums(pin.manifest_for(data)) == \
        pytest.approx(pinned_sums)
    pin.release()
    s.stop()


def test_broker_sweep_and_kick_gating():
    """The admission-path kick: disabled knob => no kick; the sweep body
    compacts exactly the tables whose foldable tally crossed
    `compaction_min_fallbacks`, through the broker's registry."""
    from snappydata_tpu.resource.broker import global_broker

    s, data = _session()
    broker = global_broker()
    assert any(d is data for _nm, d in broker._iter_tables()), \
        "column table must be registered with the broker"
    _props().set("compaction_enabled", False)
    assert compact.maybe_kick(broker) is False
    _props().set("compaction_enabled", True)

    _debris(s, data)
    before = _expected(s)
    s.sql("SELECT count(*), sum(q) FROM ct").rows()   # count the fallback
    assert compact.foldable_fallbacks(data) >= 1
    compact._sweep_body(broker)   # the thread body, run synchronously
    assert all(not v.deltas and v.delete_mask is None
               for v in data.snapshot().views)
    assert compact.foldable_fallbacks(data) == 0
    assert _expected(s) == before
    s.stop()


def test_stats_surface_reports_lanes_and_compaction():
    """Dashboard / REST surface: the scan snapshot carries the aggregate
    lane counters and compaction progress; encoding_mix itemizes each
    table's OWN fallback tally (the compaction trigger)."""
    from snappydata_tpu.observability.stats_service import (encoding_mix,
                                                            scan_snapshot)

    s, data = _session()
    _props().set("agg_on_codes", "on")
    s.sql("SELECT q, count(*), sum(v) FROM ct GROUP BY q ORDER BY q")
    _debris(s, data)
    s.sql("SELECT count(*), sum(q) FROM ct").rows()
    snap = scan_snapshot(s.catalog)
    assert snap["agg_code_domain"] > 0
    assert snap["agg_dict_space"] > 0
    assert "agg_rle_runs" in snap and snap["agg_on_codes"] == "on"
    assert snap["compaction_enabled"] in (True, False)
    fb = encoding_mix(s.catalog)["ct"]["compressed_fallbacks"]
    assert fb.get("deltas", 0) > 0, fb
    compact.run_compaction_pass(data, force=True)
    snap = scan_snapshot(s.catalog)
    assert snap["compaction_passes"] > 0
    assert snap["compaction_batches_rewritten"] > 0
    assert encoding_mix(s.catalog)["ct"]["compressed_fallbacks"] == {}
    s.stop()


def test_faultstorm_menu_covers_compaction():
    """Satellite (a): the storm menu injects at the compaction seam, and
    the storm op both manufactures debris and forces the pass."""
    from snappydata_tpu.reliability import faultstorm

    points = {m[0] for m in faultstorm._MENU}
    assert "storage.compaction" in points
    assert {m[3] for m in faultstorm._MENU
            if m[0] == "storage.compaction"} == {"op_compact"}
    assert hasattr(faultstorm._Storm, "op_compact")
